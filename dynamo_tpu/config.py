"""Layered configuration: defaults -> TOML file -> environment.

The reference builds RuntimeConfig/WorkerConfig with figment
(`lib/runtime/src/config.rs:26-143`): dataclass defaults, overlaid by a
TOML file, overlaid by ``DYN_<SECTION>_<FIELD>`` environment variables —
highest layer wins. This is the same cascade for this framework's settings;
the launch CLI seeds its argparse defaults from it, so precedence ends up
CLI > env > TOML > defaults.

Env naming: section ``runtime`` field ``http_port`` -> ``DYN_RUNTIME_HTTP_PORT``.
The TOML file is taken from ``DYN_CONFIG`` (path) unless given explicitly.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, TypeVar

try:  # python >= 3.11
    import tomllib
except ModuleNotFoundError:  # 3.10: the vendored API-compatible backport
    import tomli as tomllib

logger = logging.getLogger(__name__)

T = TypeVar("T")


_TRUTHY = ("1", "true", "yes", "on")


def env_flag(env: dict[str, str], key: str, default: bool = False) -> bool:
    """Parse a boolean env toggle (the one definition of 'truthy')."""
    raw = env.get(key)
    return default if raw is None else raw.strip().lower() in _TRUTHY


def _coerce(value: str, target_type: Any) -> Any:
    """Parse an env string into the field's annotated type."""
    if target_type is bool or target_type == "bool":
        return value.strip().lower() in _TRUTHY
    if target_type is int or target_type == "int":
        return int(value)
    if target_type is float or target_type == "float":
        return float(value)
    return value


def _field_types(cls) -> dict[str, Any]:
    out = {}
    for f in dataclasses.fields(cls):
        t = f.type
        if isinstance(t, str):  # from __future__ annotations
            t = {"int": int, "float": float, "bool": bool, "str": str}.get(
                t.replace(" | None", ""), str
            )
        out[f.name] = t
    return out


def load_config(
    defaults: T,
    *,
    section: str,
    toml_path: str | os.PathLike | None = None,
    env: dict[str, str] | None = None,
    env_prefix: str = "DYN",
) -> T:
    """Overlay ``defaults`` (a dataclass instance) with the ``[section]``
    table of a TOML file and then with ``{env_prefix}_{SECTION}_{FIELD}``
    environment variables. Unknown TOML keys warn and are ignored."""
    env = os.environ if env is None else env
    cls = type(defaults)
    values = dataclasses.asdict(defaults)
    types = _field_types(cls)

    path = toml_path or env.get(f"{env_prefix}_CONFIG")
    if path:
        with open(path, "rb") as f:
            doc = tomllib.load(f)
        table = doc.get(section, {})
        for k, v in table.items():
            if k in values:
                values[k] = v
            else:
                logger.warning("config file %s: unknown key [%s] %s", path, section, k)

    for name, t in types.items():
        env_key = f"{env_prefix}_{section.upper()}_{name.upper()}"
        if env_key in env:
            try:
                values[name] = _coerce(env[env_key], t)
            except (ValueError, TypeError) as exc:
                raise ValueError(f"bad value for {env_key}: {env[env_key]!r}") from exc

    return cls(**values)


@dataclasses.dataclass
class RuntimeSettings:
    """Deployment-level settings (the reference's RuntimeConfig role)."""

    host: str = "127.0.0.1"
    http_port: int = 8080
    store: str = ""  # tcp://host:port; empty = in-process
    log_level: str = "INFO"
    log_jsonl: bool = False  # DYN_RUNTIME_LOG_JSONL=1 -> JSON-lines logs


@dataclasses.dataclass
class WorkerSettings:
    """Per-worker engine settings (the reference's WorkerConfig role)."""

    model: str = "test-tiny"
    num_pages: int = 512
    max_batch_size: int = 64
    router_mode: str = "round_robin"
    mesh: str = ""  # '' | 'auto' | 'dp=2,tp=4,...'
    decode_steps: int = 1
    # Per-step prefill chunk budget while decodes are running (stall-free
    # mixed steps); 0 restores phase-exclusive prefill-XOR-decode steps.
    chunk_prefill_tokens: int = 512
    # Speculative decoding draft length (n-gram self-drafting, lossless);
    # 0 disables. See docs/SCHEDULER.md "Speculative steps".
    spec_k: int = 0
    # Overlapped execution: depth-1 mixed-step pipeline with device-resident
    # token feedback (bare DYN_OVERLAP also arms it). Output streams stay
    # bit-identical to off. See docs/SCHEDULER.md "Overlapped execution".
    overlap: bool = False
    # Chain speculative verify steps through the pipeline (accepted tokens
    # stay device-resident). Off barriers every spec step to the sync
    # verify path. Bare DYN_OVERLAP_SPEC=0 also clears it.
    overlap_spec: bool = True
    # KV-cache storage dtype: 'bf16' (default) or 'fp8' (float8_e4m3fn,
    # halves KV HBM; attention upcasts to the query dtype at the matmul).
    kv_cache_dtype: str = "bf16"


@dataclasses.dataclass
class SloSettings:
    """Latency targets the deployment is accountable to.

    The north-star metric is goodput *under* these targets (tokens/sec from
    requests that attained them), not raw throughput. Consumed by the
    frontend's SLO accountant (``observability/slo.py``) and, via the
    planner's percentile knob, by scaling decisions.
    """

    ttft_ms: float = 500.0  # p50 time-to-first-token target (north star)
    itl_p99_ms: float = 50.0  # per-request p99 inter-token-latency target


@dataclasses.dataclass
class SloSchedSettings:
    """Admission-control plane knobs (``dynamo_tpu/sched``).

    The master toggle is the bare ``DYN_SLO_SCHED`` flag (not part of this
    section); these tune the plane once it is on. Env: ``DYN_SLO_SCHED_*``,
    TOML: ``[slo_sched]``.
    """

    ttft_budget_ms: float = 500.0  # tier-0 EDF deadline budget
    tier_stretch: float = 2.0  # deadline budget multiplier per priority tier
    # Path to a profiler-produced WorkerProfile JSON; empty = the predictor
    # runs on its online-corrected fallback and the router skips the
    # attainment term unless a profile is wired in code.
    profile: str = ""
    attainment_weight: float = 1.0  # router cost weight for predicted attainment
    # ITL-driven chunk-budget controller (shrinks chunk_prefill_tokens when
    # the live decode-step tail nears the ITL budget; see SloSettings).
    chunk_floor_tokens: int = 64
    chunk_shrink_at: float = 0.9
    chunk_relax_at: float = 0.5
    chunk_cooldown_steps: int = 8


@dataclasses.dataclass
class TenantSettings:
    """Default per-tenant admission quota (``dynamo_tpu/sched/tenants``).

    Zeros mean unlimited. Env: ``DYN_TENANT_*``, TOML: ``[tenant]``.
    """

    rate_tokens_per_s: float = 0.0  # token-bucket refill rate (prompt tokens)
    burst_tokens: float = 0.0  # bucket capacity; 0 -> 2s of rate
    max_inflight_tokens: int = 0  # cap on a tenant's live prompt tokens
    # JSON object of per-tenant overrides keyed by tenant id, e.g.
    # '{"heavy": {"rate_tokens_per_s": 1000, "max_inflight_tokens": 4096}}'.
    quotas: str = ""


@dataclasses.dataclass
class CacheAwareSettings:
    """Cache-aware serving knobs (residual-cost admission + router term).

    The master toggle is the bare ``DYN_CACHE_AWARE`` flag (not part of
    this section); these tune the plane once it is on. Env:
    ``DYN_CACHE_AWARE_*``, TOML: ``[cache_aware]``.
    """

    weight: float = 1.0  # router cost weight for predicted residual prefill
    # Prefill throughput assumed when converting residual tokens into
    # seconds of predicted TTFT contribution for the router cost.
    rate_tokens_per_s: float = 20000.0
    # Router skips the cache term for a worker whose KV-event feed is
    # staler than this — a stale index must not skew placement.
    max_staleness_s: float = 10.0


@dataclasses.dataclass
class FleetSettings:
    """Fleet-simulation harness knobs (``dynamo_tpu/fleetsim``).

    Env: ``DYN_FLEET_*``, TOML: ``[fleet]``. These tune how the harness
    runs a scenario; the scenario spec itself (trace, fleet shape, faults,
    checks) stays in code so runs are reviewable and deterministic.
    """

    spawn_timeout_s: float = 120.0  # per-worker READY deadline
    drain_timeout_s: float = 15.0  # SIGTERM -> SIGKILL escalation deadline
    workers: int = 0  # override the scenario's fleet size (0 = scenario value)
    report_dir: str = ""  # write scenario reports here ("" = stdout only)
    metrics_poll_s: float = 1.0  # federated /metrics scrape cadence


@dataclasses.dataclass
class StoreSettings:
    """HA control-plane knobs (``dynamo_tpu/runtime/replication``).

    Replication is armed by a non-empty ``replicas`` list (every store
    process gets the same list plus its own ``replica_index``); with the
    defaults the store is the single-process deployment and the whole plane
    is dormant. Env: ``DYN_STORE_*``, TOML: ``[store]``.
    """

    # Comma list of every replica's advertised url (tcp://host:port), in
    # priority order; index 0 is the bootstrap leader. "" = no replication.
    replicas: str = ""
    replica_index: int = 0  # this process's position in ``replicas``
    promote_after_s: float = 1.0  # leaderless window before a follower elects
    poll_s: float = 0.25  # peer who_leads poll cadence (election + watchdog)
    # Extra seconds of lease grace granted at promotion, on top of one full
    # TTL — covers clients still walking the replica list for the new leader.
    epoch_grace_s: float = 0.0
    # How long a multi-endpoint StoreClient keeps walking the replica list
    # for a leader before an op fails with ConnectionError.
    client_failover_s: float = 5.0


@dataclasses.dataclass
class RouterResyncSettings:
    """Router KV-event resync knobs (``dynamo_tpu/router/events``).

    A frontend (re)start — or a dropped worker stream — rebuilds the prefix
    index from the workers' sequence-numbered snapshot feeds; these tune the
    reconnect discipline. Env: ``DYN_ROUTER_RESYNC_*``, TOML:
    ``[router_resync]``.
    """

    backoff_s: float = 0.2  # first reconnect delay after a dropped event stream
    max_backoff_s: float = 5.0  # reconnect delay ceiling


@dataclasses.dataclass
class AnomalySettings:
    """Anomaly-sentinel knobs (``dynamo_tpu/observability/anomaly``).

    Rolling-window detectors over the engine step stream; conservative
    defaults (warm-up floors + absolute thresholds on top of the relative
    ratios) so a quiet fleet never false-positives. Env: ``DYN_ANOMALY_*``,
    TOML: ``[anomaly]``.
    """

    enable: bool = True
    window: int = 64  # rolling detector window (steps)
    min_samples: int = 256  # baseline steps required before relative detectors arm
    ratio: float = 3.0  # window-vs-baseline ratio that counts as a spike/drop
    barrier_frac: float = 0.5  # absolute window barrier fraction floor
    gap_floor_ms: float = 50.0  # absolute window mean step-gap floor
    recompile_storm: int = 8  # new-shape compiles within one window
    shortfall_pages: int = 32  # onboard shortfall pages within one window
    clear_after: int = 64  # quiet steps before an active anomaly clears


@dataclasses.dataclass
class IncidentSettings:
    """Incident-plane capture knobs (``dynamo_tpu/observability/incidents``).

    When an anomaly detector rises, a step crashes, or an SLO burn-rate
    alert fires, the worker snapshots a bounded black-box bundle (flight
    excerpt, intersecting spans, loss ledger, config) into a size-capped
    on-disk store so a dead worker still leaves a postmortem artifact.
    Env: ``DYN_INCIDENT_*``, TOML: ``[incident]``.
    """

    enable: bool = True
    dir: str = ""  # bundle root; '' -> <tmp>/dynamo-incidents
    max_bundles: int = 32  # store-wide bundle count cap (oldest evicted)
    max_bytes: int = 16_000_000  # store-wide on-disk byte cap
    flight_last: int = 256  # flight-ring records captured per bundle
    span_window_s: float = 30.0  # spans whose lifetime intersects [now - window, now]
    cooldown_s: float = 30.0  # min seconds between bundles for the same trigger kind


@dataclasses.dataclass
class AlertSettings:
    """SLO burn-rate alerting knobs (``dynamo_tpu/observability/slo``).

    Multi-window burn rates over goodput attainment: burn = miss fraction
    in the window divided by the SLO error budget (``1 - objective``).
    A window's alert fires when its burn rate clears the threshold and
    clears only after ``clear_after`` consecutive quiet requests
    (hysteresis, same discipline as the anomaly sentinel).
    Env: ``DYN_ALERT_*``, TOML: ``[alert]``.
    """

    objective: float = 0.9  # SLO objective: fraction of requests that must attain
    fast_window: int = 64  # fast rolling window (requests; the "5 m" analogue)
    slow_window: int = 512  # slow rolling window (requests; the "1 h" analogue)
    fast_burn: float = 4.0  # fast-window burn-rate threshold
    slow_burn: float = 2.0  # slow-window burn-rate threshold
    min_requests: int = 32  # requests seen in a window before its alert arms
    clear_after: int = 32  # quiet requests before an active alert clears


@dataclasses.dataclass
class AttribSettings:
    """Latency-attribution knobs (``dynamo_tpu/observability/attribution``).

    Env: ``DYN_ATTRIB_*``, TOML: ``[attrib]``.
    """

    # |unattributed| / e2e above this marks the explain budget incomplete.
    tolerance_frac: float = 0.1
    # Cap on flight STEP records each worker returns per explain query.
    max_steps: int = 2048


@dataclasses.dataclass
class TuneSettings:
    """Auto-tuner knobs (``dynamo_tpu/tuning``).

    Tune the closed-loop knob search itself — the space it sweeps and the
    probe discipline behind each trial — not the knobs it searches over
    (those live in their own sections/envs). Env: ``DYN_TUNE_*``, TOML:
    ``[tune]``.
    """

    preset: str = "test-tiny"  # model preset the probe engine is built from
    mode: str = "mock"  # probe backend: 'mock' (CPU proxy) | 'jax' (real model)
    seed: int = 0  # workload seed; the whole search is deterministic under it
    rounds: int = 3  # max coordinate-descent sweeps over the knob list
    requests: int = 16  # requests per full-length measured probe
    isl: int = 96  # probe prompt length (tokens)
    osl: int = 48  # probe decode length (tokens)
    rung_frac: float = 0.5  # successive-halving rung-0 probe scale (of requests)
    plateau_eps: float = 0.005  # relative gain below this counts as a plateau
    plateau_rounds: int = 1  # consecutive plateau rounds before early stop
    max_trials: int = 0  # hard cap on measured trials (0 = unlimited)
    out_dir: str = "bench/results/tune"  # journal + profile + report root
    knobs: str = ""  # comma list restricting swept knob names ("" = all)


def load_runtime_settings(**kw) -> RuntimeSettings:
    return load_config(RuntimeSettings(), section="runtime", **kw)


def load_worker_settings(**kw) -> WorkerSettings:
    return load_config(WorkerSettings(), section="worker", **kw)


def load_slo_settings(**kw) -> SloSettings:
    return load_config(SloSettings(), section="slo", **kw)


def load_slo_sched_settings(**kw) -> SloSchedSettings:
    return load_config(SloSchedSettings(), section="slo_sched", **kw)


def load_tenant_settings(**kw) -> TenantSettings:
    return load_config(TenantSettings(), section="tenant", **kw)


def load_cache_aware_settings(**kw) -> CacheAwareSettings:
    return load_config(CacheAwareSettings(), section="cache_aware", **kw)


def load_fleet_settings(**kw) -> FleetSettings:
    return load_config(FleetSettings(), section="fleet", **kw)


def load_store_settings(**kw) -> StoreSettings:
    return load_config(StoreSettings(), section="store", **kw)


def load_router_resync_settings(**kw) -> RouterResyncSettings:
    return load_config(RouterResyncSettings(), section="router_resync", **kw)


def load_anomaly_settings(**kw) -> AnomalySettings:
    return load_config(AnomalySettings(), section="anomaly", **kw)


def load_incident_settings(**kw) -> IncidentSettings:
    return load_config(IncidentSettings(), section="incident", **kw)


def load_alert_settings(**kw) -> AlertSettings:
    return load_config(AlertSettings(), section="alert", **kw)


def load_attrib_settings(**kw) -> AttribSettings:
    return load_config(AttribSettings(), section="attrib", **kw)


def load_tune_settings(**kw) -> TuneSettings:
    return load_config(TuneSettings(), section="tune", **kw)
