"""Block payload storage backends for the capacity tiers.

A payload is the pair of numpy arrays (k, v) for one page across all layers:
shape [num_layers, page_size, num_kv_heads, head_dim] each. Backends only
store/retrieve bytes-like payloads; capacity policy lives in TierPool.

Parity: reference `block_manager/storage.rs:104-433` (System/Pinned/Disk
backends) and the `NullDeviceStorage` CI fake (`tests/block_manager.rs`).
"""

from __future__ import annotations

import abc
import pathlib
import shutil

import numpy as np

Payload = tuple[np.ndarray, np.ndarray]  # (k, v) for one page


class BlockStorage(abc.ABC):
    @abc.abstractmethod
    def write(self, block_hash: int, payload: Payload) -> None: ...

    @abc.abstractmethod
    def read(self, block_hash: int) -> Payload | None: ...

    @abc.abstractmethod
    def delete(self, block_hash: int) -> None: ...

    def close(self) -> None:
        pass


class HostStorage(BlockStorage):
    """Host-RAM storage (the G2 medium)."""

    def __init__(self) -> None:
        self._data: dict[int, Payload] = {}

    def write(self, block_hash: int, payload: Payload) -> None:
        k, v = payload
        self._data[block_hash] = (np.ascontiguousarray(k), np.ascontiguousarray(v))

    def read(self, block_hash: int) -> Payload | None:
        return self._data.get(block_hash)

    def delete(self, block_hash: int) -> None:
        self._data.pop(block_hash, None)

    def __len__(self) -> int:
        return len(self._data)


class DiskStorage(BlockStorage):
    """Disk storage, one .npz file per block (the G3 medium)."""

    def __init__(self, root: str | pathlib.Path) -> None:
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, block_hash: int) -> pathlib.Path:
        return self.root / f"{block_hash:016x}.npz"

    def write(self, block_hash: int, payload: Payload) -> None:
        k, v = payload
        tmp = self._path(block_hash).with_suffix(".tmp")
        with tmp.open("wb") as fh:
            np.savez(fh, k=k, v=v)
        tmp.rename(self._path(block_hash))  # atomic publish

    def read(self, block_hash: int) -> Payload | None:
        p = self._path(block_hash)
        if not p.exists():
            return None
        with np.load(p) as z:
            return z["k"], z["v"]

    def delete(self, block_hash: int) -> None:
        self._path(block_hash).unlink(missing_ok=True)

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)


class NullStorage(BlockStorage):
    """Metadata-only backend: remembers which hashes exist, stores no data.

    Lets capacity/eviction/ordering logic run in CI without payload memory —
    ``read`` returns None, so onboarding treats blocks as instantly lost.
    """

    def __init__(self) -> None:
        self.hashes: set[int] = set()

    def write(self, block_hash: int, payload: Payload) -> None:
        self.hashes.add(block_hash)

    def read(self, block_hash: int) -> Payload | None:
        return None

    def delete(self, block_hash: int) -> None:
        self.hashes.discard(block_hash)
