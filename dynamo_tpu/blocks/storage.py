"""Block payload storage backends for the capacity tiers.

A payload is the pair of numpy arrays (k, v) for one page across all layers:
shape [num_layers, page_size, num_kv_heads, head_dim] each. Backends only
store/retrieve bytes-like payloads; capacity policy lives in TierPool.

Parity: reference `block_manager/storage.rs:104-433` (System/Pinned/Disk
backends) and the `NullDeviceStorage` CI fake (`tests/block_manager.rs`).
"""

from __future__ import annotations

import abc
import pathlib
import shutil
from typing import Any

import numpy as np

Payload = tuple[np.ndarray, np.ndarray]  # (k, v) for one page


class BlockStorage(abc.ABC):
    @abc.abstractmethod
    def write(self, block_hash: int, payload: Payload) -> None: ...

    @abc.abstractmethod
    def read(self, block_hash: int) -> Payload | None: ...

    @abc.abstractmethod
    def delete(self, block_hash: int) -> None: ...

    def exists(self, block_hash: int) -> bool:
        """Cheap membership probe; backends override when read() is costly."""
        return self.read(block_hash) is not None

    def close(self) -> None:
        pass


class HostStorage(BlockStorage):
    """Host-RAM storage (the G2 medium)."""

    def __init__(self) -> None:
        self._data: dict[int, Payload] = {}

    def write(self, block_hash: int, payload: Payload) -> None:
        k, v = payload
        self._data[block_hash] = (np.ascontiguousarray(k), np.ascontiguousarray(v))

    def read(self, block_hash: int) -> Payload | None:
        return self._data.get(block_hash)

    def delete(self, block_hash: int) -> None:
        self._data.pop(block_hash, None)

    def __len__(self) -> int:
        return len(self._data)


class DiskStorage(BlockStorage):
    """Disk storage, one .npz file per block (the G3 medium)."""

    def __init__(self, root: str | pathlib.Path) -> None:
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, block_hash: int) -> pathlib.Path:
        return self.root / f"{block_hash:016x}.npz"

    def write(self, block_hash: int, payload: Payload) -> None:
        k, v = payload
        tmp = self._path(block_hash).with_suffix(".tmp")
        with tmp.open("wb") as fh:
            np.savez(fh, k=k, v=v)
        tmp.rename(self._path(block_hash))  # atomic publish

    def read(self, block_hash: int) -> Payload | None:
        p = self._path(block_hash)
        if not p.exists():
            return None
        with np.load(p) as z:
            return z["k"], z["v"]

    def delete(self, block_hash: int) -> None:
        self._path(block_hash).unlink(missing_ok=True)

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)


class RemoteStorage(BlockStorage):
    """Deployment-wide block storage over the object store (the G4 medium).

    KV pages serialized as npz blobs into ``ObjectStore`` — i.e. the same
    store plane every node already joins, so a block offloaded by one worker
    is onboardable by any other (the cross-instance reuse role of the
    reference's remote/object G4 tier, `block_manager/` storage hierarchy).

    The block manager runs on the engine thread; the object store is
    asyncio. Calls are bridged with ``run_coroutine_threadsafe`` onto the
    store's loop — same blocking profile as DiskStorage (G3), and like G3 it
    sits behind the capacity tiers, never on the decode hot path.
    """

    def __init__(self, objects: "Any", loop: "Any", *, prefix: str = "kv", timeout: float = 30.0) -> None:
        self.objects = objects
        self.loop = loop
        self.prefix = prefix
        self.timeout = timeout

    def _name(self, block_hash: int) -> str:
        return f"{self.prefix}/{block_hash:016x}"

    def _run(self, coro):
        import asyncio
        import concurrent.futures

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            raise RuntimeError("RemoteStorage used from the store's own event loop (would deadlock)")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout=self.timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise

    def write(self, block_hash: int, payload: Payload) -> None:
        import io

        k, v = payload
        buf = io.BytesIO()
        np.savez(buf, k=np.asarray(k), v=np.asarray(v))
        self._run(self.objects.put(self._name(block_hash), buf.getvalue()))

    def read(self, block_hash: int) -> Payload | None:
        import io

        from dynamo_tpu.runtime.objects import ObjectError

        try:
            data = self._run(self.objects.get(self._name(block_hash)))
        except ObjectError:
            return None
        with np.load(io.BytesIO(data)) as z:
            return z["k"], z["v"]

    def delete(self, block_hash: int) -> None:
        from dynamo_tpu.runtime.objects import ObjectError

        try:
            self._run(self.objects.delete(self._name(block_hash)))
        except ObjectError:
            pass

    def exists(self, block_hash: int) -> bool:
        return self._run(self.objects.stat(self._name(block_hash))) is not None


class NullStorage(BlockStorage):
    """Metadata-only backend: remembers which hashes exist, stores no data.

    Lets capacity/eviction/ordering logic run in CI without payload memory —
    ``read`` returns None, so onboarding treats blocks as instantly lost.
    """

    def __init__(self) -> None:
        self.hashes: set[int] = set()

    def write(self, block_hash: int, payload: Payload) -> None:
        self.hashes.add(block_hash)

    def read(self, block_hash: int) -> Payload | None:
        return None

    def delete(self, block_hash: int) -> None:
        self.hashes.discard(block_hash)
