"""A capacity-bounded pool of completed KV blocks with LRU eviction.

Each tier (G2 host, G3 disk) is one TierPool over a storage backend. On
insert beyond capacity the least-recently-used block is evicted and handed to
``on_evict`` — which the manager uses to cascade G2 evictions into G3.

Parity: reference per-tier BlockPool with priority eviction
(`block_manager/pool.rs:156`, `pool/priority_key.rs`): our priority key is
(priority, lru-order) — lower priority evicts first, ties by recency.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable

from dynamo_tpu.blocks.storage import BlockStorage, Payload

logger = logging.getLogger(__name__)


@dataclass
class TierStats:
    capacity: int
    used: int
    hits: int
    misses: int
    evictions: int


class TierPool:
    def __init__(
        self,
        name: str,
        storage: BlockStorage,
        capacity_blocks: int,
        *,
        on_evict: Callable[[int, Payload | None], None] | None = None,
    ) -> None:
        self.name = name
        self.storage = storage
        self.capacity = capacity_blocks
        self.on_evict = on_evict
        self._lru: OrderedDict[int, int] = OrderedDict()  # block_hash -> priority
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._lru

    def has_local(self, block_hash: int) -> bool:
        """Membership in this tier's own (in-memory) index only — shared
        tiers additionally consult the backend in ``__contains__``."""
        return block_hash in self._lru

    def __len__(self) -> int:
        return len(self._lru)

    def put(self, block_hash: int, payload: Payload, *, priority: int = 0) -> None:
        """Insert (or refresh) a block; evicts LRU/low-priority past capacity."""
        if self.capacity <= 0:
            return
        if block_hash in self._lru:
            self._lru.move_to_end(block_hash)
            return
        while len(self._lru) >= self.capacity:
            self._evict_one()
        self.storage.write(block_hash, payload)
        self._lru[block_hash] = priority

    def _evict_one(self) -> None:
        # Lowest priority first; among equals, least recently used (front).
        victim = min(self._lru, key=lambda h: self._lru[h])
        lowest = self._lru[victim]
        for h, p in self._lru.items():  # first (= oldest) with lowest priority
            if p == lowest:
                victim = h
                break
        self._lru.pop(victim)
        # Only fetch the payload when someone will receive it — for a
        # terminal tier (no cascade) the read would be a pure waste, and on
        # a remote backend a full round-trip per eviction.
        payload = self.storage.read(victim) if self.on_evict is not None else None
        self.storage.delete(victim)
        self._evictions += 1
        if self.on_evict is not None:
            self.on_evict(victim, payload)

    def get(self, block_hash: int) -> Payload | None:
        """Fetch a block's payload (touches LRU). None on miss or lost payload."""
        if block_hash not in self._lru:
            self._misses += 1
            return None
        payload = self.storage.read(block_hash)
        if payload is None:  # metadata-only backend or lost file
            self._lru.pop(block_hash, None)
            self._misses += 1
            return None
        self._lru.move_to_end(block_hash)
        self._hits += 1
        return payload

    def remove(self, block_hash: int) -> None:
        if self._lru.pop(block_hash, None) is not None:
            self.storage.delete(block_hash)

    def clear(self) -> int:
        n = len(self._lru)
        for h in list(self._lru):
            self.remove(h)
        return n

    def stats(self) -> TierStats:
        return TierStats(
            capacity=self.capacity,
            used=len(self._lru),
            hits=self._hits,
            misses=self._misses,
            evictions=self._evictions,
        )


class SharedTierPool(TierPool):
    """A tier whose backend is shared between workers (the G4 object store).

    Local LRU state tracks only **this worker's own writes** (capacity
    applies to what we put there); membership and reads additionally fall
    through to the backend, so blocks offloaded by *other* workers are
    discoverable and onboardable. Semantics are a best-effort shared cache:
    a peer enforcing its own capacity may delete a block between our probe
    and fetch — readers must (and do) treat a None payload as a miss.
    """

    _degraded = False  # log-once latch: probes run per block on the request path

    def _note_failure(self, what: str) -> None:
        if not self._degraded:
            self._degraded = True
            logger.warning("shared tier %s degraded: %s failed (reads as misses "
                           "until the backend recovers)", self.name, what, exc_info=True)

    def _note_success(self) -> None:
        if self._degraded:
            self._degraded = False
            logger.info("shared tier %s recovered", self.name)

    def __contains__(self, block_hash: int) -> bool:
        if self.has_local(block_hash):
            return True
        exists = getattr(self.storage, "exists", None)
        if exists is None:
            return False
        try:
            hit = bool(exists(block_hash))
        except Exception:
            # A degraded remote tier must read as a miss, never break the
            # engine step that's probing it.
            self._note_failure("membership probe")
            return False
        self._note_success()
        return hit

    def get(self, block_hash: int) -> Payload | None:
        if self.has_local(block_hash):
            return super().get(block_hash)
        try:
            payload = self.storage.read(block_hash)  # a peer's block
            self._note_success()
        except Exception:
            self._note_failure("remote read")
            payload = None
        if payload is None:
            self._misses += 1
            return None
        self._hits += 1
        return payload
