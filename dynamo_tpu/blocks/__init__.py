"""Multi-tier KV block management: G1 (HBM) -> G2 (host RAM) -> G3 (disk).

The engine's PageAllocator (dynamo_tpu.engine.allocator) is the G1 tier.
This package adds the capacity tiers behind it:

- :mod:`dynamo_tpu.blocks.storage` — payload backends: host memory, disk
  (one file per block), and a Null backend for CI (metadata only).
- :mod:`dynamo_tpu.blocks.tier` — a capacity-bounded, LRU-evicting pool of
  completed blocks keyed by sequence hash.
- :mod:`dynamo_tpu.blocks.manager` — the KvBlockManager: write-through
  offload of committed G1 pages into G2 (cascading to G3 on G2 eviction),
  and onboarding — extending a prefill's prefix match by copying blocks
  back into freshly-allocated HBM pages.

Parity: reference block manager (SURVEY.md §2 rows 27-29) — CacheLevel
G1/G2/G3 pools (`block_manager.rs:69-82`), OffloadManager (`offload.rs:80`),
storage backends (`storage.rs:104-433`). TPU mapping: NIXL RDMA is replaced
by device<->host copies of page slices (`jax.device_get` / donated scatter),
and G4 (remote) arrives with disaggregation (KV migration over the runtime's
stream transport).
"""

from dynamo_tpu.blocks.manager import KvBlockManager, BlockManagerConfig
from dynamo_tpu.blocks.tier import TierPool
from dynamo_tpu.blocks.storage import HostStorage, DiskStorage, NullStorage

__all__ = [
    "KvBlockManager",
    "BlockManagerConfig",
    "TierPool",
    "HostStorage",
    "DiskStorage",
    "NullStorage",
]
