"""KvBlockManager: ties the capacity tiers to the engine's G1 pages.

Flow (parity: reference OffloadManager `offload.rs:80` + onboarding):

- **Offload (write-through):** when a G1 page fills and commits to the
  prefix cache, its payload is copied device->host into G2. A G2 eviction
  cascades the payload into G3 (disk). G1 eviction then never loses data
  that was worth keeping.
- **Onboard:** at admission, after the G1 prefix match stops, the manager is
  asked for the *next* blocks in the chain; hits are copied back into
  freshly-allocated G1 pages, extending the cached prefix and shrinking
  prefill compute.

Device access is through two callables injected by the engine runner
(``read_page(page_id) -> (k, v)``, ``write_page(page_id, k, v)``), keeping
this module free of JAX so tier logic unit-tests run instantly.
"""

from __future__ import annotations

import logging
import pathlib
import threading
from dataclasses import dataclass
from typing import Callable

import numpy as np

from dynamo_tpu.blocks.storage import DiskStorage, HostStorage, NullStorage, Payload
from dynamo_tpu.blocks.tier import SharedTierPool, TierPool

logger = logging.getLogger(__name__)

ReadPage = Callable[[int], Payload]
WritePage = Callable[[int, np.ndarray, np.ndarray], None]
WritePages = Callable[[list, list, list], None]


@dataclass
class BlockManagerConfig:
    g2_capacity_blocks: int = 1024
    g3_capacity_blocks: int = 0  # 0 disables the disk tier
    g3_path: str | pathlib.Path = "/tmp/dynamo_tpu_g3"
    g4_capacity_blocks: int = 0  # 0 disables the remote (object-store) tier
    onboard_limit: int = 64  # max blocks copied back per admission
    null_storage: bool = False  # CI: capacity logic without payload memory


class KvBlockManager:
    def __init__(
        self,
        config: BlockManagerConfig,
        *,
        read_page: ReadPage,
        write_page: WritePage,
        write_pages: WritePages | None = None,
        g2_storage=None,
        g4_storage=None,
    ) -> None:
        self.config = config
        self._read_page = read_page
        self._write_page = write_page
        self._write_pages = write_pages
        # Tier metadata + storage ops are guarded per block: the async
        # onboarding session fetches payloads from a background thread while
        # the engine thread offloads freshly committed pages into the same
        # pools. Per-block granularity keeps a slow G3/G4 read from gating
        # flush_offloads (and thus the next engine step) for a whole fetch.
        self._lock = threading.RLock()

        # G4: deployment-wide remote tier (object store). Pass a
        # `storage.RemoteStorage` (launch wires it from the runtime store);
        # capacity without a backend runs metadata-only (CI). SharedTierPool:
        # local LRU over our own writes, fall-through probes for peers'.
        self.g4: TierPool | None = None
        if config.g4_capacity_blocks > 0:
            self.g4 = SharedTierPool("g4", g4_storage or NullStorage(), config.g4_capacity_blocks)

        self.g3: TierPool | None = None
        if config.g3_capacity_blocks > 0:
            g3_storage = NullStorage() if config.null_storage else DiskStorage(config.g3_path)

            def cascade_g4(block_hash: int, payload: Payload | None) -> None:
                if self.g4 is not None and payload is not None:
                    self.g4.put(block_hash, payload)

            self.g3 = TierPool(
                "g3", g3_storage, config.g3_capacity_blocks, on_evict=cascade_g4
            )

        def cascade(block_hash: int, payload: Payload | None) -> None:
            if payload is None:
                return
            if self.g3 is not None:
                self.g3.put(block_hash, payload)
            elif self.g4 is not None:  # no disk tier: spill host -> remote
                self.g4.put(block_hash, payload)

        if g2_storage is None:
            g2_storage = NullStorage() if config.null_storage else HostStorage()
        self.g2 = TierPool("g2", g2_storage, config.g2_capacity_blocks, on_evict=cascade)
        self.offloaded = 0
        self.onboarded = 0

    @property
    def _tiers(self) -> list[TierPool]:
        return [t for t in (self.g2, self.g3, self.g4) if t is not None]

    # -- offload path ------------------------------------------------------

    def offload(self, block_hash: int, page_id: int) -> None:
        """Write-through one committed G1 page into G2 (no-op if present)."""
        self.offload_batch([(block_hash, page_id)])

    def offload_batch(self, items: list[tuple[int, int]], *, read_pages=None,
                      read_pages_async=None) -> None:
        """Write-through many (block_hash, page_id) pairs at once.

        With ``read_pages`` (``list[page_id] -> list[Payload]``) the device
        reads collapse into one batched gather + one device->host transfer;
        otherwise falls back to per-page reads. ``read_pages_async``
        (``list[page_id] -> handle`` with ``wait() -> list[Payload]``) is
        preferred over both: the gather is dispatched and its device->host
        DMA kicked off immediately, and this thread only blocks at the tier
        puts — the copy overlaps whatever the engine does in between.
        """
        todo: list[tuple[int, int]] = []
        seen: set[int] = set()
        with self._lock:
            for block_hash, page_id in items:
                # Dedup against LOCAL membership only: a shared G4's full
                # __contains__ does a remote round-trip per probe, which would
                # gate flush_offloads (and thus the next engine step) on store
                # latency for every freshly committed block. Re-offloading a
                # block a peer already persisted is harmless.
                if block_hash in seen or any(tier.has_local(block_hash) for tier in self._tiers):
                    continue
                seen.add(block_hash)
                todo.append((block_hash, page_id))
        if not todo:
            return
        if read_pages_async is not None:
            payloads = read_pages_async([p for _, p in todo]).wait()
        elif read_pages is not None:
            payloads = read_pages([p for _, p in todo])
        else:
            payloads = [self._read_page(p) for _, p in todo]
        for (block_hash, _), payload in zip(todo, payloads):
            with self._lock:
                self.g2.put(block_hash, payload)
            self.offloaded += 1

    # -- onboard path ------------------------------------------------------

    def lookup(self, block_hash: int) -> Payload | None:
        """G2 first, then G3, then G4 (a deeper hit promotes back into G2)."""
        with self._lock:
            return self._lookup_tiered(block_hash)[0]

    def _lookup_tiered(self, block_hash: int) -> tuple[Payload | None, str]:
        payload = self.g2.get(block_hash)
        if payload is not None:
            return payload, "g2"
        for name, tier in (("g3", self.g3), ("g4", self.g4)):
            if tier is None:
                continue
            payload = tier.get(block_hash)
            if payload is not None:
                self.g2.put(block_hash, payload)
                return payload, name
        return None, ""

    def probe_prefix(self, block_hashes: list[int], start: int, *, local_only: bool = False) -> int:
        """How many consecutive blocks from ``start`` the tiers hold.

        Membership-only — no payload I/O. Admission uses this to budget and
        allocate pages first; payloads are fetched only once pages exist
        (otherwise each failed admission attempt would re-read from disk).
        ``local_only`` skips a shared G4's remote fall-through probes —
        the residual-cost *estimate* must not gate EDF prepare() on store
        round-trips (it may undercount peers' blocks; pricing, not policy).
        """
        n = 0
        with self._lock:
            for h in block_hashes[start:]:
                if n >= self.config.onboard_limit:
                    break
                if any(
                    tier.has_local(h) if local_only else h in tier
                    for tier in self._tiers
                ):
                    n += 1
                else:
                    break
        return n

    def fetch_prefix(self, block_hashes: list[int], start: int, count: int) -> list[Payload]:
        """Read up to ``count`` consecutive payloads; may return fewer if a
        block was evicted (or its payload lost) since the probe."""
        return self.fetch_prefix_tiered(block_hashes, start, count)[0]

    def fetch_prefix_tiered(
        self, block_hashes: list[int], start: int, count: int
    ) -> tuple[list[Payload], list[str]]:
        """``fetch_prefix`` plus the tier each payload came from.

        The async onboarding session runs this off the engine thread; the
        per-block lock in ``_lookup_tiered`` is what makes that safe against
        concurrent offloads. Tier names feed the per-tier onboard metrics."""
        payloads: list[Payload] = []
        tiers: list[str] = []
        for h in block_hashes[start : start + count]:
            with self._lock:
                payload, tier = self._lookup_tiered(h)
            if payload is None:
                break
            payloads.append(payload)
            tiers.append(tier)
        return payloads, tiers

    def onboard(self, page_ids: list[int], payloads: list[Payload]) -> None:
        """Copy payloads host->device into the given (freshly-allocated) pages.

        With a batched writer wired (``ModelRunner.write_pages``) N pages cost
        one transfer + one scatter dispatch; the per-page path is the fallback
        for runners without it."""
        if not payloads:
            return
        if self._write_pages is not None and len(payloads) > 1:
            pids = list(page_ids[: len(payloads)])
            self._write_pages(pids, [k for k, _ in payloads], [v for _, v in payloads])
        else:
            for pid, (k, v) in zip(page_ids, payloads):
                self._write_page(pid, k, v)
        self.onboarded += len(payloads)

    # -- admin -------------------------------------------------------------

    def clear(self) -> int:
        with self._lock:
            return sum(tier.clear() for tier in self._tiers)

    def stats(self) -> dict:
        out = {"g2": self.g2.stats().__dict__, "offloaded": self.offloaded, "onboarded": self.onboarded}
        if self.g3 is not None:
            out["g3"] = self.g3.stats().__dict__
        if self.g4 is not None:
            out["g4"] = self.g4.stats().__dict__
        return out
