"""Ring attention: sequence-parallel exact attention over the ``sp`` mesh axis.

For prompts too long for one chip's HBM/FLOPs, the sequence axis is sharded
across ``sp`` devices. Each device keeps its local Q shard and streams every
K/V shard through the ring: at step s it attends its Q against the K/V chunk
currently resident, folds the result into an online-softmax accumulator
(numerically identical to full attention), then rotates K/V to the next
device with ``ppermute`` over ICI. Compute and communication overlap; memory
per device stays O(T/sp).

The reference has no sequence/context parallelism at all (SURVEY.md §5 —
engines own attention and long context is handled by KV offload); this module
is a TPU-first capability addition per the build plan (§7 step 6).

Causality is handled by global position masking, so it composes with paged
prefill: pass the absolute positions of the Q and KV shards.
"""

from __future__ import annotations

import functools

import jax

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(*args, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(*args, **kw)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _pcast_varying(x, axes):
    """Mark ``x`` as device-varying over ``axes`` where jax tracks vma
    (>= 0.5); identity on 0.4.x, whose shard_map has no vma types and
    accepts replicated/varying carries interchangeably."""
    pcast = getattr(jax.lax, "pcast", None)
    return x if pcast is None else pcast(x, axes, to="varying")


NEG_INF = -1e30


def _chunk_attention(q, k, v, q_pos, kv_pos, scale):
    """Partial attention of q against one K/V chunk: returns (acc, m, l).

    K/V arrive with their native (possibly grouped) head count and are
    expanded here, locally — the ring rotates the compact GQA shards, not the
    query-head-inflated copies.

    acc: unnormalized weighted values [B, Tq, H, hd] (f32)
    m:   running max logit [B, H, Tq]
    l:   running sum of exp [B, H, Tq]
    """
    h, hkv = q.shape[2], k.shape[2]
    if h != hkv:
        k = jnp.repeat(k, h // hkv, axis=2)
        v = jnp.repeat(v, h // hkv, axis=2)
    logits = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    mask = kv_pos[:, None, None, :] <= q_pos[:, None, :, None]  # [B, 1, Tq, Ts]
    logits = jnp.where(mask, logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [B, H, Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    acc = acc1 * a1.transpose(0, 2, 1)[..., None] + acc2 * a2.transpose(0, 2, 1)[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def ring_attention_sharded(q, k, v, q_pos, kv_pos, *, axis_name: str, scale: float,
                           vary_axes: tuple[str, ...] | None = None):
    """Body to run under shard_map: local shards, full-sequence semantics.

    q:      [B, Tq_local, H, hd]      (local Q shard)
    k, v:   [B, Ts_local, Hkv, hd]    (local K/V shard, rotates around the ring)
    q_pos:  [B, Tq_local] global positions of the local Q shard
    kv_pos: [B, Ts_local] global positions of the local K/V shard (rotates too)
    """
    n = jax.lax.psum(1, axis_name)
    b, tq, h, _ = q.shape
    hd_v = v.shape[-1]  # may differ from q/k (MLA: value = latent, k = latent+rope)

    # pcast-to-varying: mark the fresh accumulators as varying over every
    # mapped axis (the ring axis, plus dp when the batch dim is sharded
    # through the shard_map) so the fori_loop carry type matches the
    # (device-varying) merged partials.
    vary = tuple(vary_axes) if vary_axes else (axis_name,)
    acc = _pcast_varying(jnp.zeros((b, tq, h, hd_v), jnp.float32), vary)
    m = _pcast_varying(jnp.full((b, h, tq), NEG_INF, jnp.float32), vary)
    l = _pcast_varying(jnp.zeros((b, h, tq), jnp.float32), vary)

    def ring_step(i, carry):
        acc, m, l, k_cur, v_cur, kv_pos_cur = carry
        a2, m2, l2 = _chunk_attention(q, k_cur, v_cur, q_pos, kv_pos_cur, scale)
        acc, m, l = _merge(acc, m, l, a2, m2, l2)
        # Rotate K/V (and their positions) one step around the ring.
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        p_nxt = jax.lax.ppermute(kv_pos_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt, p_nxt

    acc, m, l, _, _, _ = jax.lax.fori_loop(
        0, n, ring_step, (acc, m, l, k, v, kv_pos)
    )
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, hd] full sequence (host view)
    k: jnp.ndarray,  # [B, T, Hkv, hd]
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T] global positions
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    scale: float | None = None,
) -> jnp.ndarray:
    """Causal exact attention with the sequence sharded over ``axis_name``.

    T must divide evenly by the axis size. Returns [B, T, H, hd].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # Keep the batch dim dp-sharded through the ring: the engine's step
    # inputs arrive P("dp", ...), and replicating batch here (P(None, sp))
    # forces an SPMD involuntary full rematerialization of every ring input
    # at the prefill boundary (a real collective on ICI). The ring's own
    # collectives ride only ``axis_name``; dp stays pure data parallel.
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    seq_spec = P(batch_axis, axis_name, None, None)
    pos_spec = P(batch_axis, axis_name)

    body = functools.partial(
        ring_attention_sharded, axis_name=axis_name, scale=scale,
        vary_axes=(axis_name,) + ((batch_axis,) if batch_axis else ()),
    )
    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, pos_spec, pos_spec),
        out_specs=seq_spec,
    )
    return fn(q, k, v, positions, positions)
