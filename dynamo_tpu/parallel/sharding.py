"""GSPMD sharding rules for Llama-family params, paged KV cache, activations.

Megatron-style tensor parallelism expressed purely as shardings — no explicit
collectives; XLA inserts the all-reduce after ``wo`` / ``w_down`` row-parallel
matmuls and partitions QKV/gate/up column-parallel:

| tensor              | shape                   | spec                        |
|---------------------|-------------------------|-----------------------------|
| embed               | [V, D]                  | (tp, None) — vocab-sharded  |
| lm_head             | [D, V]                  | (None, tp)                  |
| wq / wk / wv        | [L, D, H*hd]            | (None, None, tp)            |
| wo                  | [L, H*hd, D]            | (None, tp, None)            |
| w_gate / w_up       | [L, D, F]               | (None, None, tp)            |
| w_down              | [L, F, D]               | (None, tp, None)            |
| MoE expert weights  | [L, E, D, F]            | (None, ep, None, tp)        |
| router              | [L, D, E]               | replicated                  |
| norms               | [L, D] / [D]            | replicated                  |
| k/v cache           | [L, pages, ps, kv*hd]   | (None, None, None, tp)      |

KV-head sharding of the cache matches the head sharding of k/v projections,
so cache writes and paged-attention gathers are collective-free; GQA requires
``tp <= num_kv_heads`` (MeshPlan.auto enforces this).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_shardings(mesh: Mesh, params: dict[str, Any]) -> dict[str, Any]:
    """A pytree of NamedShardings matching the params pytree."""

    def spec_for(path: tuple[str, ...], leaf) -> P:
        name = path[-1]
        if name == "embed":
            return P("tp", None)
        if name == "lm_head":
            return P(None, "tp")
        if name in ("wq", "wk", "wv"):
            return P(None, None, "tp")
        if name == "wo":
            return P(None, "tp", None)
        if name in ("w_gate", "w_up"):
            if leaf.ndim == 4:  # MoE: [L, E, D, F]
                return P(None, "ep", None, "tp")
            return P(None, None, "tp")
        if name == "w_down":
            if leaf.ndim == 4:  # MoE: [L, E, F, D]
                return P(None, "ep", "tp", None)
            return P(None, "tp", None)
        if name in ("bq", "bk", "bv"):  # qkv biases follow the head split
            return P(None, "tp")
        # MLA (models/mla.py): heads shard on tp; the shared latent
        # projections replicate (they're rank-512-ish — tiny next to the
        # per-head up-projections).
        if name in ("w_uk", "w_uv"):  # [L, r_kv, H, dn|dv]
            return P(None, None, "tp", None)
        if name in ("w_q_b", "w_q"):  # output dim is H*(dn+dr)
            return P(None, None, "tp")
        if name == "wo_mla":  # [L, H*dv, D]
            return P(None, "tp", None)
        if name in ("w_shared_gate", "w_shared_up"):
            return P(None, None, "tp")
        if name == "w_shared_down":
            return P(None, "tp", None)
        return P()  # norms, router, shared_gate: replicated

    def walk(tree, path):
        if isinstance(tree, dict):
            # Weight-only int8 leaf {"qw": int8, "scale": [..., d_out]}:
            # qw shards exactly like the float weight it replaces (derive
            # the spec from the real qw array — same ndim); scale keeps only
            # the output-channel axis (the weight spec minus its -2 axis).
            if "qw" in tree and "scale" in tree:
                base = spec_for(path, tree["qw"])
                scale_spec = P(*base[:-2], base[-1]) if len(base) >= 2 else base
                return {
                    "qw": NamedSharding(mesh, base),
                    "scale": NamedSharding(mesh, scale_spec),
                }
            # Packed int4 leaf {"qw4": int8[..., d_in//2, O], "scale":
            # [..., G, O], "qbias"?}: qw4 keeps the float weight's rank, so
            # the base spec applies unchanged. The scale's group axis
            # subdivides d_in exactly like the packed byte axis does, so it
            # inherits the same spec (a row-parallel tp split of d_in maps
            # to a tp split of whole groups, provided tp divides G — the
            # same divisibility the weight split already requires).
            if "qw4" in tree and "scale" in tree:
                base = spec_for(path, tree["qw4"])
                scale_spec = base
                out = {
                    "qw4": NamedSharding(mesh, base),
                    "scale": NamedSharding(mesh, scale_spec),
                }
                if "qbias" in tree:
                    out["qbias"] = NamedSharding(mesh, scale_spec)
                return out
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return NamedSharding(mesh, spec_for(path, tree))

    return walk(params, ())


def cache_shardings(mesh: Mesh, attn_type: str = "gqa") -> NamedSharding:
    """Paged KV cache [L, pages, ps, W] placement.

    GQA: shard the head-major flattened KV-head dim on tp (head h occupies
    [h*hd, (h+1)*hd), so a tp-split is a contiguous block of whole heads,
    matching the k/v projection sharding).

    MLA: REPLICATE. The latent stream is shared by every query head (MQA) —
    a width split would slice latent channels and force per-layer
    collectives inside attention. Replication is what DeepSeek TP serving
    does everywhere: the latent cache is ~7-25x smaller than an equivalent
    GQA cache, so one copy per tp rank still beats a sharded GQA cache."""
    if attn_type == "mla":
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(None, None, None, "tp"))


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Request-batch inputs [B, ...]: shard batch on dp."""
    return NamedSharding(mesh, P("dp", *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: dict[str, Any], mesh: Mesh) -> dict[str, Any]:
    """Place a params pytree onto the mesh with TP/EP shardings."""
    shardings = param_shardings(mesh, params)
    return jax.tree.map(jax.device_put, params, shardings)
