"""Device meshes and topology planning.

Axis convention (order matters — outermost first so DCN-crossing axes come
before ICI axes when multi-slice):

- ``dp`` — data parallel: independent batch shards (requests).
- ``tp`` — tensor parallel: attention heads / MLP hidden dimension.
- ``sp`` — sequence parallel: ring-attention shards of the sequence axis.
- ``ep`` — expert parallel: MoE experts (aliases tp's devices when unused).

``MeshPlan.auto`` picks a plan for a model on N devices: tp capped by the
model's KV-head count (so the paged cache shards cleanly), remaining devices
to dp. Explicit plans override for benchmarks and disagg topologies.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.sp * self.ep

    @classmethod
    def auto(cls, num_devices: int, *, num_kv_heads: int, num_experts: int = 0) -> "MeshPlan":
        """Largest tp dividing both device count and KV-head count; rest dp.

        MoE models put the non-dp factor on ``ep`` instead when experts
        outnumber KV heads (wide-EP regime, e.g. DeepSeek).
        """
        tp = 1
        for cand in range(min(num_devices, num_kv_heads), 0, -1):
            if num_devices % cand == 0 and num_kv_heads % cand == 0:
                tp = cand
                break
        if num_experts and num_experts >= num_kv_heads and num_devices > 1:
            ep = 1
            for cand in range(min(num_devices, num_experts), 0, -1):
                if num_devices % cand == 0 and num_experts % cand == 0:
                    ep = cand
                    break
            if ep > 1:
                return cls(dp=num_devices // ep, ep=ep)
        return cls(dp=num_devices // tp, tp=tp)


def make_mesh(plan: MeshPlan, devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < plan.num_devices:
        raise ValueError(f"plan needs {plan.num_devices} devices, have {len(devices)}")
    arr = np.asarray(devices[: plan.num_devices]).reshape(plan.dp, plan.tp, plan.sp, plan.ep)
    return Mesh(arr, AXES)
