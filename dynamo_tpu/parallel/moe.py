"""Expert-parallel MoE dispatch: capacity-bounded scatter/combine.

TPU-native routed-MoE execution, replacing the dense every-token-through-
every-expert formulation (``models/llama._mlp_moe`` dense path) with the
standard capacity-based dispatch used by TPU MoE stacks (GShard/Switch
lineage), expressed so GSPMD turns the data movement into all-to-all
collectives over the ``ep`` mesh axis:

- Router top-k picks (expert, weight) per token; every (token, choice) pair
  gets a *position* inside its expert's fixed-capacity buffer via a one-hot
  cumsum (O(N*k*E), no vocabulary-scale sorts, static shapes throughout).
- Tokens are **scattered** into ``[E, C, D]`` expert buffers (O(N*k*D) data
  movement — never the O(N*E*C*D) dispatch-einsum of the original GShard
  formulation, which is quadratic in tokens at prefill widths).
- Expert FFNs run as batched matmuls ``[E, C, D] @ [E, D, F]`` — one MXU
  contraction over all local experts. With ``w_gate/w_up/w_down`` sharded
  ``P(None, ep, None, tp)`` (see ``parallel/sharding.py``), GSPMD shards the
  expert axis and inserts the token all-to-all at the scatter/gather
  boundaries; ICI carries exactly the dispatched tokens.
- Combine gathers each choice's output row and mixes by routing weight.

Over-capacity tokens are dropped (zero contribution from that choice,
Switch-style, earlier tokens win); serving engines size ``capacity_factor``
so drops are measure-zero, and tests use a no-drop capacity to prove
bit-parity with the dense formulation.

Parity: the reference delegates wide-EP MoE serving to SGLang's DeepEP path
(`examples/sglang/`, SURVEY.md §2 parallelism table row EP); this module is
the first-party TPU equivalent of that capability.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np
import jax.numpy as jnp

from dynamo_tpu.models.quant import maybe_dequant as _dq


class _DropCounter:
    """Process-wide cumulative (choices, drops) across every capacity
    dispatch — the live counterpart of :func:`moe_drop_stats` (which
    recomputes routing offline). Fed from inside the jitted dispatch via
    ``jax.debug.callback`` (two scalars per MoE layer per step, async — no
    device stall), read by ``EngineCore.metrics()`` into
    ``ForwardPassMetrics.moe_*`` and from there onto the Prometheus plane
    (`deploy/metrics_service.py`). Process-wide because the dispatch has no
    engine identity; workers run one engine per process, so per-worker
    series stay exact (a dual-engine test process sees the sum).

    The dropless and dense dispatches never drop, so their zero is implicit.
    On backends without host-callback support (axon tunnel) the counter
    stays 0 — see :func:`_host_callback_supported`.

    Counts are DISPATCH-level: the runner bucket-pads batch/time, and padded
    rows route and occupy capacity slots like real ones, so ``choices``
    includes them. The drop *rate* stays representative because
    :func:`expert_capacity` is sized from the same padded N — padding
    inflates numerator and denominator together, it does not mask real
    drops.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.choices = 0
        self.dropped = 0

    def add(self, choices: int, dropped: int) -> None:
        with self._lock:
            self.choices += int(choices)
            self.dropped += int(dropped)

    def snapshot(self) -> tuple[int, int]:
        with self._lock:
            return self.choices, self.dropped

    def reset(self) -> None:
        with self._lock:
            self.choices = 0
            self.dropped = 0


DROP_COUNTER = _DropCounter()


_callback_ok: bool | None = None


def _host_callback_supported() -> bool:
    """Probe once whether the active backend implements host callbacks.

    Not a given: the axon-tunneled v5e PJRT plugin raises UNIMPLEMENTED for
    send/recv host callbacks (discovered by running the counter on it), so
    the drop counter must degrade to disabled there instead of crashing the
    first capacity-dispatch step."""
    global _callback_ok
    if _callback_ok is None:
        # The first call usually happens while TRACING a model forward; a jit
        # execution is illegal under an ambient trace, so probe on a fresh
        # thread (no trace context — JAX traces are thread-local).
        result: dict[str, object] = {}

        def _probe() -> None:
            try:
                out = jax.jit(
                    lambda x: (jax.debug.callback(lambda _v: None, x), x + 1)[1]
                )(jnp.int32(0))
                out.block_until_ready()
                result["ok"] = True
            except Exception as e:
                result["ok"] = False
                result["err"] = repr(e)

        t = threading.Thread(target=_probe, name="moe-callback-probe")
        t.start()
        t.join()
        _callback_ok = result.get("ok", False)
        if not _callback_ok:
            import logging

            logging.getLogger(__name__).warning(
                "backend rejects host callbacks (%s): MoE drop counters "
                "disabled — moe_dropped_total will read 0 regardless of "
                "drops; set DYNAMO_MOE_DROP_STATS=1 to force (and crash "
                "loudly) if this backend should support them",
                result.get("err", "probe thread died"),
            )
    return _callback_ok


def _drop_stats_enabled() -> bool:
    """DYNAMO_MOE_DROP_STATS=0 disables the in-dispatch counter, =1 forces
    it (crashing loudly on backends without host callbacks); default is
    on wherever the backend supports it."""
    env = os.environ.get("DYNAMO_MOE_DROP_STATS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return _host_callback_supported()


def route_tokens(
    lp: dict,
    x: jnp.ndarray,  # [N, D] flattened tokens
    *,
    k: int,
    scoring: str = "softmax",
    norm_topk: bool = True,
    scaling: float = 1.0,
    n_group: int = 0,
    topk_group: int = 0,
    group_score: str = "max",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Router semantics shared by every MoE family; returns (weights f32[N,k],
    expert ids i32[N,k]).

    - ``softmax`` scoring + ``norm_topk``: Mixtral (softmax over all logits,
      gather top-k, renormalize — algebraically softmax(top-k logits)).
    - ``softmax`` without norm: Qwen2-MoE (weights are raw softmax probs).
    - ``sigmoid``: DeepSeek-V3. Selection uses scores *plus* the aux-free
      load-balancing bias ``router_bias`` (e_score_correction_bias,
      topk_method=noaux_tc), optionally group-limited: experts are split
      into ``n_group`` groups, only the best ``topk_group`` groups stay
      eligible. The *weights* use the unbiased scores, renormalized, then
      scaled by ``routed_scaling_factor``. (HF `modeling_deepseek_v3.py`.)
    - ``group_score``: how a group is ranked — DeepSeek-V2's
      group_limited_greedy uses the per-group ``"max"`` score
      (`modeling_deepseek_v2.py:76`); V3's noaux_tc uses the ``"top2sum"``
      of biased scores (`modeling_deepseek_v3.py:127`).
    """
    logits = (x @ lp["router"]).astype(jnp.float32)  # [N, E]
    if scoring == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    elif scoring == "softmax":
        scores = jax.nn.softmax(logits, axis=-1)
    else:
        raise ValueError(f"unknown moe scoring {scoring!r}")
    choice = scores + lp["router_bias"] if "router_bias" in lp else scores
    if n_group > 1 and 0 < topk_group < n_group:
        n, e = choice.shape
        grouped = choice.reshape(n, n_group, e // n_group)
        if group_score == "top2sum":
            gscore = jax.lax.top_k(grouped, min(2, e // n_group))[0].sum(-1)  # [N, G]
        else:
            gscore = grouped.max(-1)
        _, gidx = jax.lax.top_k(gscore, topk_group)
        gmask = jnp.zeros_like(gscore, dtype=bool).at[
            jnp.arange(n)[:, None], gidx
        ].set(True)
        choice = jnp.where(
            jnp.repeat(gmask, e // n_group, axis=1), choice, -jnp.inf
        )
    _, topi = jax.lax.top_k(choice, k)
    weights = jnp.take_along_axis(scores, topi, axis=1)  # [N, k] unbiased
    if norm_topk:
        weights = weights / (weights.sum(-1, keepdims=True) + 1e-20)
    return weights * scaling, topi


def moe_mlp_dropless(
    lp: dict,
    x: jnp.ndarray,  # [N, D] flattened tokens
    *,
    num_experts_per_token: int,
    routing: dict | None = None,
) -> jnp.ndarray:
    """Dropless routed MoE via ``lax.ragged_dot`` (TPU grouped matmul).

    Token copies are stable-sorted by expert id (an O(N*k) argsort — token
    count, never vocabulary), expert FFNs run as ragged grouped matmuls with
    per-expert group sizes, and results unsort back. No capacity, no drops:
    output is exact and independent of batch composition — the default
    serving path whenever the expert axis is not sharded (parity with the
    dropless DeepEP-style dispatch the reference gets from SGLang).
    """
    n, d = x.shape
    e = lp["router"].shape[-1]
    k = num_experts_per_token

    weights, topi = route_tokens(lp, x, k=k, **(routing or {}))

    flat_e = topi.reshape(-1)  # [N*k]
    order = jnp.argsort(flat_e, stable=True)
    xk = jnp.repeat(x, k, axis=0)[order]  # [N*k, D] grouped by expert
    group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)

    gate = jax.nn.silu(jax.lax.ragged_dot(xk, _dq(lp["w_gate"]), group_sizes))
    up = jax.lax.ragged_dot(xk, _dq(lp["w_up"]), group_sizes)
    down = jax.lax.ragged_dot(gate * up, _dq(lp["w_down"]), group_sizes)  # [N*k, D]

    rows = jnp.zeros_like(down).at[order].set(down)  # unsort
    out = (rows.astype(jnp.float32) * weights.reshape(-1)[:, None]).reshape(n, k, d).sum(axis=1)
    return out.astype(x.dtype)


def expert_capacity(num_tokens: int, num_experts: int, k: int, capacity_factor: float) -> int:
    """Per-expert buffer size: ceil(N*k/E * f), clamped to [k, N*k] and
    rounded up to a multiple of 8 (TPU sublane alignment)."""
    c = int(num_tokens * k * capacity_factor / num_experts + 0.999)
    c = max(k, min(c, num_tokens * k))
    return -(-c // 8) * 8


def moe_drop_stats(
    lp: dict,
    x: jnp.ndarray,  # [N, D] flattened tokens
    *,
    num_experts_per_token: int,
    capacity_factor: float = 1.25,
    capacity: int | None = None,
    routing: dict | None = None,
) -> tuple[int, int]:
    """(total choices, dropped choices) for this batch under the capacity
    dispatch's drop rule — the observability hook for drop rate (the
    dispatch itself is pure jit; this recomputes routing on demand, so call
    it on sampled batches, not the hot path)."""
    n = x.shape[0]
    e = lp["router"].shape[-1]
    k = num_experts_per_token
    c = capacity if capacity is not None else expert_capacity(n, e, k, capacity_factor)
    _w, topi = route_tokens(lp, x, k=k, **(routing or {}))
    flat_e = np.asarray(topi).reshape(-1)
    oh = np.eye(e, dtype=np.int64)[flat_e]
    pos = (np.cumsum(oh, axis=0) * oh).sum(-1) - 1
    dropped = int((pos >= c).sum())
    return n * k, dropped


def moe_mlp(
    lp: dict,
    x: jnp.ndarray,  # [N, D] flattened tokens
    *,
    num_experts_per_token: int,
    capacity_factor: float = 1.25,
    capacity: int | None = None,
    routing: dict | None = None,
) -> jnp.ndarray:
    """Routed MoE FFN over flattened tokens; returns [N, D].

    ``lp`` holds ``router [D, E]``, ``w_gate/w_up [E, D, F]``, ``w_down
    [E, F, D]`` (one layer's slice of the stacked params).
    """
    n, d = x.shape
    e = lp["router"].shape[-1]
    k = num_experts_per_token
    c = capacity if capacity is not None else expert_capacity(n, e, k, capacity_factor)

    weights, topi = route_tokens(lp, x, k=k, **(routing or {}))

    # Buffer position of each (token, choice) within its expert: rank among
    # all earlier assignments to the same expert (token-major priority).
    flat_e = topi.reshape(-1)  # [N*k]
    oh = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)  # [N*k, E]
    pos = (jnp.cumsum(oh, axis=0) * oh).sum(-1) - 1  # [N*k]
    keep = pos < c
    slot = jnp.where(keep, pos, c)  # dropped choices land in a spill row

    if _drop_stats_enabled():
        jax.debug.callback(
            DROP_COUNTER.add, jnp.int32(n * k), (~keep).sum().astype(jnp.int32)
        )

    # Scatter tokens into expert buffers (+1 spill row, sliced off).
    xk = jnp.repeat(x, k, axis=0)  # [N*k, D] — choice j of token t at t*k+j
    buf = jnp.zeros((e, c + 1, d), x.dtype).at[flat_e, slot].set(xk)
    expert_in = buf[:, :c]  # [E, C, D]

    # Batched expert FFN: one contraction over all experts; GSPMD shards the
    # leading axis on ep from the weight shardings.
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, _dq(lp["w_gate"])))
    up = jnp.einsum("ecd,edf->ecf", expert_in, _dq(lp["w_up"]))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, _dq(lp["w_down"]))  # [E, C, D]

    # Combine: gather each choice's row, weight, and sum over the k choices.
    rows = expert_out[flat_e, jnp.minimum(slot, c - 1)]  # [N*k, D]
    w = (weights.reshape(-1) * keep.astype(weights.dtype))[:, None]
    out = (rows.astype(jnp.float32) * w).reshape(n, k, d).sum(axis=1)
    return out.astype(x.dtype)
