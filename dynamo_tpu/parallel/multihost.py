"""Multi-host mesh bring-up: ``jax.distributed`` coordinated by the runtime.

One serving worker can span multiple hosts (a TPU pod slice): every host
runs the same process, `jax.distributed.initialize` stitches their local
chips into one global device set, and a single GSPMD mesh (dp/tp/sp/ep —
``parallel/mesh.py``) spans all of them. Bring-up needs a rendezvous — the
leader picks the coordinator address, followers must learn it and start
together — which runs through the discovery store via the lease-bound
leader/worker barrier (``runtime/barrier.py``), so a host dying during
bring-up releases its slot instead of wedging the fleet.

The same flags the reference threads through its engines are accepted here
(`--num-nodes/--node-rank/--leader-addr`): reference
`lib/llm/src/engines.rs:43` (``MultiNodeConfig``), `flags.rs:82-100`,
`lib/runtime/src/utils/leader_worker_barrier.rs:137`.

Usage (each host)::

    cfg = MultiNodeConfig(num_nodes=2, node_rank=rank)
    await bringup(cfg, runtime)      # rendezvous + jax.distributed.initialize
    mesh = make_mesh(plan)           # jax.devices() is now the global set

CPU-mesh variant for tests: works identically with
``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count=K`` in each
process — the 2-process test in ``tests/test_multihost.py`` serves a sharded
model this way without TPU hardware.
"""

from __future__ import annotations

import dataclasses
import logging
import socket

logger = logging.getLogger(__name__)

BARRIER_NAME = "jax-multihost-bringup"


@dataclasses.dataclass
class MultiNodeConfig:
    """Topology of one logical worker spanning several hosts."""

    num_nodes: int = 1
    node_rank: int = 0
    # host:port of the rank-0 jax coordinator. Leader: picked automatically
    # if unset. Followers: learned through the barrier if unset.
    leader_addr: str | None = None

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0

    @property
    def is_multi_node(self) -> bool:
        return self.num_nodes > 1


def _pick_coordinator_addr(port: int = 0) -> str:
    """A host:port the other nodes can reach; an OS-assigned free port."""
    host = socket.gethostbyname(socket.gethostname())
    with socket.socket() as s:
        s.bind(("", port))
        port = s.getsockname()[1]
    return f"{host}:{port}"


async def bringup(
    cfg: MultiNodeConfig,
    runtime=None,
    *,
    timeout: float = 120.0,
    _initialize=None,  # test seam: replaces jax.distributed.initialize
) -> str | None:
    """Rendezvous (if needed) and initialize the global device runtime.

    Returns the coordinator address in use (None for single-node). After this
    returns, ``jax.devices()`` on every node is the same global list and any
    mesh built from it spans the hosts.
    """
    if not cfg.is_multi_node:
        return None
    import jax

    initialize = _initialize or jax.distributed.initialize

    if cfg.is_leader:
        addr = cfg.leader_addr or _pick_coordinator_addr()
        if runtime is not None:
            # Publish the coordinator address and wait for every follower's
            # check-in (they check in *before* their own initialize, so the
            # leader reaches its blocking initialize only once all ranks are
            # about to connect — linear control flow, lease-bound slots).
            from dynamo_tpu.runtime.barrier import leader_barrier

            await leader_barrier(
                runtime, BARRIER_NAME, {"leader_addr": addr, "num_nodes": cfg.num_nodes},
                num_workers=cfg.num_nodes - 1, timeout=timeout,
            )
        elif cfg.leader_addr is None:
            raise ValueError("leader needs --leader-addr or a runtime store for rendezvous")
    else:
        addr = cfg.leader_addr
        if addr is None:
            if runtime is None:
                raise ValueError("follower needs --leader-addr or a runtime store for rendezvous")
            from dynamo_tpu.runtime.barrier import worker_barrier

            data = await worker_barrier(runtime, BARRIER_NAME, f"rank-{cfg.node_rank}", timeout=timeout)
            addr = data["leader_addr"]
            if data["num_nodes"] != cfg.num_nodes:
                raise ValueError(
                    f"rank {cfg.node_rank}: leader expects {data['num_nodes']} nodes, "
                    f"this process was launched with {cfg.num_nodes}"
                )

    logger.info("jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
                addr, cfg.num_nodes, cfg.node_rank)
    # Blocks until every rank has connected to the coordinator.
    initialize(
        coordinator_address=addr,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank,
    )
    return addr
