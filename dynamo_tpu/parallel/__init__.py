"""Parallelism: device meshes, GSPMD shardings, sequence parallelism.

The reference delegates intra-model parallelism to wrapped engines (NCCL
TP/PP/EP inside vLLM/SGLang — SURVEY.md §2 parallelism table). Here it is
first-class and XLA-native: annotate parameter/cache shardings over a named
mesh and let GSPMD insert the collectives over ICI.

- :mod:`dynamo_tpu.parallel.mesh` — mesh axes (``dp``, ``tp``, ``sp``, ``ep``)
  and topology helpers.
- :mod:`dynamo_tpu.parallel.sharding` — sharding rules for model params,
  paged KV cache, and activations (megatron-style TP: attention heads and
  MLP hidden sharded on ``tp``; experts on ``ep``; batch on ``dp``).
- :mod:`dynamo_tpu.parallel.ring` — ring attention over the ``sp`` axis for
  long-context prefill (shard_map + ppermute), absent from the reference
  (SURVEY.md §5) but first-class here.
"""

from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh
from dynamo_tpu.parallel.sharding import shard_params, cache_shardings, param_shardings

__all__ = ["MeshPlan", "make_mesh", "shard_params", "cache_shardings", "param_shardings"]
