"""Fleet control tower: a terminal dashboard over the frontend's debug plane.

``python -m dynamo_tpu.top [--url http://host:port] [--once] [--interval S]``

Polls three frontend surfaces and renders one consolidated frame:

- ``GET /metrics`` — the federated Prometheus document (frontend registry
  plus every worker's engine registry), from which we pull throughput, SLO
  attainment and burn rates, active alerts, per-worker queue depths, active
  anomalies, and the lost-time ledger's top causes.
- ``GET /debug/incidents`` — the fleet-wide incident bundle listing.
- ``GET /debug/federation`` — per-worker scrape-failure counters and the
  most recent failure detail.

``--once`` renders a single frame and exits (used by tests and for piping
into files); without it the screen refreshes every ``--interval`` seconds
until interrupted. The tower is read-only — it never mutates fleet state.
"""

from __future__ import annotations

import argparse
import asyncio
import re
import sys
import time
from collections import defaultdict
from typing import Any

# One exposition-format sample: name, optional {label="value",...}, value.
_SAMPLE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)")
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> list[tuple[str, dict[str, str], float]]:
    """Parse Prometheus text exposition into (name, labels, value) samples.

    Tolerant by design: comment/blank lines are skipped and unparseable
    values (e.g. ``NaN`` renders fine via float, but garbage doesn't) drop
    the sample rather than raising — the tower must render whatever a
    half-healthy fleet serves.
    """
    samples: list[tuple[str, dict[str, str], float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = dict(_LABEL.findall(raw_labels)) if raw_labels else {}
        samples.append((name, labels, value))
    return samples


class FleetSnapshot:
    """One poll of the frontend: parsed metrics + incident/federation JSON."""

    def __init__(
        self,
        samples: list[tuple[str, dict[str, str], float]],
        incidents: dict[str, Any] | None,
        federation: dict[str, Any] | None,
        errors: list[str],
    ) -> None:
        self.samples = samples
        self.incidents = incidents or {}
        self.federation = federation or {}
        self.errors = errors

    def value(self, name: str, **labels: str) -> float | None:
        for n, lab, v in self.samples:
            if n == name and all(lab.get(k) == want for k, want in labels.items()):
                return v
        return None

    def by_label(self, name: str, key: str) -> dict[str, float]:
        out: dict[str, float] = {}
        for n, lab, v in self.samples:
            if n == name and key in lab:
                out[lab[key]] = v
        return out

    def workers(self) -> list[str]:
        seen = {lab["worker"] for _, lab, _ in self.samples if "worker" in lab}
        return sorted(seen)


async def poll(url: str, *, timeout: float = 5.0) -> FleetSnapshot:
    import aiohttp

    errors: list[str] = []
    samples: list[tuple[str, dict[str, str], float]] = []
    incidents: dict[str, Any] | None = None
    federation: dict[str, Any] | None = None
    client_timeout = aiohttp.ClientTimeout(total=timeout)
    async with aiohttp.ClientSession(timeout=client_timeout) as session:
        try:
            async with session.get(f"{url}/metrics") as resp:
                samples = parse_prometheus(await resp.text())
        except Exception as exc:
            errors.append(f"/metrics: {type(exc).__name__}: {exc}")
        try:
            async with session.get(f"{url}/debug/incidents") as resp:
                if resp.status == 200:
                    incidents = await resp.json()
        except Exception as exc:
            errors.append(f"/debug/incidents: {type(exc).__name__}: {exc}")
        try:
            async with session.get(f"{url}/debug/federation") as resp:
                if resp.status == 200:
                    federation = await resp.json()
        except Exception as exc:
            errors.append(f"/debug/federation: {type(exc).__name__}: {exc}")
    return FleetSnapshot(samples, incidents, federation, errors)


def _fmt_age(ts: float | None, now: float) -> str:
    if not ts:
        return "-"
    age = max(0.0, now - ts)
    if age < 120:
        return f"{age:.0f}s ago"
    if age < 7200:
        return f"{age / 60:.0f}m ago"
    return f"{age / 3600:.1f}h ago"


def render(snap: FleetSnapshot, *, url: str, now: float | None = None) -> str:
    now = time.time() if now is None else now
    lines: list[str] = []
    lines.append(
        f"dynamo-tpu fleet control tower  {url}  "
        f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(now))}"
    )
    lines.append("=" * 78)
    for err in snap.errors:
        lines.append(f"  !! {err}")

    # --- SLO / throughput -------------------------------------------------
    out_tok = snap.value("dynamo_output_tokens_total")
    good_tok = snap.value("dynamo_goodput_tokens_total")
    attain = snap.value("dynamo_slo_attainment_ratio")
    lines.append("slo")
    lines.append(
        f"  output tokens {out_tok if out_tok is not None else '-':>12}"
        f"   goodput tokens {good_tok if good_tok is not None else '-':>12}"
        f"   attainment {f'{attain:.3f}' if attain is not None else '-':>7}"
    )
    burns = snap.by_label("dynamo_slo_burn_rate", "window")
    if burns:
        burn_txt = "   ".join(f"{w} burn {v:.2f}x" for w, v in sorted(burns.items()))
        lines.append(f"  {burn_txt}")

    # --- alerts -----------------------------------------------------------
    active = {k: v for k, v in snap.by_label("dynamo_alert_active", "kind").items() if v}
    fired = snap.by_label("dynamo_alert_fired_total", "kind")
    lines.append("alerts")
    if active:
        for kind in sorted(active):
            lines.append(f"  FIRING {kind}  (fired {fired.get(kind, 0):.0f}x total)")
    else:
        total_fired = sum(fired.values())
        lines.append(f"  none active  ({total_fired:.0f} fired total)")

    # --- store HA ---------------------------------------------------------
    roles = {k: v for k, v in snap.by_label("dynamo_store_role", "role").items() if v}
    epoch = snap.value("dynamo_store_epoch")
    lag = snap.value("dynamo_store_replication_lag_seconds")
    failovers = snap.value("dynamo_store_failovers_total")
    retries = snap.value("dynamo_store_client_op_retries_total")
    resyncs = snap.value("dynamo_router_index_resyncs_total")
    lines.append("store")
    role = next(iter(sorted(roles)), "-")
    lines.append(
        f"  role {role:<9} epoch {f'{epoch:.0f}' if epoch is not None else '-':>4}"
        f"   repl lag {f'{lag:.3f}s' if lag is not None else '-':>8}"
        f"   failovers {f'{failovers:.0f}' if failovers is not None else '-':>3}"
        f"   op retries {f'{retries:.0f}' if retries is not None else '-':>3}"
        f"   index resyncs {f'{resyncs:.0f}' if resyncs is not None else '-':>3}"
    )

    # --- per-worker -------------------------------------------------------
    running = snap.by_label("dynamo_engine_requests_running", "worker")
    waiting = snap.by_label("dynamo_engine_requests_waiting", "worker")
    anomalies: dict[str, list[str]] = defaultdict(list)
    for n, lab, v in snap.samples:
        if n == "dynamo_anomaly_active" and v and "worker" in lab and "kind" in lab:
            anomalies[lab["worker"]].append(lab["kind"])
    workers = sorted(set(running) | set(waiting) | set(anomalies))
    lines.append(f"workers ({len(workers)})")
    for w in workers:
        anom = ",".join(sorted(anomalies.get(w, []))) or "-"
        lines.append(
            f"  {w:<18} running {running.get(w, 0):>5.0f}"
            f"  waiting {waiting.get(w, 0):>5.0f}  anomalies {anom}"
        )
    if not workers:
        lines.append("  (no worker registries federated yet)")

    # --- lost time --------------------------------------------------------
    lost: dict[str, float] = defaultdict(float)
    for n, lab, v in snap.samples:
        # Exact sample name: the Counter family also emits a unix-epoch
        # `..._created` sample per label set, which must not be summed.
        if n == "dynamo_engine_lost_time_seconds_total" and "cause" in lab:
            lost[lab["cause"]] += v
    lines.append("lost time (top causes, fleet-wide)")
    if lost:
        for cause, secs in sorted(lost.items(), key=lambda kv: -kv[1])[:6]:
            lines.append(f"  {cause:<28} {secs:>9.3f}s")
    else:
        lines.append("  (no lost-time ledger samples)")

    # --- roofline ---------------------------------------------------------
    # Device-cost plane: achieved fraction of the chip's peak per worker
    # and step kind, with which resource binds (memory vs compute).
    roofline: list[tuple[str, str, str, float]] = []
    for n, lab, v in snap.samples:
        if n == "dynamo_engine_roofline_frac" and "step_kind" in lab:
            roofline.append(
                (lab.get("worker", "?"), lab["step_kind"], lab.get("bound", "?"), v)
            )
    lines.append("roofline (frac of chip peak, by step kind)")
    if roofline:
        for worker, step_kind, bound, frac in sorted(roofline)[:8]:
            bar = "#" * int(min(1.0, max(0.0, frac)) * 20)
            lines.append(
                f"  {worker:<18} {step_kind:<12} {frac:>6.3f} [{bar:<20}] {bound}-bound"
            )
    else:
        lines.append("  (no cost-plane samples; DYN_COST_PLANE=0 or no steps yet)")

    # --- federation health ------------------------------------------------
    failures = snap.by_label("dynamo_federation_scrape_failures_total", "worker")
    fed_failures = snap.federation.get("failures") or {}
    merged = dict(fed_failures)
    for w, v in failures.items():
        merged[w] = max(float(merged.get(w, 0)), v)
    lines.append("federation")
    if merged:
        for w in sorted(merged):
            lines.append(f"  {w:<18} scrape failures {merged[w]:>6.0f}")
    else:
        lines.append("  no scrape failures")
    last = snap.federation.get("last_failure")
    if last:
        lines.append(
            f"  last: worker={last.get('worker', '?')} endpoint={last.get('endpoint', '?')}"
            f" {last.get('error', '?')} ({_fmt_age(last.get('ts'), now)})"
        )

    # --- incidents --------------------------------------------------------
    items = snap.incidents.get("incidents") or []
    lines.append(f"incidents ({snap.incidents.get('count', len(items))} on disk)")
    for item in sorted(items, key=lambda i: i.get("ts", 0), reverse=True)[:5]:
        trigger = item.get("trigger") or {}
        what = trigger.get("anomaly") or trigger.get("alert") or trigger.get("error") or ""
        lines.append(
            f"  {item.get('id', '?'):<34} {item.get('kind', '?'):<9}"
            f" {item.get('worker', '?'):<14} {what:<22} {_fmt_age(item.get('ts'), now)}"
        )
    if not items:
        lines.append("  none captured")
    return "\n".join(lines)


async def run(url: str, *, once: bool, interval: float) -> int:
    while True:
        snap = await poll(url)
        frame = render(snap, url=url)
        if once:
            print(frame)
            # Only connection-level failure of every surface is an error;
            # partial degradation still renders (and reports) fine.
            return 1 if len(snap.errors) >= 3 else 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        await asyncio.sleep(interval)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.top",
        description="Terminal control tower over a dynamo-tpu frontend.",
    )
    parser.add_argument(
        "--url", default="http://127.0.0.1:8000", help="frontend base URL"
    )
    parser.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    parser.add_argument(
        "--interval", type=float, default=2.0, help="refresh period in seconds"
    )
    args = parser.parse_args(argv)
    url = args.url.rstrip("/")
    try:
        return asyncio.run(run(url, once=args.once, interval=args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
