"""SentencePiece ``tokenizer.model`` support without the sentencepiece library.

A SentencePiece model file is a serialized ``ModelProto``. This module
implements just enough protobuf wire-format decoding to extract the pieces
(text, score, type), the trainer's model type (unigram vs BPE), and the
normalizer's dummy-prefix flag — then rebuilds an equivalent fast tokenizer
with the ``tokenizers`` library:

- unigram models -> ``tokenizers.models.Unigram`` (same Viterbi semantics)
- BPE models -> ``tokenizers.models.BPE`` with merges reconstructed from the
  vocab (a pair (l, r) is a merge iff l+r is a piece; priority = the merged
  piece's score, ties to shorter pieces), the standard slow->fast conversion.

Parity: reference tokenizer stack accepts SentencePiece artifacts alongside
tokenizer.json (`lib/llm/src/tokenizers.rs`; TokenizerKind GGUF/HF/SPM);
SURVEY §2 row 21 flags SentencePiece as the missing kind here.
"""

from __future__ import annotations

import pathlib
import struct
from typing import Any, Iterator

# piece types (sentencepiece_model.proto SentencePiece.Type)
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6

_UNIGRAM, _BPE = 1, 2


class ProtoError(ValueError):
    pass


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ProtoError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ProtoError("varint too long")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) triples of one message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:  # 64-bit
            value = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            value = buf[pos : pos + n]
            if len(value) != n:
                raise ProtoError("truncated length-delimited field")
            pos += n
        elif wire == 5:  # 32-bit
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise ProtoError(f"unsupported wire type {wire}")
        yield field, wire, value


class SentencePieceModel:
    """Parsed ModelProto: pieces + the handful of specs that matter."""

    def __init__(self, data: bytes) -> None:
        self.pieces: list[tuple[str, float, int]] = []  # (text, score, type)
        self.model_type = _UNIGRAM
        self.unk_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.add_dummy_prefix = True
        for field, _wire, value in _fields(data):
            if field == 1:  # repeated SentencePiece
                self.pieces.append(self._parse_piece(value))
            elif field == 2:  # TrainerSpec
                self._parse_trainer(value)
            elif field == 3:  # NormalizerSpec
                self._parse_normalizer(value)
        if not self.pieces:
            raise ProtoError("no pieces in SentencePiece model")
        # ids may also be derivable from piece types when TrainerSpec omits them
        for i, (_text, _score, ptype) in enumerate(self.pieces):
            if ptype == UNKNOWN:
                self.unk_id = i
                break

    @staticmethod
    def _parse_piece(buf: bytes) -> tuple[str, float, int]:
        text, score, ptype = "", 0.0, NORMAL
        for field, wire, value in _fields(buf):
            if field == 1 and wire == 2:
                text = value.decode("utf-8")
            elif field == 2 and wire == 5:
                (score,) = struct.unpack("<f", value)
            elif field == 3 and wire == 0:
                ptype = value
        return text, score, ptype

    def _parse_trainer(self, buf: bytes) -> None:
        def signed(v: int) -> int:  # ids are int32; -1 means "disabled"
            return v - (1 << 64) if v >= (1 << 63) else v

        for field, wire, value in _fields(buf):
            if field == 3 and wire == 0:  # model_type
                self.model_type = value
            elif field == 40 and wire == 0:  # unk_id
                self.unk_id = signed(value)
            elif field == 41 and wire == 0:  # bos_id
                self.bos_id = signed(value)
            elif field == 42 and wire == 0:  # eos_id
                self.eos_id = signed(value)

    def _parse_normalizer(self, buf: bytes) -> None:
        for field, wire, value in _fields(buf):
            if field == 3 and wire == 0:  # add_dummy_prefix
                self.add_dummy_prefix = bool(value)


def _bpe_merges(vocab: dict[str, int], scores: dict[str, float]) -> list[tuple[str, str]]:
    """Reconstruct merge order from a BPE-type piece list.

    Every piece that splits into two in-vocab halves was produced by a merge;
    the trainer assigned higher scores to earlier merges, so sorting by
    (-score, len) recovers a priority order equivalent to the original."""
    merges: list[tuple[float, int, str, str]] = []
    for piece in vocab:
        if len(piece) < 2:
            continue
        best = None
        for i in range(1, len(piece)):
            l, r = piece[:i], piece[i:]
            if l in vocab and r in vocab:
                cand = (scores[l] + scores[r], l, r)
                if best is None or cand[0] > best[0]:
                    best = cand
        if best is not None:
            merges.append((scores[piece], len(piece), best[1], best[2]))
    merges.sort(key=lambda m: (-m[0], m[1]))
    return [(l, r) for _s, _n, l, r in merges]


def build_tokenizer(model: SentencePieceModel):
    """SentencePieceModel -> BaseTokenizer (fast tokenizers backend)."""
    from tokenizers import AddedToken, Tokenizer, decoders, models, pre_tokenizers

    from dynamo_tpu.tokenizer import HfTokenizer

    pieces = model.pieces
    prepend = "first" if model.add_dummy_prefix else "never"
    if model.model_type == _BPE:
        vocab = {text: i for i, (text, _s, _t) in enumerate(pieces)}
        scores = {text: s for text, s, _t in pieces}
        unk_text = pieces[model.unk_id][0] if 0 <= model.unk_id < len(pieces) else None
        tk = Tokenizer(
            models.BPE(
                vocab=vocab,
                merges=_bpe_merges(vocab, scores),
                unk_token=unk_text,
                fuse_unk=True,
                byte_fallback=any(t == BYTE for _p, _s, t in pieces),
            )
        )
    else:
        tk = Tokenizer(
            models.Unigram(
                [(text, score) for text, score, _t in pieces],
                unk_id=model.unk_id,
                byte_fallback=any(t == BYTE for _p, _s, t in pieces),
            )
        )
    tk.pre_tokenizer = pre_tokenizers.Metaspace(replacement="▁", prepend_scheme=prepend)
    tk.decoder = decoders.Sequence(
        [decoders.Replace("▁", " "), decoders.ByteFallback(), decoders.Fuse(), decoders.Strip(" ", 1, 0)]
    )
    specials = [
        AddedToken(text, special=True, normalized=False)
        for text, _s, t in pieces
        if t == CONTROL
    ]
    if specials:
        tk.add_special_tokens(specials)
    eos_ids = {model.eos_id} if 0 <= model.eos_id < len(pieces) else None
    bos = model.bos_id if 0 <= model.bos_id < len(pieces) else None
    return HfTokenizer(tk, eos_token_ids=eos_ids, bos_token_id=bos)


def load_sentencepiece(path: str | pathlib.Path):
    """tokenizer.model path -> BaseTokenizer."""
    return build_tokenizer(SentencePieceModel(pathlib.Path(path).read_bytes()))


# ---------------------------------------------------------------------------
# Writer (tests / artifact tooling): pieces -> serialized ModelProto
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    v &= (1 << 64) - 1  # protobuf encodes negatives as 64-bit two's complement
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def write_model(
    pieces: list[tuple[str, float, int]],
    *,
    model_type: str = "unigram",
    unk_id: int = 0,
    bos_id: int = 1,
    eos_id: int = 2,
    add_dummy_prefix: bool = True,
) -> bytes:
    """Serialize a minimal, spec-conformant ModelProto."""
    out = bytearray()
    for text, score, ptype in pieces:
        body = bytearray()
        raw = text.encode("utf-8")
        body += _tag(1, 2) + _varint(len(raw)) + raw
        body += _tag(2, 5) + struct.pack("<f", score)
        body += _tag(3, 0) + _varint(ptype)
        out += _tag(1, 2) + _varint(len(body)) + bytes(body)
    trainer = bytearray()
    trainer += _tag(3, 0) + _varint(_BPE if model_type == "bpe" else _UNIGRAM)
    trainer += _tag(40, 0) + _varint(unk_id)
    trainer += _tag(41, 0) + _varint(bos_id)
    trainer += _tag(42, 0) + _varint(eos_id)
    out += _tag(2, 2) + _varint(len(trainer)) + bytes(trainer)
    normalizer = bytearray()
    normalizer += _tag(3, 0) + _varint(1 if add_dummy_prefix else 0)
    out += _tag(3, 2) + _varint(len(normalizer)) + bytes(normalizer)
    return bytes(out)
