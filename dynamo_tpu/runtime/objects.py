"""Object store: chunked, checksummed blobs over the KeyValueStore.

The reference distributes model-card artifacts (tokenizer files, prompt
templates) through the NATS object store (`model_card/model.rs:230-326`
``move_to_nats``/``move_from_nats``). Here the same role rides the
deployment's existing KeyValueStore: an object is a metadata record plus
fixed-size chunk entries, so any worker joined to the store can fetch a
card's artifacts without shared filesystems. Chunking keeps single values
within the TCP store codec's comfort zone; a sha256 in the metadata makes
partial/overwritten uploads detectable at read time.

URLs: ``object://<name>`` — `ModelDeploymentCard.move_to_store` rewrites
artifact paths to these, `resolve_from_store` materializes them back to
local files (worker-side cache dir).
"""

from __future__ import annotations

import hashlib
import json
import logging
import pathlib
from typing import Any

from dynamo_tpu.runtime.discovery import KeyValueStore

logger = logging.getLogger(__name__)

OBJECT_PREFIX = "objects/"
DEFAULT_CHUNK = 256 * 1024
URL_SCHEME = "object://"


class ObjectError(RuntimeError):
    pass


class ObjectStore:
    def __init__(self, store: KeyValueStore, *, chunk_size: int = DEFAULT_CHUNK) -> None:
        self.store = store
        self.chunk_size = chunk_size

    @staticmethod
    def _meta_key(name: str) -> str:
        return f"{OBJECT_PREFIX}{name}/meta"

    @staticmethod
    def _chunk_key(name: str, i: int) -> str:
        return f"{OBJECT_PREFIX}{name}/chunk/{i:08d}"

    async def put(self, name: str, data: bytes, *, metadata: dict[str, Any] | None = None) -> str:
        """Store ``data``; returns the object URL. Overwrites atomically
        enough for this plane: meta is written last, so readers either see
        the old complete object or the new one (chunk counts validated)."""
        digest = hashlib.sha256(data).hexdigest()
        n_chunks = max(1, -(-len(data) // self.chunk_size))
        old_meta = await self.stat(name)
        for i in range(n_chunks):
            chunk = data[i * self.chunk_size : (i + 1) * self.chunk_size]
            await self.store.put(self._chunk_key(name, i), chunk)
        meta = {
            "size": len(data),
            "sha256": digest,
            "chunks": n_chunks,
            "chunk_size": self.chunk_size,
            **({"metadata": metadata} if metadata else {}),
        }
        await self.store.put(self._meta_key(name), json.dumps(meta).encode())
        # An overwrite with fewer chunks would otherwise orphan the old tail.
        if old_meta is not None:
            for i in range(n_chunks, int(old_meta.get("chunks", 0))):
                await self.store.delete(self._chunk_key(name, i))
        logger.info("object %s stored (%d bytes, %d chunks)", name, len(data), n_chunks)
        return URL_SCHEME + name

    async def get(self, name: str) -> bytes:
        raw_meta = await self.store.get(self._meta_key(name))
        if raw_meta is None:
            raise ObjectError(f"object {name!r} not found")
        meta = json.loads(raw_meta)
        parts: list[bytes] = []
        for i in range(int(meta["chunks"])):
            chunk = await self.store.get(self._chunk_key(name, i))
            if chunk is None:
                raise ObjectError(f"object {name!r} missing chunk {i} (partial upload?)")
            parts.append(chunk)
        data = b"".join(parts)[: int(meta["size"])]
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta["sha256"]:
            raise ObjectError(f"object {name!r} checksum mismatch (concurrent overwrite?)")
        return data

    async def stat(self, name: str) -> dict[str, Any] | None:
        raw = await self.store.get(self._meta_key(name))
        return json.loads(raw) if raw is not None else None

    async def delete(self, name: str) -> bool:
        meta = await self.stat(name)
        if meta is None:
            return False
        await self.store.delete(self._meta_key(name))
        for i in range(int(meta["chunks"])):
            await self.store.delete(self._chunk_key(name, i))
        return True

    async def put_file(self, name: str, path: str | pathlib.Path) -> str:
        return await self.put(name, pathlib.Path(path).read_bytes())

    async def get_to_file(self, name: str, path: str | pathlib.Path) -> pathlib.Path:
        p = pathlib.Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(await self.get(name))
        return p


def is_object_url(value: str | None) -> bool:
    return bool(value) and str(value).startswith(URL_SCHEME)


def object_name(url: str) -> str:
    if not is_object_url(url):
        raise ObjectError(f"not an object url: {url!r}")
    return url[len(URL_SCHEME) :]
