"""Hierarchical component model: Namespace -> Component -> Endpoint -> Instance.

An *instance* is one live served endpoint, identified by the lease id of the
process serving it; its discovery record carries the transport address of its
stream server. Liveness is the lease: when a worker dies, its lease expires,
its instance records vanish, and every watching client drops it from rotation
— membership is fully dynamic with no explicit deregistration needed.

Parity: reference `lib/runtime/src/component.rs:106-419` (addressing), etcd
instance path scheme `component.rs:69` and NATS subject scheme
`component.rs:380-391`, DistributedRuntime `lib/runtime/src/distributed.rs`.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
from dataclasses import dataclass, field
from typing import Any

from dynamo_tpu.runtime.discovery import DEFAULT_LEASE_TTL, KeyValueStore, Lease, MemoryStore
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.transport import InMemoryTransport, Transport

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"^[a-zA-Z0-9_-]+$")

INSTANCE_PREFIX = "instances"
MODEL_PREFIX = "models"


def _validate_name(name: str, kind: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid {kind} name {name!r}: must match [a-zA-Z0-9_-]+")
    return name


@dataclass(frozen=True)
class Instance:
    """One live served endpoint (discovery record)."""

    namespace: str
    component: str
    endpoint: str
    lease_id: int
    address: str  # transport address, e.g. tcp://host:port/subject or mem://subject
    metadata: dict[str, Any] = field(default_factory=dict, hash=False, compare=False)

    @property
    def instance_id(self) -> int:
        return self.lease_id

    @property
    def key(self) -> str:
        return instance_key(self.namespace, self.component, self.endpoint, self.lease_id)

    @property
    def subject(self) -> str:
        return instance_subject(self.namespace, self.component, self.endpoint, self.lease_id)

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "lease_id": self.lease_id,
                "address": self.address,
                "metadata": self.metadata,
            }
        ).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Instance":
        obj = json.loads(data)
        return cls(**obj)


def instance_prefix(namespace: str, component: str, endpoint: str) -> str:
    return f"{INSTANCE_PREFIX}/{namespace}/{component}/{endpoint}:"


def instance_key(namespace: str, component: str, endpoint: str, lease_id: int) -> str:
    return f"{instance_prefix(namespace, component, endpoint)}{lease_id:x}"


def instance_subject(namespace: str, component: str, endpoint: str, lease_id: int) -> str:
    return f"{namespace}.{component}.{endpoint}-{lease_id:x}"


class DistributedRuntime:
    """Cluster handle: discovery store + stream transport + primary lease.

    ``DistributedRuntime.detached()`` gives a fully in-process runtime (memory
    store + in-memory transport) — the default for single-node serving and
    tests. Multi-process deployments pass a TCP store client and TcpTransport.
    """

    def __init__(
        self,
        store: KeyValueStore | None = None,
        transport: Transport | None = None,
        *,
        lease_ttl: float = DEFAULT_LEASE_TTL,
    ) -> None:
        self.store = store if store is not None else MemoryStore()
        self.transport = transport if transport is not None else InMemoryTransport()
        self._lease_ttl = lease_ttl
        self._primary_lease: Lease | None = None
        self._keepalive_task: asyncio.Task | None = None
        self._secondary_tasks: list[asyncio.Task] = []
        self._served: list[tuple[str, str]] = []  # (subject, key)
        self._closed = False

    @classmethod
    def detached(cls) -> "DistributedRuntime":
        return cls(MemoryStore(), InMemoryTransport())

    # -- leases ------------------------------------------------------------

    async def primary_lease(self) -> Lease:
        if self._primary_lease is None:
            self._primary_lease = await self.store.create_lease(self._lease_ttl)
            self._keepalive_task = asyncio.create_task(self._keepalive_loop(self._primary_lease))
        return self._primary_lease

    async def secondary_lease(self, ttl: float | None = None) -> Lease:
        """An extra kept-alive lease: a distinct instance identity within this
        process (e.g. several engine workers sharing one runtime)."""
        lease = await self.store.create_lease(ttl if ttl is not None else self._lease_ttl)
        self._secondary_tasks.append(asyncio.create_task(self._keepalive_loop(lease)))
        return lease

    async def _keepalive_loop(self, lease: Lease) -> None:
        interval = max(lease.ttl / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                await lease.keep_alive()
            except KeyError:
                logger.error("primary lease %d expired; runtime is no longer discoverable", lease.id)
                return
            except Exception:
                logger.exception("lease keep-alive failed; retrying")

    # -- addressing --------------------------------------------------------

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, _validate_name(name, "namespace"))

    # -- lifecycle ---------------------------------------------------------

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        for t in self._secondary_tasks:
            t.cancel()
        for subject, key in self._served:
            await self.transport.unregister_engine(subject)
            try:
                await self.store.delete(key)
            except Exception:
                pass
        if self._primary_lease is not None:
            try:
                await self._primary_lease.revoke()
            except Exception:
                pass
        await self.transport.close()
        await self.store.close()


@dataclass(frozen=True)
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, _validate_name(name, "component"))


@dataclass(frozen=True)
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, _validate_name(name, "endpoint"))


@dataclass(frozen=True)
class Endpoint:
    runtime: DistributedRuntime
    namespace: str
    component: str
    name: str

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    async def serve(
        self,
        engine: AsyncEngine[Any, Any],
        *,
        metadata: dict[str, Any] | None = None,
        lease: Lease | None = None,
    ) -> Instance:
        """Bind ``engine`` to this endpoint and publish the instance record.

        The record is attached to the (primary) lease: if this process stops
        renewing, the instance disappears cluster-wide within one TTL.
        """
        rt = self.runtime
        if lease is None:
            lease = await rt.primary_lease()
        subject = instance_subject(self.namespace, self.component, self.name, lease.id)
        await rt.transport.register_engine(subject, engine)
        instance = Instance(
            namespace=self.namespace,
            component=self.component,
            endpoint=self.name,
            lease_id=lease.id,
            address=rt.transport.address_of(subject),
            metadata=metadata or {},
        )
        await rt.store.put(instance.key, instance.to_bytes(), lease_id=lease.id)
        rt._served.append((subject, instance.key))
        logger.info("serving %s as instance %x at %s", self.path, lease.id, instance.address)
        return instance

    def client(self, **kwargs: Any) -> "Client":
        from dynamo_tpu.runtime.client import Client

        return Client(self, **kwargs)
