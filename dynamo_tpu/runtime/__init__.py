"""Distributed runtime core.

Capability parity with the reference's `lib/runtime` crate (see SURVEY.md §1
L1/L2), re-designed for asyncio + an in-process/TCP transport pair:

- :mod:`dynamo_tpu.runtime.engine` — the streaming ``AsyncEngine`` abstraction
  and per-request ``Context`` (id / stop / kill lifecycle).
- :mod:`dynamo_tpu.runtime.discovery` — pluggable key-value discovery store
  with TTL leases, prefix watch (etcd-equivalent; in-memory and TCP-served).
- :mod:`dynamo_tpu.runtime.transport` — the request/response data plane
  (broker-free: direct streams with a two-part codec).
- :mod:`dynamo_tpu.runtime.component` — hierarchical addressing:
  Namespace -> Component -> Endpoint -> Instance(lease_id).
- :mod:`dynamo_tpu.runtime.client` — endpoint clients with instance watching
  and router modes (round-robin / random / direct / KV).
"""

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineError
from dynamo_tpu.runtime.discovery import (
    KeyValueStore,
    Lease,
    MemoryStore,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.component import DistributedRuntime, Instance

__all__ = [
    "AsyncEngine",
    "Context",
    "EngineError",
    "KeyValueStore",
    "Lease",
    "MemoryStore",
    "WatchEvent",
    "WatchEventType",
    "DistributedRuntime",
    "Instance",
]
