"""Logging setup: human-readable or JSONL, driven by env toggles.

Parity: the reference's tracing-subscriber init (`lib/runtime/src/
logging.rs:100-268`) with its env switches (`config.rs:163-176`):

- ``DYN_LOGGING_JSONL=1``      -> one JSON object per line (ts, level,
  logger, message, plus any ``extra={...}`` fields flattened in).
- ``DYN_LOG_LEVEL=DEBUG``      -> root level (default INFO).
- ``DYN_LOG_USE_LOCAL_TZ=1``   -> local-time timestamps (default UTC).
- ``DYN_SDK_DISABLE_ANSI_LOGGING=1`` -> no color in the text format.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import sys

_RESERVED = set(logging.LogRecord("", 0, "", 0, "", (), None).__dict__) | {
    "message", "asctime", "taskName"
}

_LEVEL_COLOR = {"DEBUG": "\x1b[36m", "INFO": "\x1b[32m", "WARNING": "\x1b[33m",
                "ERROR": "\x1b[31m", "CRITICAL": "\x1b[35m"}
_RESET = "\x1b[0m"


class JsonlFormatter(logging.Formatter):
    """One JSON object per line; record ``extra`` fields are flattened in
    (the span-field capture role of the reference's JSONL mode)."""

    def __init__(self, *, local_tz: bool = False) -> None:
        super().__init__()
        self._tz = None if local_tz else datetime.timezone.utc

    def format(self, record: logging.LogRecord) -> str:
        ts = datetime.datetime.fromtimestamp(record.created, tz=self._tz)
        doc = {
            "time": ts.isoformat(),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                doc[k] = v
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=str)


class TraceContextFilter(logging.Filter):
    """Stamp the active span's trace identity onto every log record.

    Any log line emitted while a :class:`~dynamo_tpu.tracing.Span` is open in
    the current task/thread gains ``trace_id``/``span_id`` fields (flattened
    into JSONL output), so engine log lines correlate with
    ``GET /debug/traces/{id}`` timelines without grepping timestamps.
    Records that already carry a ``trace_id`` (spans log their own) keep it.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "trace_id"):
            try:
                from dynamo_tpu.tracing import current_span

                span = current_span()
            except Exception:
                span = None
            if span is not None:
                record.trace_id = span.trace_id
                record.span_id = span.span_id
        return True


class TextFormatter(logging.Formatter):
    def __init__(self, *, ansi: bool = True, local_tz: bool = False) -> None:
        super().__init__("%(asctime)s %(levelname)s %(name)s %(message)s")
        self._ansi = ansi
        self._tz = None if local_tz else datetime.timezone.utc

    def formatTime(self, record, datefmt=None):  # noqa: N802 (stdlib API)
        return datetime.datetime.fromtimestamp(record.created, tz=self._tz).isoformat(timespec="milliseconds")

    def format(self, record: logging.LogRecord) -> str:
        out = super().format(record)
        if self._ansi and record.levelname in _LEVEL_COLOR:
            out = f"{_LEVEL_COLOR[record.levelname]}{out}{_RESET}"
        return out


def setup_logging(
    *,
    jsonl: bool | None = None,
    level: str | None = None,
    env: dict[str, str] | None = None,
    stream=None,
) -> logging.Handler:
    """Install the root handler; returns it (tests inspect).

    Explicit ``jsonl``/``level`` (e.g. from the RuntimeSettings cascade) win;
    otherwise the reference-named env toggles apply."""
    from dynamo_tpu.config import env_flag

    env = os.environ if env is None else env
    if jsonl is None:
        jsonl = env_flag(env, "DYN_LOGGING_JSONL")
    local_tz = env_flag(env, "DYN_LOG_USE_LOCAL_TZ")
    no_ansi = env_flag(env, "DYN_SDK_DISABLE_ANSI_LOGGING")
    level = (level or env.get("DYN_LOG_LEVEL", "INFO")).upper()

    handler = logging.StreamHandler(stream or sys.stderr)
    handler.addFilter(TraceContextFilter())
    if jsonl:
        handler.setFormatter(JsonlFormatter(local_tz=local_tz))
    else:
        ansi = not no_ansi and getattr(handler.stream, "isatty", lambda: False)()
        handler.setFormatter(TextFormatter(ansi=ansi, local_tz=local_tz))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level, logging.INFO))
    return handler
