"""Deterministic fault injection for the serving stack.

A process-wide registry of *named fault points* threaded through the hot
seams of the stack (TCP connect/read/write, store ops, lease keep-alive,
engine step, KV-chunk send/recv, prefill execution). Chaos tests — and
operators reproducing an incident — arm faults with a compact spec and the
affected call sites fail deterministically; with nothing armed, every
injection site costs exactly one attribute check (``if FAULTS.armed:``), so
the plane is free on the hot path.

Grammar (``DYN_FAULTS`` env var or :meth:`FaultRegistry.arm`)::

    DYN_FAULTS="tcp.connect:drop@0.5,kv.chunk.send:corrupt@1,engine.step:crash@3"

Comma-separated ``point:action[@spec]`` entries:

- ``point`` — a key of :data:`FAULT_POINTS` (unknown points are rejected at
  arm time, so a typo fails loudly instead of silently never firing).
- ``action`` — ``drop`` raises :class:`DropFault` (a ``ConnectionError``);
  ``crash`` raises :class:`CrashFault` (a ``RuntimeError``); ``corrupt``
  returns ``"corrupt"`` from :meth:`FaultRegistry.fire` and the call site
  mutates its payload; ``delay`` sleeps ``DYN_FAULTS_DELAY_S`` (default
  0.05s) and returns ``"delay"``.
- ``spec`` — when omitted, the fault fires on every call. ``@N`` (int)
  fires on the Nth call only (1-based). ``@N+`` fires on every call from
  the Nth. ``@p`` with ``0 < p < 1`` fires with probability ``p`` from a
  per-point PRNG seeded by ``DYN_FAULTS_SEED`` (default 0) — same seed,
  same firing sequence, every run.

Determinism is the point: a chaos scenario that kills the third engine step
kills the third engine step on every machine, every time.
"""

from __future__ import annotations

import logging
import os
import random
import time
import zlib
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

#: Every named injection point in the stack, with where it lives. ``arm()``
#: validates against this registry and ``tools/check_fault_points.py`` fails
#: CI if any point is never armed by a chaos test.
FAULT_POINTS: dict[str, str] = {
    "tcp.connect": "runtime/tcp.py — caller-side asyncio.open_connection to a worker",
    "tcp.read": "runtime/tcp.py — caller-side response-frame read on the data plane",
    "tcp.write": "runtime/tcp.py — caller-side request-frame write on the data plane",
    "store.op": "runtime/store_server.py — StoreClient request/response call to the store",
    "store.watch": "runtime/discovery.py + store_server.py — per-event delivery on a prefix watch",
    "store.replicate": "runtime/replication.py — follower-side apply of one replicated mutation record",
    "store.promote": "runtime/replication.py — a follower's promotion to store leader",
    "lease.keepalive": "runtime/discovery.py — lease keep-alive refresh",
    "engine.step": "engine/service.py — one engine step in the service loop",
    "kv.chunk.send": "disagg/transfer.py — sender side of one v2 KV chunk",
    "kv.chunk.recv": "disagg/transfer.py — receiver ingest of one KV chunk",
    "prefill.exec": "disagg/prefill_worker.py — execution of one claimed prefill task",
    "sched.admit": "engine/core.py — admission of one waiting request into prefill (SLO sched seam)",
}

_ACTIONS = ("drop", "crash", "corrupt", "delay")


class FaultInjected(Exception):
    """Marker mixin: this exception was raised by the fault plane."""


class DropFault(FaultInjected, ConnectionError):
    """Injected connection-level failure (reads as a network drop)."""


class CrashFault(FaultInjected, RuntimeError):
    """Injected process/step-level failure (reads as a crash)."""


@dataclass
class _Plan:
    """One armed fault: parsed action + firing schedule + counters."""

    point: str
    action: str
    kind: str  # always | once | from | prob
    n: int = 0
    p: float = 0.0
    rng: random.Random | None = None
    calls: int = 0
    fired: int = 0
    raw: str = field(default="")

    def should_fire(self) -> bool:
        self.calls += 1
        if self.kind == "always":
            return True
        if self.kind == "once":
            return self.calls == self.n
        if self.kind == "from":
            return self.calls >= self.n
        assert self.rng is not None
        return self.rng.random() < self.p


def _parse_entry(entry: str, seed: int) -> _Plan:
    head, sep, spec = entry.partition("@")
    point, _, action = head.partition(":")
    point = point.strip()
    action = action.strip()
    if point not in FAULT_POINTS:
        known = ", ".join(sorted(FAULT_POINTS))
        raise ValueError(f"unknown fault point {point!r} (known: {known})")
    if action not in _ACTIONS:
        raise ValueError(f"unknown fault action {action!r} in {entry!r} (known: {_ACTIONS})")
    plan = _Plan(point=point, action=action, kind="always", raw=entry.strip())
    if sep:
        spec = spec.strip()
        if spec.endswith("+"):
            plan.kind, plan.n = "from", int(spec[:-1])
        elif "." in spec:
            p = float(spec)
            if not 0.0 < p < 1.0:
                raise ValueError(f"fault probability must be in (0, 1): {entry!r}")
            plan.kind, plan.p = "prob", p
            # Per-point stream: arming a second fault must not perturb the
            # firing sequence of the first.
            plan.rng = random.Random(seed ^ zlib.crc32(point.encode()))
        else:
            plan.kind, plan.n = "once", int(spec)
        if plan.kind in ("once", "from") and plan.n < 1:
            raise ValueError(f"fault call index is 1-based: {entry!r}")
    return plan


class FaultRegistry:
    """Process-wide fault plane. The hot-path contract is::

        if FAULTS.armed:          # one attribute check when nothing is armed
            FAULTS.fire("tcp.connect")

    ``fire`` raises for ``drop``/``crash`` plans, returns ``"corrupt"`` /
    ``"delay"`` for the call site to act on, and ``None`` when the point has
    no armed plan or the schedule says not this call.
    """

    def __init__(self) -> None:
        self.armed = False
        self._plans: dict[str, _Plan] = {}

    def arm(self, spec: str, *, seed: int | None = None) -> None:
        """Parse and arm ``spec`` (the ``DYN_FAULTS`` grammar). Replaces any
        previously armed plans. Empty spec disarms."""
        if seed is None:
            seed = int(os.environ.get("DYN_FAULTS_SEED", "0"))
        plans: dict[str, _Plan] = {}
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            plan = _parse_entry(entry, seed)
            plans[plan.point] = plan  # last entry per point wins
        self._plans = plans
        self.armed = bool(plans)
        if plans:
            logger.warning("fault plane armed: %s", ", ".join(p.raw for p in plans.values()))

    def disarm(self) -> None:
        self._plans = {}
        self.armed = False

    def fire(self, point: str) -> str | None:
        """Evaluate ``point`` against the armed plans (see class docstring)."""
        plan = self._plans.get(point)
        if plan is None or not plan.should_fire():
            return None
        plan.fired += 1
        logger.warning("fault fired: %s -> %s (call %d)", point, plan.action, plan.calls)
        if plan.action == "drop":
            raise DropFault(f"injected drop at {point} (call {plan.calls})")
        if plan.action == "crash":
            raise CrashFault(f"injected crash at {point} (call {plan.calls})")
        if plan.action == "delay":
            time.sleep(float(os.environ.get("DYN_FAULTS_DELAY_S", "0.05")))
            return "delay"
        return "corrupt"

    def fired(self, point: str) -> int:
        """How many times the plan at ``point`` has fired (0 if unarmed)."""
        plan = self._plans.get(point)
        return plan.fired if plan is not None else 0

    def counts(self) -> dict[str, dict[str, int]]:
        """Per-point ``{calls, fired}`` for armed plans (test introspection)."""
        return {pt: {"calls": p.calls, "fired": p.fired} for pt, p in self._plans.items()}


#: The process-wide registry. Call sites import this binding directly
#: (``from dynamo_tpu.runtime.faults import FAULTS``) so the unarmed check is
#: a single attribute load on a module global.
FAULTS = FaultRegistry()

_env_spec = os.environ.get("DYN_FAULTS", "")
if _env_spec:
    FAULTS.arm(_env_spec)


def corrupt_bytes(buf: bytes) -> bytes:
    """Flip the first byte — the canonical payload mutation for ``corrupt``."""
    if not buf:
        return buf
    return bytes([buf[0] ^ 0xFF]) + buf[1:]


__all__ = [
    "FAULT_POINTS",
    "FAULTS",
    "FaultRegistry",
    "FaultInjected",
    "DropFault",
    "CrashFault",
    "corrupt_bytes",
]
