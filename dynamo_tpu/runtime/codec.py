"""Two-part wire codec for the stream data plane.

Frames are ``[4-byte big-endian length][msgpack body]``. The body always has a
control part (``t`` = frame type, plus routing/identity fields) and an
optional payload part (``p``) — the same split as the reference's
TwoPartCodec (`lib/runtime/src/pipeline/network/codec/two_part.rs`): control
headers small and introspectable, payload opaque.

msgpack (not JSON) keeps the per-token hot path cheap; the payload may carry
raw bytes (e.g. serialized arrays) with no base64 overhead.

Blob frames (wire v3): bulk payloads (KV page bytes) don't belong inside
msgpack — packing them copies every byte once on each side and the unpacker
materialises one more copy. A blob frame keeps the msgpack body as a small
*head* and appends the payload as raw bytes after it:

    [4-byte length | BLOB_FLAG][msgpack head incl. "blob"=body_len][raw body]

The high bit of the length prefix marks the frame as a blob frame; it is
free because ``MAX_FRAME_BYTES`` < 2**31. ``write_blob_frame`` writes the
payload buffers (memoryviews) straight to the socket — no intermediate
concatenation — and ``read_frame`` surfaces the body as ``fields["blob"]``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import Any, Sequence

import msgpack

MAX_FRAME_BYTES = 256 * 1024 * 1024  # hard cap; a corrupt length prefix fails fast
BLOB_FLAG = 0x8000_0000  # high bit of the length prefix marks a blob frame


class FrameType(str, Enum):
    REQUEST = "req"        # caller -> worker: open a stream {subject, id, p}
    #                        + optional "trace" = {trace_id, span_id}: the W3C
    #                        trace context of the calling span, extracted into
    #                        the worker-side Context (distributed tracing)
    PROLOGUE = "pro"       # worker -> caller: stream accepted (or error detail)
    DATA = "dat"           # worker -> caller: one response item
    ERROR = "err"          # worker -> caller: stream failed; terminal
    COMPLETE = "end"       # worker -> caller: stream finished; terminal
    STOP = "stp"           # caller -> worker: stop generating (graceful)
    KILL = "kil"           # caller -> worker: hard-cancel the stream


@dataclass(frozen=True)
class Frame:
    type: FrameType
    fields: dict[str, Any]

    @property
    def payload(self) -> Any:
        return self.fields.get("p")


def encode_frame(ftype: FrameType, **fields: Any) -> bytes:
    body = msgpack.packb({"t": ftype.value, **fields}, use_bin_type=True)
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(body)} bytes")
    return len(body).to_bytes(4, "big") + body


def decode_body(body: bytes) -> Frame:
    obj = msgpack.unpackb(body, raw=False)
    t = obj.pop("t")
    return Frame(type=FrameType(t), fields=obj)


async def read_frame(reader: asyncio.StreamReader) -> Frame | None:
    """Read one frame; None on clean EOF.

    Blob frames come back as a normal :class:`Frame` with the raw body bytes
    under ``fields["blob"]`` (replacing the head's declared body length).
    """
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    prefix = int.from_bytes(header, "big")
    is_blob = bool(prefix & BLOB_FLAG)
    length = prefix & ~BLOB_FLAG
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds cap")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    frame = decode_body(body)
    if is_blob:
        blob_len = frame.fields.get("blob")
        if not isinstance(blob_len, int) or blob_len < 0 or blob_len > MAX_FRAME_BYTES:
            raise ValueError(f"blob frame with bad body length: {blob_len!r}")
        try:
            blob = await reader.readexactly(blob_len)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return None
        frame.fields["blob"] = blob
    return frame


def write_frame(writer: asyncio.StreamWriter, ftype: FrameType, **fields: Any) -> None:
    writer.write(encode_frame(ftype, **fields))


def write_blob_frame(
    writer: asyncio.StreamWriter,
    ftype: FrameType,
    buffers: Sequence[Any],
    **fields: Any,
) -> int:
    """Write ``[prefix|BLOB_FLAG][head][buffers...]`` without concatenating.

    ``buffers`` is a sequence of bytes-like objects (memoryviews of KV pages);
    each is handed to the socket writer as-is, so the only copies are the
    kernel ones. Returns the body byte count.
    """
    body_len = sum(len(b) for b in buffers)
    if body_len > MAX_FRAME_BYTES:
        raise ValueError(f"blob body too large: {body_len} bytes")
    head = msgpack.packb({"t": ftype.value, **fields, "blob": body_len}, use_bin_type=True)
    if len(head) > MAX_FRAME_BYTES:
        raise ValueError(f"frame head too large: {len(head)} bytes")
    writer.write((len(head) | BLOB_FLAG).to_bytes(4, "big"))
    writer.write(head)
    for buf in buffers:
        writer.write(buf)
    return body_len
