"""Two-part wire codec for the stream data plane.

Frames are ``[4-byte big-endian length][msgpack body]``. The body always has a
control part (``t`` = frame type, plus routing/identity fields) and an
optional payload part (``p``) — the same split as the reference's
TwoPartCodec (`lib/runtime/src/pipeline/network/codec/two_part.rs`): control
headers small and introspectable, payload opaque.

msgpack (not JSON) keeps the per-token hot path cheap; the payload may carry
raw bytes (e.g. serialized arrays) with no base64 overhead.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import Any

import msgpack

MAX_FRAME_BYTES = 256 * 1024 * 1024  # hard cap; a corrupt length prefix fails fast


class FrameType(str, Enum):
    REQUEST = "req"        # caller -> worker: open a stream {subject, id, p}
    #                        + optional "trace" = {trace_id, span_id}: the W3C
    #                        trace context of the calling span, extracted into
    #                        the worker-side Context (distributed tracing)
    PROLOGUE = "pro"       # worker -> caller: stream accepted (or error detail)
    DATA = "dat"           # worker -> caller: one response item
    ERROR = "err"          # worker -> caller: stream failed; terminal
    COMPLETE = "end"       # worker -> caller: stream finished; terminal
    STOP = "stp"           # caller -> worker: stop generating (graceful)
    KILL = "kil"           # caller -> worker: hard-cancel the stream


@dataclass(frozen=True)
class Frame:
    type: FrameType
    fields: dict[str, Any]

    @property
    def payload(self) -> Any:
        return self.fields.get("p")


def encode_frame(ftype: FrameType, **fields: Any) -> bytes:
    body = msgpack.packb({"t": ftype.value, **fields}, use_bin_type=True)
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(body)} bytes")
    return len(body).to_bytes(4, "big") + body


def decode_body(body: bytes) -> Frame:
    obj = msgpack.unpackb(body, raw=False)
    t = obj.pop("t")
    return Frame(type=FrameType(t), fields=obj)


async def read_frame(reader: asyncio.StreamReader) -> Frame | None:
    """Read one frame; None on clean EOF."""
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    length = int.from_bytes(header, "big")
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds cap")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return decode_body(body)


def write_frame(writer: asyncio.StreamWriter, ftype: FrameType, **fields: Any) -> None:
    writer.write(encode_frame(ftype, **fields))
