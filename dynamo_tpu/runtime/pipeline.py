"""Typed pipeline graph: compose Operators, split segments across the network.

The reference models a request path as Source/Sink nodes linked by typed
edges, with ``ServiceFrontend``/``ServiceBackend`` at the ends and
``SegmentSource``/``SegmentSink`` where one logical pipeline is cut into
network-separated halves (`lib/runtime/src/pipeline/nodes*.rs`,
`pipeline.rs:43-120`). In this framework a node is an
:class:`~dynamo_tpu.runtime.engine.AsyncEngine` and an edge is an async
response stream, so the graph machinery reduces to three pieces:

- :class:`Pipeline` — an ordered list of operator factories; ``build(backend)``
  folds them right-to-left into one engine (the frontend), ``split(at)``
  cuts the list into two pipelines deployable in different processes.
- :class:`SegmentSink` — the head-side stand-in for the cut edge: an engine
  whose downstream is attached later (a runtime Client, usually).
- :func:`serve_segment` — the tail side: builds the remaining pipeline onto
  the real backend and publishes it as an endpoint (the SegmentSource role).

Per-request :class:`Context` flows through every operator (stop/kill
propagate down the chain; see ``Operator.generate``), which is the
reference's per-request context (`pipeline/context.rs`).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Callable

from dynamo_tpu.runtime.engine import AsyncEngine, Context, Operator

# An operator factory: downstream engine -> engine. Operator subclasses are
# factories already (their __init__ takes the downstream engine).
OperatorFactory = Callable[[AsyncEngine[Any, Any]], AsyncEngine[Any, Any]]


class PipelineError(RuntimeError):
    pass


class SegmentSink(AsyncEngine[Any, Any]):
    """The cut edge's head side: forwards to an engine attached at runtime.

    ``attach`` is once-only (reference ``EdgeAlreadySet``); generating before
    attachment fails loudly rather than hanging — a segment whose remote half
    never came up must surface, not queue.
    """

    def __init__(self) -> None:
        self._engine: AsyncEngine[Any, Any] | None = None

    def attach(self, engine: AsyncEngine[Any, Any]) -> None:
        if self._engine is not None:
            raise PipelineError("segment edge already attached")
        self._engine = engine

    @property
    def attached(self) -> bool:
        return self._engine is not None

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if self._engine is None:
            raise PipelineError("segment edge not attached (remote half not connected)")
        async for item in self._engine.generate(request, context):
            yield item


class _ClientEngine(AsyncEngine[Any, Any]):
    """Adapts a runtime Client (watch + routing) to the engine interface."""

    def __init__(self, client: Any, **call_kw: Any) -> None:
        self.client = client
        self.call_kw = call_kw

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        async for item in self.client.generate(request, context, **self.call_kw):
            yield item


class Pipeline:
    """An ordered operator chain, frontend-most first.

    ``Pipeline([A, B]).build(backend)`` produces ``A(B(backend))``: requests
    enter A, responses stream back out of A.
    """

    def __init__(self, operators: list[OperatorFactory] | None = None) -> None:
        self.operators: list[OperatorFactory] = list(operators or [])

    def link(self, factory: OperatorFactory) -> "Pipeline":
        """Append the next (deeper) stage; returns self for chaining."""
        self.operators.append(factory)
        return self

    def build(self, backend: AsyncEngine[Any, Any]) -> AsyncEngine[Any, Any]:
        engine = backend
        for factory in reversed(self.operators):
            engine = factory(engine)
            if not isinstance(engine, AsyncEngine):
                raise PipelineError(f"operator factory {factory!r} did not produce an AsyncEngine")
        return engine

    def split(self, at: int) -> tuple["Pipeline", "Pipeline", SegmentSink]:
        """Cut into (head, tail) at operator index ``at``.

        The returned :class:`SegmentSink` is the head's backend:
        ``head.build(sink)``. Deploy the tail remotely with
        :func:`serve_segment`, then ``sink.attach(segment_client(...))``.
        """
        if not 0 <= at <= len(self.operators):
            raise PipelineError(f"split point {at} outside [0, {len(self.operators)}]")
        return Pipeline(self.operators[:at]), Pipeline(self.operators[at:]), SegmentSink()


async def serve_segment(
    endpoint: Any,
    pipeline: Pipeline,
    backend: AsyncEngine[Any, Any],
    *,
    lease: Any | None = None,
    metadata: dict[str, Any] | None = None,
) -> Any:
    """SegmentSource: publish the tail half as a network endpoint."""
    return await endpoint.serve(pipeline.build(backend), lease=lease, metadata=metadata)


def segment_client(client: Any, **call_kw: Any) -> AsyncEngine[Any, Any]:
    """Engine view of a started runtime Client, for ``SegmentSink.attach``."""
    return _ClientEngine(client, **call_kw)


class FnOperator(Operator[Any, Any]):
    """Operator from two plain functions (request map, item map) — the
    lightweight way to drop a transform into a pipeline."""

    def __init__(
        self,
        downstream: AsyncEngine[Any, Any],
        *,
        on_request: Callable[[Any], Any] | None = None,
        on_item: Callable[[Any], Any] | None = None,
    ) -> None:
        super().__init__(downstream)
        self._on_request = on_request
        self._on_item = on_item

    @classmethod
    def factory(
        cls,
        *,
        on_request: Callable[[Any], Any] | None = None,
        on_item: Callable[[Any], Any] | None = None,
    ) -> OperatorFactory:
        return lambda downstream: cls(downstream, on_request=on_request, on_item=on_item)

    async def transform_request(self, request: Any, context: Context) -> Any:
        return self._on_request(request) if self._on_request else request

    async def transform_stream(self, stream, request, context):
        async for item in stream:
            yield self._on_item(item) if self._on_item else item
