"""Endpoint client: instance watching, routing modes, per-instance circuit breakers.

A client watches the discovery prefix for its endpoint and keeps a live
instance table. Each request picks an instance by router mode:

- ``round_robin`` / ``random`` — load-agnostic spreading (DP across replicas).
- ``direct`` — pin to a specific instance id (used by the disagg path and by
  the KV router, which computes the instance id itself and then goes direct).

Instances that fail requests are routed around by a per-instance circuit
breaker rather than removed — discovery owns membership (lease expiry), the
client only routes around errors. The breaker opens after
``breaker_threshold`` consecutive failures, stays open for
``breaker_open_seconds``, then admits a single half-open probe whose outcome
closes or re-opens it. Workers announcing ``metadata={"draining": True}``
are ineligible for new requests while they finish in-flight work.

The watch loop reconnects on store failure with jittered exponential
backoff (it previously died permanently on the first hiccup); restarts and
staleness are exported via :func:`watch_snapshot` / :func:`breaker_snapshot`
into the frontend registry (``dynamo_client_*`` families). Parity:
reference `component/client.rs:56-150` and PushRouter modes
(`egress/push_router.rs:72-85`).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import sys
import time
import weakref
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import Endpoint, Instance, instance_prefix
from dynamo_tpu.runtime.discovery import WatchEvent, WatchEventType
from dynamo_tpu.runtime.engine import Context, EngineError
from dynamo_tpu.runtime.transport import NoSuchSubjectError

logger = logging.getLogger(__name__)

DEFAULT_INHIBIT_SECONDS = 2.0

#: Breaker states as exported by ``dynamo_client_breaker_state``.
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2

_WATCH_BACKOFF_BASE = 0.05
_WATCH_BACKOFF_CAP = 5.0

#: Live clients, for metric snapshots (weak: a dropped client stops exporting).
_CLIENTS: "weakref.WeakSet[Client]" = weakref.WeakSet()


class NoInstancesError(RuntimeError):
    """No routable instance for an endpoint (none known, or the pinned one
    is gone/draining/broken). Carries the endpoint path and how many
    instances the client knew about, for debuggability at the call site."""

    def __init__(self, message: str, *, endpoint_path: str = "", known_instances: int = 0) -> None:
        super().__init__(message)
        self.endpoint_path = endpoint_path
        self.known_instances = known_instances


class CircuitBreaker:
    """Consecutive-failure breaker for one instance.

    closed --(threshold consecutive failures)--> open
    open --(open_seconds elapse)--> half-open, admitting ONE probe
    half-open --probe success--> closed / --probe failure--> open again
    """

    __slots__ = ("threshold", "open_seconds", "failures", "state", "_opened_at",
                 "_probe_inflight", "_probe_started")

    def __init__(self, threshold: int, open_seconds: float) -> None:
        self.threshold = max(1, threshold)
        self.open_seconds = open_seconds
        self.failures = 0
        self.state = BREAKER_CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0

    def _probe_live(self, now: float) -> bool:
        # A probe that never reported back (cancelled mid-flight) must not
        # wedge the breaker half-open forever.
        return self._probe_inflight and now - self._probe_started < max(self.open_seconds, 1.0)

    def allow(self, now: float) -> bool:
        """Side-effect-free routability check."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            return now - self._opened_at >= self.open_seconds and not self._probe_live(now)
        return not self._probe_live(now)  # half-open: one probe at a time

    def begin_attempt(self, now: float) -> None:
        """A request is actually being dispatched to this instance."""
        if self.state == BREAKER_OPEN and now - self._opened_at >= self.open_seconds:
            self.state = BREAKER_HALF_OPEN
        if self.state == BREAKER_HALF_OPEN:
            self._probe_inflight = True
            self._probe_started = now

    def record_success(self) -> None:
        self.failures = 0
        self.state = BREAKER_CLOSED
        self._probe_inflight = False

    def record_failure(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self.failures += 1
        if self.state == BREAKER_HALF_OPEN or self.failures >= self.threshold:
            self.state = BREAKER_OPEN
            self._opened_at = now
        self._probe_inflight = False


class Client:
    def __init__(
        self,
        endpoint: Endpoint,
        *,
        router_mode: str = "round_robin",
        inhibit_seconds: float | None = None,
        max_attempts: int = 3,
        breaker_threshold: int | None = None,
    ) -> None:
        if router_mode not in ("round_robin", "random", "direct"):
            raise ValueError(f"unknown router mode: {router_mode}")
        self.endpoint = endpoint
        self.router_mode = router_mode
        self._instances: dict[int, Instance] = {}
        self._breakers: dict[int, CircuitBreaker] = {}
        if inhibit_seconds is None:
            inhibit_seconds = float(os.environ.get("DYN_CLIENT_BREAKER_OPEN_S", DEFAULT_INHIBIT_SECONDS))
        if breaker_threshold is None:
            breaker_threshold = int(os.environ.get("DYN_CLIENT_BREAKER_THRESHOLD", "3"))
        self._breaker_open_seconds = inhibit_seconds
        self._breaker_threshold = breaker_threshold
        self._max_attempts = max_attempts
        self._rr_counter = 0
        self._watch_task: asyncio.Task | None = None
        self._changed: asyncio.Event = asyncio.Event()
        self.watch_restarts = 0
        self._watch_down_since: float | None = None
        _CLIENTS.add(self)

    # -- instance table ----------------------------------------------------

    async def start(self) -> "Client":
        if self._watch_task is None:
            # Seed synchronously so the first generate() after start() sees
            # currently-registered instances; the watch (whose initial
            # snapshot upserts idempotently) then keeps the table live.
            await self._resync()
            self._watch_task = asyncio.create_task(self._watch_loop())
        return self

    def _apply(self, event: WatchEvent) -> None:
        if event.type is WatchEventType.PUT and event.value is not None:
            inst = Instance.from_bytes(event.value)
            self._instances[inst.instance_id] = inst
        elif event.type is WatchEventType.DELETE:
            lease_hex = event.key.rsplit(":", 1)[-1]
            iid = int(lease_hex, 16)
            self._instances.pop(iid, None)
            self._breakers.pop(iid, None)  # departed: drop breaker state
        self._changed.set()

    async def _resync(self) -> None:
        """Rebuild the instance table from a prefix scan. Watch replay only
        upserts, so deletions missed during a watch outage would otherwise
        leave phantom instances — reconcile against ground truth instead."""
        ep = self.endpoint
        prefix = instance_prefix(ep.namespace, ep.component, ep.name)
        fresh: dict[int, Instance] = {}
        for value in (await ep.runtime.store.get_prefix(prefix)).values():
            inst = Instance.from_bytes(value)
            fresh[inst.instance_id] = inst
        self._instances = fresh
        self._breakers = {iid: b for iid, b in self._breakers.items() if iid in fresh}
        self._changed.set()

    async def _watch_loop(self) -> None:
        ep = self.endpoint
        prefix = instance_prefix(ep.namespace, ep.component, ep.name)
        backoff = _WATCH_BACKOFF_BASE
        while True:
            try:
                async for event in ep.runtime.store.watch_prefix(prefix):
                    backoff = _WATCH_BACKOFF_BASE
                    self._watch_down_since = None
                    self._apply(event)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if self._watch_down_since is None:
                    self._watch_down_since = time.monotonic()
                self.watch_restarts += 1
                delay = backoff * random.uniform(0.5, 1.0)
                logger.warning(
                    "instance watch for %s failed (%s: %s); reconnecting in %.2fs (restart #%d)",
                    ep.path, type(exc).__name__, exc, delay, self.watch_restarts,
                )
                await asyncio.sleep(delay)
                backoff = min(backoff * 2.0, _WATCH_BACKOFF_CAP)
            else:
                # The store closed the stream cleanly — still a resubscribe.
                if self._watch_down_since is None:
                    self._watch_down_since = time.monotonic()
                self.watch_restarts += 1
                await asyncio.sleep(backoff * random.uniform(0.5, 1.0))
                backoff = min(backoff * 2.0, _WATCH_BACKOFF_CAP)
            try:
                await self._resync()
                self._watch_down_since = None
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.warning("instance resync for %s failed; will retry after next watch attempt", ep.path)

    def watch_staleness(self) -> float:
        """Seconds the instance watch has been down (0.0 while healthy)."""
        if self._watch_down_since is None:
            return 0.0
        return time.monotonic() - self._watch_down_since

    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    def instance_ids(self) -> list[int]:
        return list(self._instances.keys())

    async def wait_for_instances(self, *, count: int = 1, timeout: float = 10.0) -> list[Instance]:
        await self.start()
        deadline = time.monotonic() + timeout
        while len(self._instances) < count:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self._instances)}/{count} instances after {timeout}s"
                )
            self._changed.clear()
            try:
                await asyncio.wait_for(self._changed.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                continue
        return self.instances()

    # -- selection ---------------------------------------------------------

    def _breaker_for(self, instance_id: int) -> CircuitBreaker:
        b = self._breakers.get(instance_id)
        if b is None:
            b = self._breakers[instance_id] = CircuitBreaker(
                self._breaker_threshold, self._breaker_open_seconds
            )
        return b

    @property
    def _inhibited(self) -> dict[int, float]:
        """Legacy view: instance_id -> blocked-until deadline, for instances
        the breaker currently refuses to route to."""
        now = time.monotonic()
        return {
            iid: b._opened_at + b.open_seconds
            for iid, b in self._breakers.items()
            if not b.allow(now)
        }

    def _eligible(self) -> list[Instance]:
        now = time.monotonic()
        alive = list(self._instances.values())
        active = [i for i in alive if not i.metadata.get("draining")]
        pool = [
            i for i in active
            if (b := self._breakers.get(i.instance_id)) is None or b.allow(now)
        ]
        # Everything blocked is worse than trying a blocked one: degrade to
        # the non-draining set, then to anything alive, rather than fail.
        return pool or active or alive

    def _pick(self, instance_id: int | None) -> Instance:
        if instance_id is not None:
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoInstancesError(
                    f"instance {instance_id:x} not found for {self.endpoint.path} "
                    f"({len(self._instances)} instances known)",
                    endpoint_path=self.endpoint.path,
                    known_instances=len(self._instances),
                )
            if inst.metadata.get("draining"):
                raise NoInstancesError(
                    f"instance {instance_id:x} is draining for {self.endpoint.path} "
                    f"({len(self._instances)} instances known)",
                    endpoint_path=self.endpoint.path,
                    known_instances=len(self._instances),
                )
            b = self._breakers.get(instance_id)
            if b is not None and not b.allow(time.monotonic()):
                raise NoInstancesError(
                    f"instance {instance_id:x} breaker open for {self.endpoint.path} "
                    f"({len(self._instances)} instances known)",
                    endpoint_path=self.endpoint.path,
                    known_instances=len(self._instances),
                )
            return inst
        pool = self._eligible()
        if not pool:
            raise NoInstancesError(
                f"no live instances for {self.endpoint.path}",
                endpoint_path=self.endpoint.path,
                known_instances=len(self._instances),
            )
        if self.router_mode == "random":
            return random.choice(pool)
        self._rr_counter += 1
        return pool[self._rr_counter % len(pool)]

    def inhibit(self, instance_id: int) -> None:
        """Record one failure against ``instance_id`` (legacy name; the
        breaker opens after ``breaker_threshold`` consecutive failures)."""
        self._breaker_for(instance_id).record_failure()

    def breaker_states(self) -> dict[int, int]:
        """instance_id -> breaker state (0 closed / 1 half-open / 2 open)."""
        return {iid: b.state for iid, b in self._breakers.items()}

    # -- request path ------------------------------------------------------

    async def generate(
        self,
        request: Any,
        context: Context | None = None,
        *,
        instance_id: int | None = None,
    ) -> AsyncIterator[Any]:
        """Open a response stream on one instance (retrying across replicas).

        Retries only happen before the first response item — once tokens have
        flowed, a failure surfaces to the caller (no replay of partial
        streams, same stance as the reference).
        """
        context = context or Context()
        await self.start()
        transport = self.endpoint.runtime.transport
        attempts = self._max_attempts if instance_id is None else 1
        last_error: Exception | None = None
        for _ in range(attempts):
            inst = self._pick(instance_id)
            breaker = self._breaker_for(inst.instance_id)
            breaker.begin_attempt(time.monotonic())
            # Traced requests get a per-hop client span; its span_id becomes
            # the remote side's parent (injected via the hop context's trace,
            # which the transport forwards on the wire). Untraced internal
            # traffic pays nothing.
            span = None
            hop_ctx = context
            if context.trace is not None:
                from dynamo_tpu.tracing import Span, trace_of

                span = Span(
                    "rpc_client", trace=trace_of(context), request_id=context.id,
                    endpoint=self.endpoint.path, instance=f"{inst.instance_id:x}",
                )
                span.__enter__()
                hop_ctx = context.child()
                hop_ctx.trace = span.context.to_dict()
            stream = transport.generate(inst.address, request, hop_ctx)
            try:
                try:
                    first = await anext(stream)
                except StopAsyncIteration:
                    breaker.record_success()
                    return
                except (NoSuchSubjectError, ConnectionError, OSError, EngineError) as exc:
                    breaker.record_failure()
                    logger.warning(
                        "instance %x failed pre-stream: %s (breaker %s, %d consecutive failures)",
                        inst.instance_id, exc,
                        {0: "closed", 1: "half-open", 2: "open"}[breaker.state],
                        breaker.failures,
                    )
                    last_error = exc
                    if span is not None:
                        span.__exit__(type(exc), exc, None)
                        span = None
                    continue
                breaker.record_success()
                yield first
                async for item in stream:
                    yield item
                return
            finally:
                await stream.aclose()
                if span is not None:
                    # Consumer walk-away (GeneratorExit/cancel) is not a span
                    # failure; real stream errors mark the span status=error.
                    et, ev, tb = sys.exc_info()
                    if et in (GeneratorExit, asyncio.CancelledError, StopAsyncIteration):
                        et, ev, tb = None, None, None
                    span.__exit__(et, ev, tb)
        if last_error is not None:
            raise last_error
        raise NoInstancesError(
            f"no attempt succeeded for {self.endpoint.path}",
            endpoint_path=self.endpoint.path,
            known_instances=len(self._instances),
        )

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None


# -- metric snapshots ---------------------------------------------------------
#
# The frontend registry syncs these on scrape (the kernel_fallbacks idiom):
# module-level views over every live client in the process, keyed for the
# dynamo_client_* label sets.


def watch_snapshot() -> dict[str, dict[str, float]]:
    """Per-endpoint ``{"restarts": n, "staleness": seconds}`` across clients."""
    out: dict[str, dict[str, float]] = {}
    for client in list(_CLIENTS):
        agg = out.setdefault(client.endpoint.path, {"restarts": 0.0, "staleness": 0.0})
        agg["restarts"] += client.watch_restarts
        agg["staleness"] = max(agg["staleness"], client.watch_staleness())
    return out


def breaker_snapshot() -> dict[tuple[str, str], int]:
    """(endpoint_path, instance_hex) -> breaker state across live clients."""
    out: dict[tuple[str, str], int] = {}
    for client in list(_CLIENTS):
        for iid, state in client.breaker_states().items():
            key = (client.endpoint.path, f"{iid:x}")
            out[key] = max(out.get(key, BREAKER_CLOSED), state)
    return out
