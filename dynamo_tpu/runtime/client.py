"""Endpoint client: instance watching, routing modes, failure inhibition.

A client watches the discovery prefix for its endpoint and keeps a live
instance table. Each request picks an instance by router mode:

- ``round_robin`` / ``random`` — load-agnostic spreading (DP across replicas).
- ``direct`` — pin to a specific instance id (used by the disagg path and by
  the KV router, which computes the instance id itself and then goes direct).

Instances that fail a request are *inhibited* for a short window rather than
removed — discovery owns membership (lease expiry), the client only routes
around transient errors. Parity: reference `component/client.rs:56-150` and
PushRouter modes (`egress/push_router.rs:72-85`).
"""

from __future__ import annotations

import asyncio
import logging
import random
import sys
import time
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import Endpoint, Instance, instance_prefix
from dynamo_tpu.runtime.discovery import WatchEventType
from dynamo_tpu.runtime.engine import Context, EngineError
from dynamo_tpu.runtime.transport import NoSuchSubjectError

logger = logging.getLogger(__name__)

DEFAULT_INHIBIT_SECONDS = 2.0


class NoInstancesError(RuntimeError):
    pass


class Client:
    def __init__(
        self,
        endpoint: Endpoint,
        *,
        router_mode: str = "round_robin",
        inhibit_seconds: float = DEFAULT_INHIBIT_SECONDS,
        max_attempts: int = 3,
    ) -> None:
        if router_mode not in ("round_robin", "random", "direct"):
            raise ValueError(f"unknown router mode: {router_mode}")
        self.endpoint = endpoint
        self.router_mode = router_mode
        self._instances: dict[int, Instance] = {}
        self._inhibited: dict[int, float] = {}  # instance_id -> inhibit deadline
        self._inhibit_seconds = inhibit_seconds
        self._max_attempts = max_attempts
        self._rr_counter = 0
        self._watch_task: asyncio.Task | None = None
        self._changed: asyncio.Event = asyncio.Event()

    # -- instance table ----------------------------------------------------

    async def start(self) -> "Client":
        if self._watch_task is None:
            # Seed synchronously so the first generate() after start() sees
            # currently-registered instances; the watch (whose initial
            # snapshot upserts idempotently) then keeps the table live.
            ep = self.endpoint
            prefix = instance_prefix(ep.namespace, ep.component, ep.name)
            for value in (await ep.runtime.store.get_prefix(prefix)).values():
                inst = Instance.from_bytes(value)
                self._instances[inst.instance_id] = inst
            self._watch_task = asyncio.create_task(self._watch_loop())
        return self

    async def _watch_loop(self) -> None:
        ep = self.endpoint
        prefix = instance_prefix(ep.namespace, ep.component, ep.name)
        try:
            async for event in ep.runtime.store.watch_prefix(prefix):
                if event.type is WatchEventType.PUT and event.value is not None:
                    inst = Instance.from_bytes(event.value)
                    self._instances[inst.instance_id] = inst
                elif event.type is WatchEventType.DELETE:
                    lease_hex = event.key.rsplit(":", 1)[-1]
                    self._instances.pop(int(lease_hex, 16), None)
                self._changed.set()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("instance watch failed for %s", ep.path)

    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    def instance_ids(self) -> list[int]:
        return list(self._instances.keys())

    async def wait_for_instances(self, *, count: int = 1, timeout: float = 10.0) -> list[Instance]:
        await self.start()
        deadline = time.monotonic() + timeout
        while len(self._instances) < count:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self._instances)}/{count} instances after {timeout}s"
                )
            self._changed.clear()
            try:
                await asyncio.wait_for(self._changed.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                continue
        return self.instances()

    # -- selection ---------------------------------------------------------

    def _eligible(self) -> list[Instance]:
        now = time.monotonic()
        self._inhibited = {i: t for i, t in self._inhibited.items() if t > now}
        pool = [inst for iid, inst in self._instances.items() if iid not in self._inhibited]
        # All inhibited is worse than trying an inhibited one: fall back.
        return pool or list(self._instances.values())

    def _pick(self, instance_id: int | None) -> Instance:
        if instance_id is not None:
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoInstancesError(f"instance {instance_id:x} not found for {self.endpoint.path}")
            return inst
        pool = self._eligible()
        if not pool:
            raise NoInstancesError(f"no live instances for {self.endpoint.path}")
        if self.router_mode == "random":
            return random.choice(pool)
        self._rr_counter += 1
        return pool[self._rr_counter % len(pool)]

    def inhibit(self, instance_id: int) -> None:
        self._inhibited[instance_id] = time.monotonic() + self._inhibit_seconds

    # -- request path ------------------------------------------------------

    async def generate(
        self,
        request: Any,
        context: Context | None = None,
        *,
        instance_id: int | None = None,
    ) -> AsyncIterator[Any]:
        """Open a response stream on one instance (retrying across replicas).

        Retries only happen before the first response item — once tokens have
        flowed, a failure surfaces to the caller (no replay of partial
        streams, same stance as the reference).
        """
        context = context or Context()
        await self.start()
        transport = self.endpoint.runtime.transport
        attempts = self._max_attempts if instance_id is None else 1
        last_error: Exception | None = None
        for _ in range(attempts):
            inst = self._pick(instance_id)
            # Traced requests get a per-hop client span; its span_id becomes
            # the remote side's parent (injected via the hop context's trace,
            # which the transport forwards on the wire). Untraced internal
            # traffic pays nothing.
            span = None
            hop_ctx = context
            if context.trace is not None:
                from dynamo_tpu.tracing import Span, trace_of

                span = Span(
                    "rpc_client", trace=trace_of(context), request_id=context.id,
                    endpoint=self.endpoint.path, instance=f"{inst.instance_id:x}",
                )
                span.__enter__()
                hop_ctx = context.child()
                hop_ctx.trace = span.context.to_dict()
            stream = transport.generate(inst.address, request, hop_ctx)
            try:
                try:
                    first = await anext(stream)
                except StopAsyncIteration:
                    return
                except (NoSuchSubjectError, ConnectionError, OSError, EngineError) as exc:
                    logger.warning("instance %x failed pre-stream: %s; inhibiting", inst.instance_id, exc)
                    self.inhibit(inst.instance_id)
                    last_error = exc
                    if span is not None:
                        span.__exit__(type(exc), exc, None)
                        span = None
                    continue
                yield first
                async for item in stream:
                    yield item
                return
            finally:
                await stream.aclose()
                if span is not None:
                    # Consumer walk-away (GeneratorExit/cancel) is not a span
                    # failure; real stream errors mark the span status=error.
                    et, ev, tb = sys.exc_info()
                    if et in (GeneratorExit, asyncio.CancelledError, StopAsyncIteration):
                        et, ev, tb = None, None, None
                    span.__exit__(et, ev, tb)
        raise last_error if last_error is not None else NoInstancesError(self.endpoint.path)

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
