"""The discovery store served over TCP — this deployment's etcd.

One process (typically the frontend) runs ``StoreServer`` around a
MemoryStore; every other process connects with ``StoreClient``, which
implements the same ``KeyValueStore`` interface — nothing above the store
can tell local from remote. Leases live server-side, so a client process
dying (keep-alives stop) expires its keys exactly like etcd.

Protocol: length-prefixed msgpack frames (runtime.codec). RPCs are
request/response on a single multiplexed connection (correlation ids);
watches each hold a dedicated streaming connection.

Parity: reference `transports/etcd.rs` (we speak to our own server instead
of etcd; an etcd-backed KeyValueStore can be slotted in unchanged when
available).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.codec import Frame, FrameType, read_frame, write_frame
from dynamo_tpu.runtime.discovery import (
    DEFAULT_LEASE_TTL,
    KeyValueStore,
    Lease,
    MemoryStore,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.faults import FAULTS

logger = logging.getLogger(__name__)


class StoreServer:
    def __init__(self, store: KeyValueStore | None = None, *, host: str = "0.0.0.0", port: int = 0) -> None:
        self.store = store if store is not None else MemoryStore()
        self._host = host
        self._port = port
        self._server: asyncio.Server | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> "StoreServer":
        if self._server is None:
            self._server = await asyncio.start_server(self._handle, self._host, self._port)
            self._port = self._server.sockets[0].getsockname()[1]
            logger.info("store server on %s:%d", self._host, self._port)
        return self

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task:
            self._conn_tasks.add(task)
        watch_task: asyncio.Task | None = None
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    return
                op = frame.fields.get("op")
                rid = frame.fields.get("rid")
                if op == "watch":
                    # Connection becomes a one-way event stream.
                    watch_task = asyncio.create_task(
                        self._stream_watch(writer, frame.fields["prefix"], frame.fields.get("initial", True))
                    )
                    continue
                try:
                    result = await self._execute(op, frame.fields)
                    write_frame(writer, FrameType.DATA, rid=rid, p=result)
                except KeyError as exc:
                    write_frame(writer, FrameType.ERROR, rid=rid, error=str(exc), kind="key")
                except Exception as exc:
                    logger.exception("store op %s failed", op)
                    write_frame(writer, FrameType.ERROR, rid=rid, error=str(exc), kind="internal")
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            if watch_task is not None:
                watch_task.cancel()
            writer.close()
            if task:
                self._conn_tasks.discard(task)

    async def _stream_watch(self, writer: asyncio.StreamWriter, prefix: str, initial: bool) -> None:
        try:
            async for event in self.store.watch_prefix(prefix, initial=initial):
                write_frame(
                    writer, FrameType.DATA,
                    p={"type": event.type.value, "key": event.key, "value": event.value},
                )
                await writer.drain()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("watch stream failed for %s", prefix)

    async def _execute(self, op: str, f: dict[str, Any]) -> Any:
        s = self.store
        if op == "put":
            await s.put(f["key"], f["value"], lease_id=f.get("lease_id"))
            return True
        if op == "put_if_absent":
            return await s.put_if_absent(f["key"], f["value"], lease_id=f.get("lease_id"))
        if op == "get":
            return await s.get(f["key"])
        if op == "get_prefix":
            return await s.get_prefix(f["prefix"])
        if op == "delete":
            return await s.delete(f["key"])
        if op == "create_lease":
            lease = await s.create_lease(f.get("ttl", DEFAULT_LEASE_TTL))
            return {"id": lease.id, "ttl": lease.ttl}
        if op == "keep_alive":
            await s.keep_alive(f["lease_id"])
            return True
        if op == "revoke_lease":
            await s.revoke_lease(f["lease_id"])
            return True
        raise ValueError(f"unknown op {op!r}")

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for t in list(self._conn_tasks):
            t.cancel()
        # The served store may hold resources (e.g. a persistence WAL).
        await self.store.close()


class StoreClient(KeyValueStore):
    """KeyValueStore speaking the wire protocol. One shared RPC connection
    (correlated by request id), one dedicated connection per watch."""

    def __init__(self, host: str, port: int) -> None:
        self._host = host
        self._port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._rid = itertools.count(1)
        self._reader_task: asyncio.Task | None = None
        self._lock = asyncio.Lock()
        self._watch_writers: list[asyncio.StreamWriter] = []

    @classmethod
    def from_url(cls, url: str) -> "StoreClient":
        """tcp://host:port"""
        rest = url.split("://", 1)[-1]
        host, port = rest.rsplit(":", 1)
        return cls(host, int(port))

    async def _ensure(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        self._reader, self._writer = await asyncio.open_connection(self._host, self._port)
        self._reader_task = asyncio.create_task(self._read_loop(self._reader))

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                fut = self._pending.pop(frame.fields.get("rid"), None)
                if fut is None or fut.done():
                    continue
                if frame.type is FrameType.ERROR:
                    kind = frame.fields.get("kind")
                    exc: Exception = KeyError(frame.fields.get("error")) if kind == "key" else RuntimeError(
                        frame.fields.get("error")
                    )
                    fut.set_exception(exc)
                else:
                    fut.set_result(frame.payload)
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("store connection lost"))
            self._pending.clear()

    async def _call(self, op: str, **fields: Any) -> Any:
        if FAULTS.armed:
            FAULTS.fire("store.op")
        async with self._lock:
            await self._ensure()
            rid = next(self._rid)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[rid] = fut
            write_frame(self._writer, FrameType.REQUEST, op=op, rid=rid, **fields)
            await self._writer.drain()
        return await fut

    # -- KeyValueStore API -------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        await self._call("put", key=key, value=value, lease_id=lease_id)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        return await self._call("put_if_absent", key=key, value=value, lease_id=lease_id)

    async def get(self, key: str) -> bytes | None:
        return await self._call("get", key=key)

    async def get_prefix(self, prefix: str) -> dict[str, bytes]:
        return await self._call("get_prefix", prefix=prefix)

    async def delete(self, key: str) -> bool:
        return await self._call("delete", key=key)

    async def create_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease:
        d = await self._call("create_lease", ttl=ttl)
        return Lease(id=d["id"], ttl=d["ttl"], store=self)

    async def keep_alive(self, lease_id: int) -> None:
        await self._call("keep_alive", lease_id=lease_id)

    async def revoke_lease(self, lease_id: int) -> None:
        await self._call("revoke_lease", lease_id=lease_id)

    async def watch_prefix(self, prefix: str, initial: bool = True) -> AsyncIterator[WatchEvent]:
        reader, writer = await asyncio.open_connection(self._host, self._port)
        self._watch_writers.append(writer)
        try:
            write_frame(writer, FrameType.REQUEST, op="watch", prefix=prefix, initial=initial)
            await writer.drain()
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    raise ConnectionError("watch stream closed")
                if FAULTS.armed:
                    FAULTS.fire("store.watch")
                p = frame.payload
                yield WatchEvent(WatchEventType(p["type"]), p["key"], p.get("value"))
        finally:
            self._watch_writers.remove(writer)
            writer.close()

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for w in list(self._watch_writers):
            w.close()
