"""The discovery store served over TCP — this deployment's etcd.

One process runs ``StoreServer`` around a MemoryStore; every other process
connects with ``StoreClient``, which implements the same ``KeyValueStore``
interface — nothing above the store can tell local from remote. Leases live
server-side, so a client process dying (keep-alives stop) expires its keys
exactly like etcd.

Protocol: length-prefixed msgpack frames (runtime.codec). RPCs are
request/response on a single multiplexed connection (correlation ids);
watches each hold a dedicated streaming connection, as does a follower
replica's ``op="replicate"`` log subscription (``runtime/replication.py``).

High availability: with ``--store tcp://a,tcp://b,...`` the client holds the
full replica list. All mutations go to the leader; followers answer
``who_leads`` with a redirect, and on ``ConnectionError`` the client walks
the list, discovers the new leader, transparently retries idempotent
in-flight ops exactly once, and re-arms watches with a resync. A
single-endpoint client takes exactly the pre-HA code paths.

Parity: reference `transports/etcd.rs` (we speak to our own server instead
of etcd; an etcd-backed KeyValueStore can be slotted in unchanged when
available).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.codec import Frame, FrameType, read_frame, write_frame
from dynamo_tpu.runtime.discovery import (
    DEFAULT_LEASE_TTL,
    KeyValueStore,
    Lease,
    MemoryStore,
    WatchEvent,
    WatchEventType,
)
from dynamo_tpu.runtime.faults import FAULTS

logger = logging.getLogger(__name__)

#: Ops that mutate store state — leader-only under replication.
MUTATING_OPS = frozenset(
    {"put", "put_if_absent", "delete", "create_lease", "keep_alive", "revoke_lease"}
)

#: Ops the client may transparently retry once after a reconnect: replaying
#: them cannot change the outcome (``put`` re-sends the same payload;
#: ``create_lease``/``put_if_absent``/``revoke_lease`` could double-apply).
IDEMPOTENT_OPS = frozenset({"get", "get_prefix", "keep_alive", "delete", "put", "who_leads"})


class NotLeaderError(RuntimeError):
    """Mutation sent to a follower replica; carries the leader's url hint."""

    def __init__(self, leader: str | None) -> None:
        super().__init__(f"not the store leader (leader: {leader or 'unknown'})")
        self.leader = leader


#: Client-side HA counters, surfaced by ``frontend/metrics.py`` as
#: dynamo_store_client_op_retries_total / dynamo_store_failovers_total (and
#: the role/epoch gauges when no in-process replica exists).
_CLIENT_STATS = {"retries": 0, "failovers": 0, "epoch": 0, "role": "unknown", "leader": None}


def store_client_snapshot() -> dict:
    """Process-wide StoreClient HA view (metrics sync-on-render source)."""
    return dict(_CLIENT_STATS)


class StoreServer:
    def __init__(self, store: KeyValueStore | None = None, *, host: str = "0.0.0.0", port: int = 0) -> None:
        self.store = store if store is not None else MemoryStore()
        self._host = host
        self._port = port
        self._server: asyncio.Server | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        # Replication coordinator (runtime/replication.py); None = the
        # single-replica deployment, where every HA check below short-circuits
        # on one attribute load and behavior is identical to pre-HA.
        self.repl = None

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> "StoreServer":
        if self._server is None:
            self._server = await asyncio.start_server(self._handle, self._host, self._port)
            self._port = self._server.sockets[0].getsockname()[1]
            logger.info("store server on %s:%d", self._host, self._port)
        return self

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task:
            self._conn_tasks.add(task)
        stream_task: asyncio.Task | None = None
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    return
                op = frame.fields.get("op")
                rid = frame.fields.get("rid")
                if op == "watch":
                    # Connection becomes a one-way event stream.
                    stream_task = asyncio.create_task(
                        self._stream_watch(writer, frame.fields["prefix"], frame.fields.get("initial", True))
                    )
                    continue
                if op == "replicate":
                    # Connection becomes a one-way replication-log stream.
                    stream_task = asyncio.create_task(self._stream_replicate(writer, frame.fields))
                    continue
                try:
                    result = await self._execute(op, frame.fields)
                    write_frame(writer, FrameType.DATA, rid=rid, p=result)
                except NotLeaderError as exc:
                    write_frame(
                        writer, FrameType.ERROR, rid=rid, error=str(exc),
                        kind="not_leader", leader=exc.leader,
                    )
                except KeyError as exc:
                    write_frame(writer, FrameType.ERROR, rid=rid, error=str(exc), kind="key")
                except Exception as exc:
                    logger.exception("store op %s failed", op)
                    write_frame(writer, FrameType.ERROR, rid=rid, error=str(exc), kind="internal")
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            if stream_task is not None:
                stream_task.cancel()
            writer.close()
            if task:
                self._conn_tasks.discard(task)

    async def _stream_watch(self, writer: asyncio.StreamWriter, prefix: str, initial: bool) -> None:
        try:
            async for event in self.store.watch_prefix(prefix, initial=initial):
                write_frame(
                    writer, FrameType.DATA,
                    p={"type": event.type.value, "key": event.key, "value": event.value},
                )
                await writer.drain()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("watch stream failed for %s", prefix)

    async def _stream_replicate(self, writer: asyncio.StreamWriter, fields: dict[str, Any]) -> None:
        """Serve one follower's log subscription: snapshot first, then every
        mutation record. The handshake is also an epoch fence in both
        directions — a follower that has seen a higher epoch proves this
        leader stale (it demotes), and a non-leader refuses outright."""
        repl = self.repl
        try:
            if repl is None:
                write_frame(writer, FrameType.ERROR, error="replication not enabled", kind="internal")
                await writer.drain()
                return
            follower_epoch = int(fields.get("epoch", 0) or 0)
            if follower_epoch > repl.epoch:
                write_frame(
                    writer, FrameType.ERROR, kind="stale_epoch", epoch=repl.epoch,
                    error=f"fenced: follower at epoch {follower_epoch} > leader {repl.epoch}",
                )
                await writer.drain()
                repl.note_stale(follower_epoch)
                return
            if repl.role != "leader":
                write_frame(
                    writer, FrameType.ERROR, kind="not_leader",
                    leader=repl.leader_url, error="not the store leader",
                )
                await writer.drain()
                return
            # Subscribe BEFORE snapshotting: a mutation landing in between
            # appears in both, and replay is idempotent; the follower skips
            # queued records with seq <= the snapshot's.
            queue = repl.subscribe()
            try:
                snapshot = await repl.export_snapshot()
                write_frame(
                    writer, FrameType.DATA,
                    p={"snapshot": snapshot, "e": repl.epoch, "s": repl.seq},
                )
                await writer.drain()
                logger.info("replica %s subscribed at (epoch %d, seq %d)",
                            fields.get("url", "?"), repl.epoch, repl.seq)
                while True:
                    rec = await queue.get()
                    if rec is None:  # coordinator demoted/closed: drop the stream
                        return
                    write_frame(writer, FrameType.DATA, p=rec)
                    await writer.drain()
            finally:
                repl.unsubscribe(queue)
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("replicate stream failed")

    async def _execute(self, op: str, f: dict[str, Any]) -> Any:
        s = self.store
        repl = self.repl
        if op == "who_leads":
            if repl is None:
                return {"role": "single", "leader": None, "epoch": 0, "seq": 0}
            return repl.status()
        if repl is not None and repl.role != "leader" and op in MUTATING_OPS:
            raise NotLeaderError(repl.leader_url)
        if op == "put":
            await s.put(f["key"], f["value"], lease_id=f.get("lease_id"))
            if repl is not None:
                repl.record("put", key=f["key"], value=f["value"], lease_id=f.get("lease_id"))
            return True
        if op == "put_if_absent":
            created = await s.put_if_absent(f["key"], f["value"], lease_id=f.get("lease_id"))
            if created and repl is not None:
                repl.record("put", key=f["key"], value=f["value"], lease_id=f.get("lease_id"))
            return created
        if op == "get":
            return await s.get(f["key"])
        if op == "get_prefix":
            return await s.get_prefix(f["prefix"])
        if op == "delete":
            existed = await s.delete(f["key"])
            if existed and repl is not None:
                repl.record("delete", key=f["key"])
            return existed
        if op == "create_lease":
            lease = await s.create_lease(f.get("ttl", DEFAULT_LEASE_TTL))
            if repl is not None:
                repl.record("lease", lease_id=lease.id, ttl=lease.ttl)
            return {"id": lease.id, "ttl": lease.ttl}
        if op == "keep_alive":
            await s.keep_alive(f["lease_id"])
            if repl is not None:
                ttl = getattr(s, "_lease_ttl", {}).get(f["lease_id"], DEFAULT_LEASE_TTL)
                repl.record("keepalive", lease_id=f["lease_id"], ttl=ttl)
            return True
        if op == "revoke_lease":
            await s.revoke_lease(f["lease_id"])
            if repl is not None:
                repl.record("revoke", lease_id=f["lease_id"])
            return True
        raise ValueError(f"unknown op {op!r}")

    async def close(self) -> None:
        if self.repl is not None:
            await self.repl.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for t in list(self._conn_tasks):
            t.cancel()
        # The served store may hold resources (e.g. a persistence WAL).
        await self.store.close()


class StoreClient(KeyValueStore):
    """KeyValueStore speaking the wire protocol. One shared RPC connection
    (correlated by request id), one dedicated connection per watch.

    With multiple endpoints the client is HA-aware: it discovers the leader
    via ``who_leads``, follows ``not_leader`` redirects, retries idempotent
    in-flight ops exactly once after a reconnect, and re-arms dropped watches
    against whichever replica is reachable (synthesizing DELETE events for
    keys that vanished during the outage)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        endpoints: list[tuple[str, int]] | None = None,
        failover_timeout_s: float = 5.0,
    ) -> None:
        self._endpoints = [(h, int(p)) for h, p in (endpoints or [(host, port)])]
        self._endpoint_idx = 0
        self._host, self._port = self._endpoints[0]
        self._failover_timeout_s = failover_timeout_s
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._rid = itertools.count(1)
        self._reader_task: asyncio.Task | None = None
        self._lock = asyncio.Lock()
        self._watch_writers: list[asyncio.StreamWriter] = []

    @classmethod
    def from_url(cls, url: str) -> "StoreClient":
        """``tcp://host:port`` or ``tcp://a:p1,tcp://b:p2,...`` (replica list)."""
        endpoints: list[tuple[str, int]] = []
        for part in url.split(","):
            part = part.strip()
            if not part:
                continue
            rest = part.split("://", 1)[-1]
            host, port = rest.rsplit(":", 1)
            endpoints.append((host, int(port)))
        if not endpoints:
            raise ValueError(f"no store endpoints in {url!r}")
        if len(endpoints) > 1:
            from dynamo_tpu.config import load_store_settings

            return cls(
                endpoints[0][0], endpoints[0][1], endpoints=endpoints,
                failover_timeout_s=load_store_settings().client_failover_s,
            )
        return cls(endpoints[0][0], endpoints[0][1])

    @property
    def _multi(self) -> bool:
        return len(self._endpoints) > 1

    async def _ensure(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        if not self._multi:
            self._reader, self._writer = await asyncio.open_connection(self._host, self._port)
            self._reader_task = asyncio.create_task(self._read_loop(self._reader, self._writer))
            return
        await self._connect_leader()

    async def _probe(self, host: str, port: int):
        """Open a connection and ask ``who_leads``; (reader, writer, info) on
        success, raising on any failure (caller walks the replica list)."""
        reader, writer = await asyncio.open_connection(host, port)
        try:
            write_frame(writer, FrameType.REQUEST, op="who_leads", rid=0)
            await writer.drain()
            frame = await asyncio.wait_for(read_frame(reader), 1.0)
            if frame is None or frame.type is not FrameType.DATA:
                raise ConnectionError("who_leads probe failed")
            return reader, writer, frame.payload
        except BaseException:
            writer.close()
            raise

    def _note_leader(self, info: dict, url: str) -> None:
        prev = _CLIENT_STATS["leader"]
        if prev is not None and prev != url:
            _CLIENT_STATS["failovers"] += 1
        _CLIENT_STATS["leader"] = url
        _CLIENT_STATS["role"] = info.get("role", "unknown")
        _CLIENT_STATS["epoch"] = max(_CLIENT_STATS["epoch"], int(info.get("epoch", 0) or 0))

    async def _connect_leader(self) -> None:
        """Walk the replica list until the leader answers; honors follower
        redirects and keeps trying (with backoff) until the failover window
        closes — promotion takes a beat after a leader SIGKILL."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self._failover_timeout_s
        delay = 0.05
        while True:
            hint: str | None = None
            for i in range(len(self._endpoints)):
                idx = (self._endpoint_idx + i) % len(self._endpoints)
                host, port = self._endpoints[idx]
                try:
                    reader, writer, info = await self._probe(host, port)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                if info.get("role") in ("leader", "single"):
                    self._endpoint_idx = idx
                    self._host, self._port = host, port
                    self._reader, self._writer = reader, writer
                    self._reader_task = asyncio.create_task(self._read_loop(reader, writer))
                    self._note_leader(info, f"tcp://{host}:{port}")
                    return
                writer.close()
                hint = hint or info.get("leader")
            if hint:
                for j, (h, p) in enumerate(self._endpoints):
                    if hint.endswith(f"{h}:{p}"):
                        self._endpoint_idx = j
                        break
            if loop.time() >= deadline:
                eps = ",".join(f"{h}:{p}" for h, p in self._endpoints)
                raise ConnectionError(f"no store leader reachable among {eps}")
            await asyncio.sleep(delay)
            delay = min(delay * 2, 0.5)

    async def _read_loop(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                fut = self._pending.pop(frame.fields.get("rid"), None)
                if fut is None or fut.done():
                    continue
                if frame.type is FrameType.ERROR:
                    kind = frame.fields.get("kind")
                    exc: Exception
                    if kind == "key":
                        exc = KeyError(frame.fields.get("error"))
                    elif kind == "not_leader":
                        exc = NotLeaderError(frame.fields.get("leader"))
                    else:
                        exc = RuntimeError(frame.fields.get("error"))
                    fut.set_exception(exc)
                else:
                    fut.set_result(frame.payload)
        finally:
            # Tear down this loop's connection so the next op reconnects
            # instead of writing into a dead socket and pending forever.
            writer.close()
            if self._writer is writer:
                self._writer = None
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("store connection lost"))
            self._pending.clear()

    async def _reset(self) -> None:
        async with self._lock:
            if self._reader_task is not None:
                self._reader_task.cancel()
                self._reader_task = None
            if self._writer is not None:
                self._writer.close()
                self._writer = None

    async def _call_once(self, op: str, fields: dict[str, Any]) -> Any:
        async with self._lock:
            await self._ensure()
            rid = next(self._rid)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[rid] = fut
            write_frame(self._writer, FrameType.REQUEST, op=op, rid=rid, **fields)
            await self._writer.drain()
        return await fut

    async def _call(self, op: str, **fields: Any) -> Any:
        if FAULTS.armed:
            FAULTS.fire("store.op")
        retried = False
        redirects = 0
        while True:
            try:
                return await self._call_once(op, fields)
            except NotLeaderError:
                # The op never executed server-side — always safe to chase
                # the redirect, bounded so flapping leadership can't loop us.
                redirects += 1
                if redirects > len(self._endpoints) + 1:
                    raise ConnectionError("store leadership unstable; giving up")
                await self._reset()
            except ConnectionError:
                # In-flight op at connection death: outcome unknown. Replay
                # exactly once iff replaying cannot change it (IDEMPOTENT_OPS).
                if retried or op not in IDEMPOTENT_OPS:
                    raise
                retried = True
                _CLIENT_STATS["retries"] += 1
                await self._reset()

    # -- KeyValueStore API -------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        await self._call("put", key=key, value=value, lease_id=lease_id)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        return await self._call("put_if_absent", key=key, value=value, lease_id=lease_id)

    async def get(self, key: str) -> bytes | None:
        return await self._call("get", key=key)

    async def get_prefix(self, prefix: str) -> dict[str, bytes]:
        return await self._call("get_prefix", prefix=prefix)

    async def delete(self, key: str) -> bool:
        return await self._call("delete", key=key)

    async def create_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease:
        d = await self._call("create_lease", ttl=ttl)
        return Lease(id=d["id"], ttl=d["ttl"], store=self)

    async def keep_alive(self, lease_id: int) -> None:
        await self._call("keep_alive", lease_id=lease_id)

    async def revoke_lease(self, lease_id: int) -> None:
        await self._call("revoke_lease", lease_id=lease_id)

    async def who_leads(self) -> dict:
        """Leadership view of whichever replica the RPC channel reaches."""
        return await self._call("who_leads")

    async def watch_prefix(self, prefix: str, initial: bool = True) -> AsyncIterator[WatchEvent]:
        if not self._multi:
            async for event in self._watch_single(prefix, initial):
                yield event
            return
        # HA watch: survive a replica death by re-arming against the next
        # reachable replica. Watches are served by followers too (they apply
        # the replicated log into their own store), so any live replica will
        # do. The server-side snapshot-on-subscribe replays PUTs; deletions
        # that happened during the outage are synthesized from the key set
        # this watch has already reported.
        known: set[str] = set()
        first = True
        down_since: float | None = None
        while True:
            conn = None
            for i in range(len(self._endpoints)):
                idx = (self._endpoint_idx + i) % len(self._endpoints)
                host, port = self._endpoints[idx]
                try:
                    conn = await asyncio.open_connection(host, port)
                    break
                except OSError:
                    continue
            if conn is None:
                now = asyncio.get_running_loop().time()
                down_since = down_since or now
                if now - down_since >= self._failover_timeout_s:
                    raise ConnectionError("watch stream closed")
                await asyncio.sleep(0.2)
                continue
            down_since = None
            reader, writer = conn
            self._watch_writers.append(writer)
            try:
                write_frame(
                    writer, FrameType.REQUEST, op="watch", prefix=prefix,
                    initial=True if not first else initial,
                )
                await writer.drain()
                if not first:
                    # Resync: anything we reported that no longer exists was
                    # deleted while we were dark. Diffed AFTER the subscribe
                    # frame so a concurrent delete lands in the diff or on the
                    # live stream — a duplicate DELETE is harmless, a missed
                    # one is not.
                    current = await self.get_prefix(prefix)
                    for key in sorted(known - set(current)):
                        known.discard(key)
                        yield WatchEvent(WatchEventType.DELETE, key, None)
                while True:
                    frame = await read_frame(reader)
                    if frame is None:
                        break  # replica died: re-arm on the next one
                    if FAULTS.armed:
                        FAULTS.fire("store.watch")
                    p = frame.payload
                    event = WatchEvent(WatchEventType(p["type"]), p["key"], p.get("value"))
                    if event.type is WatchEventType.PUT:
                        known.add(event.key)
                    else:
                        known.discard(event.key)
                    yield event
            finally:
                self._watch_writers.remove(writer)
                writer.close()
            first = False
            await asyncio.sleep(0.1)

    async def _watch_single(self, prefix: str, initial: bool) -> AsyncIterator[WatchEvent]:
        reader, writer = await asyncio.open_connection(self._host, self._port)
        self._watch_writers.append(writer)
        try:
            write_frame(writer, FrameType.REQUEST, op="watch", prefix=prefix, initial=initial)
            await writer.drain()
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    raise ConnectionError("watch stream closed")
                if FAULTS.armed:
                    FAULTS.fire("store.watch")
                p = frame.payload
                yield WatchEvent(WatchEventType(p["type"]), p["key"], p.get("value"))
        finally:
            self._watch_writers.remove(writer)
            writer.close()

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for w in list(self._watch_writers):
            w.close()
