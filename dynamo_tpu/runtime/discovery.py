"""Discovery / liveness plane: a pluggable key-value store with leases and watch.

This is the control-plane primitive under everything: instance registration,
model-card publication, dynamic config, barriers. Semantics follow etcd (the
reference's choice — `lib/runtime/src/transports/etcd.rs`): keys with byte
values, TTL leases that cascade-delete attached keys on expiry, and prefix
watches that stream PUT/DELETE events.

Implementations:
- :class:`MemoryStore` — in-process, used inside a single node and by tests.
- :class:`dynamo_tpu.runtime.store_server` — the same semantics served over
  TCP for multi-process / multi-host deployments (our etcd-equivalent).

An external etcd can be slotted in behind the same interface when available;
nothing above this module knows the difference.
"""

from __future__ import annotations

import abc
import asyncio
import time
from dataclasses import dataclass
from enum import Enum
from typing import AsyncIterator

from dynamo_tpu.runtime.faults import FAULTS


class WatchEventType(Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass(frozen=True)
class WatchEvent:
    type: WatchEventType
    key: str
    value: bytes | None  # None for DELETE


@dataclass
class Lease:
    """A liveness lease. Keys put with ``lease_id`` vanish when it expires.

    Default TTL mirrors the reference's 10s instance leases.
    """

    id: int
    ttl: float
    store: "KeyValueStore"

    async def keep_alive(self) -> None:
        await self.store.keep_alive(self.id)

    async def revoke(self) -> None:
        await self.store.revoke_lease(self.id)


DEFAULT_LEASE_TTL = 10.0


class KeyValueStore(abc.ABC):
    """etcd-shaped store: put/get/delete, prefix scan, TTL leases, prefix watch."""

    @abc.abstractmethod
    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None: ...

    @abc.abstractmethod
    async def get(self, key: str) -> bytes | None: ...

    @abc.abstractmethod
    async def get_prefix(self, prefix: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    async def delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    async def create_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease: ...

    @abc.abstractmethod
    async def keep_alive(self, lease_id: int) -> None: ...

    @abc.abstractmethod
    async def revoke_lease(self, lease_id: int) -> None: ...

    @abc.abstractmethod
    def watch_prefix(self, prefix: str, initial: bool = True) -> AsyncIterator[WatchEvent]:
        """Stream PUT/DELETE events under ``prefix``.

        With ``initial=True`` the current contents are first replayed as PUT
        events, so a watcher's world-model starts complete.
        """
        ...

    @abc.abstractmethod
    async def put_if_absent(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        """Atomic create. Returns False if the key already exists."""
        ...

    async def close(self) -> None:  # pragma: no cover - default no-op
        pass


class MemoryStore(KeyValueStore):
    """In-process store with full lease/watch semantics.

    Lease expiry is enforced by a lazy sweep on access plus an optional
    background reaper task, so tests can drive expiry deterministically with
    short TTLs.
    """

    def __init__(self, *, reap_interval: float = 1.0, clock=time.monotonic) -> None:
        self._data: dict[str, bytes] = {}
        self._key_lease: dict[str, int] = {}
        self._leases: dict[int, float] = {}  # lease_id -> deadline
        self._lease_ttl: dict[int, float] = {}
        self._lease_keys: dict[int, set[str]] = {}
        self._watchers: list[tuple[str, asyncio.Queue[WatchEvent]]] = []
        self._lease_next = 1
        self._clock = clock
        self._reap_interval = reap_interval
        self._reaper: asyncio.Task | None = None
        self._lock = asyncio.Lock()

    # -- internal ----------------------------------------------------------

    def _notify(self, event: WatchEvent) -> None:
        for prefix, queue in self._watchers:
            if event.key.startswith(prefix):
                queue.put_nowait(event)

    def _delete_key_locked(self, key: str) -> bool:
        if key not in self._data:
            return False
        del self._data[key]
        lease_id = self._key_lease.pop(key, None)
        if lease_id is not None and lease_id in self._lease_keys:
            self._lease_keys[lease_id].discard(key)
        self._notify(WatchEvent(WatchEventType.DELETE, key, None))
        return True

    async def _sweep_expired(self) -> None:
        now = self._clock()
        expired = [lid for lid, deadline in self._leases.items() if deadline <= now]
        for lid in expired:
            await self._revoke_locked(lid)

    async def _revoke_locked(self, lease_id: int) -> None:
        self._leases.pop(lease_id, None)
        self._lease_ttl.pop(lease_id, None)
        for key in sorted(self._lease_keys.pop(lease_id, set())):
            self._delete_key_locked(key)

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            try:
                self._reaper = asyncio.get_running_loop().create_task(self._reap_loop())
            except RuntimeError:  # no running loop (sync construction)
                pass

    async def _reap_loop(self) -> None:
        while True:
            await asyncio.sleep(self._reap_interval)
            async with self._lock:
                await self._sweep_expired()

    # -- KeyValueStore API -------------------------------------------------

    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        async with self._lock:
            await self._sweep_expired()
            if lease_id is not None and lease_id not in self._leases:
                raise KeyError(f"unknown or expired lease {lease_id}")
            self._data[key] = value
            old_lease = self._key_lease.pop(key, None)
            if old_lease is not None and old_lease in self._lease_keys:
                self._lease_keys[old_lease].discard(key)
            if lease_id is not None:
                self._key_lease[key] = lease_id
                self._lease_keys.setdefault(lease_id, set()).add(key)
            self._notify(WatchEvent(WatchEventType.PUT, key, value))

    async def put_if_absent(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        async with self._lock:
            await self._sweep_expired()
            if key in self._data:
                return False
            if lease_id is not None and lease_id not in self._leases:
                raise KeyError(f"unknown or expired lease {lease_id}")
            self._data[key] = value
            if lease_id is not None:
                self._key_lease[key] = lease_id
                self._lease_keys.setdefault(lease_id, set()).add(key)
            self._notify(WatchEvent(WatchEventType.PUT, key, value))
            return True

    async def get(self, key: str) -> bytes | None:
        async with self._lock:
            await self._sweep_expired()
            return self._data.get(key)

    async def get_prefix(self, prefix: str) -> dict[str, bytes]:
        async with self._lock:
            await self._sweep_expired()
            return {k: v for k, v in self._data.items() if k.startswith(prefix)}

    async def delete(self, key: str) -> bool:
        async with self._lock:
            await self._sweep_expired()
            return self._delete_key_locked(key)

    async def create_lease(self, ttl: float = DEFAULT_LEASE_TTL) -> Lease:
        async with self._lock:
            self._ensure_reaper()
            lid = self._lease_next
            self._lease_next += 1
            self._leases[lid] = self._clock() + ttl
            self._lease_ttl[lid] = ttl
            self._lease_keys[lid] = set()
            return Lease(id=lid, ttl=ttl, store=self)

    async def adopt_lease(self, lease_id: int, ttl: float) -> None:
        """Create — or re-arm — a lease under a *caller-chosen* id.

        The replication apply path: a follower mirrors the leader's lease ids
        so that lease-bound keys land under the same identity, and re-arms the
        deadline against its own monotonic clock on every replicated
        keepalive (absolute deadlines cannot be shipped across processes).
        The id counter is kept ahead of adopted ids so leases created after a
        promotion never collide.
        """
        async with self._lock:
            self._ensure_reaper()
            self._leases[lease_id] = self._clock() + ttl
            self._lease_ttl[lease_id] = ttl
            self._lease_keys.setdefault(lease_id, set())
            if lease_id >= self._lease_next:
                self._lease_next = lease_id + 1

    async def keep_alive(self, lease_id: int) -> None:
        if FAULTS.armed:
            FAULTS.fire("lease.keepalive")
        async with self._lock:
            await self._sweep_expired()
            if lease_id not in self._leases:
                raise KeyError(f"unknown or expired lease {lease_id}")
            self._leases[lease_id] = self._clock() + self._lease_ttl[lease_id]

    async def revoke_lease(self, lease_id: int) -> None:
        async with self._lock:
            await self._revoke_locked(lease_id)

    async def watch_prefix(self, prefix: str, initial: bool = True) -> AsyncIterator[WatchEvent]:
        queue: asyncio.Queue[WatchEvent] = asyncio.Queue()
        async with self._lock:
            await self._sweep_expired()
            snapshot = [(k, v) for k, v in self._data.items() if k.startswith(prefix)] if initial else []
            self._watchers.append((prefix, queue))
        try:
            for k, v in snapshot:
                if FAULTS.armed:
                    FAULTS.fire("store.watch")
                yield WatchEvent(WatchEventType.PUT, k, v)
            while True:
                event = await queue.get()
                if FAULTS.armed:
                    FAULTS.fire("store.watch")
                yield event
        finally:
            self._watchers.remove((prefix, queue))

    async def close(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
            self._reaper = None
