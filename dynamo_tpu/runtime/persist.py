"""Durable store state: a write-ahead log for non-ephemeral keys.

The deployment store holds two kinds of state: *ephemeral* records bound to
liveness leases (instances, metrics — their owners re-register after any
restart) and *declarative* records with no lease (GraphDeployments, static
model registrations, object-store chunks). A store-server restart must not
lose the declarative kind — that's the gap the reference fills with etcd's
own persistence; here the same durability comes from a JSONL WAL:

- every lease-less put/delete appends one line ``{"op", "key", "v": b64}``
- on start, the log is replayed into the fresh MemoryStore and compacted
  (one line per surviving key)

Lease-bound records are intentionally NOT persisted: restoring an instance
record whose owner died with the store would advertise a dead endpoint.

Usage: ``StoreServer(PersistentStore.open(path), ...)`` — or
``--store-persist PATH`` on the launch CLI's store role.
"""

from __future__ import annotations

import base64
import json
import logging
import pathlib
from typing import Any

from dynamo_tpu.runtime.discovery import MemoryStore

logger = logging.getLogger(__name__)


class PersistentStore(MemoryStore):
    """MemoryStore + WAL for lease-less writes."""

    def __init__(self, path: str | pathlib.Path) -> None:
        super().__init__()
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = None
        self._durable: set[str] = set()  # keys with a live WAL put entry
        import asyncio

        # Serializes appends with close(): an executor fsync must never race
        # a close of (and fd-number reuse after) the WAL file.
        self._wal_lock = asyncio.Lock()
        # Group commit: writers flush under _wal_lock and then wait for an
        # fsync that covers their entry; one writer at a time leads a batch
        # under _sync_lock, so N concurrent appends cost one fsync, not N.
        self._sync_lock = asyncio.Lock()
        self._wal_written = 0  # entries flushed to the fh
        self._wal_synced = 0  # entries covered by a completed fsync

    @classmethod
    async def open(cls, path: str | pathlib.Path) -> "PersistentStore":
        store = cls(path)
        await store._replay_and_compact()
        store._fh = store.path.open("a")
        return store

    async def _replay_and_compact(self) -> None:
        if not self.path.exists():
            return
        state: dict[str, bytes] = {}
        lines = 0
        # Decode per line: a torn write after a crash may leave non-UTF-8
        # garbage in the tail, and that exact scenario must not block start.
        for raw in self.path.read_bytes().splitlines():
            if not raw.strip():
                continue
            lines += 1
            try:
                doc = json.loads(raw.decode("utf-8"))
                if doc["op"] == "put":
                    state[doc["key"]] = base64.b64decode(doc["v"])
                elif doc["op"] == "delete":
                    state.pop(doc["key"], None)
            except Exception:
                logger.warning("skipping corrupt WAL line in %s", self.path)
        for key, value in state.items():
            await super().put(key, value)
        self._durable = set(state)
        # Compact: rewrite one put per surviving key (atomic replace).
        tmp = self.path.with_suffix(".compact")
        with tmp.open("w") as fh:
            for key, value in state.items():
                fh.write(self._entry("put", key, value))
        tmp.replace(self.path)
        logger.info(
            "store WAL %s: replayed %d lines -> %d keys", self.path, lines, len(state)
        )

    @staticmethod
    def _entry(op: str, key: str, value: bytes | None = None) -> str:
        doc: dict[str, Any] = {"op": op, "key": key}
        if value is not None:
            doc["v"] = base64.b64encode(value).decode()
        return json.dumps(doc) + "\n"

    async def _append(self, op: str, key: str, value: bytes | None = None) -> None:
        import asyncio
        import os

        async with self._wal_lock:
            if self._fh is None:
                return
            self._fh.write(self._entry(op, key, value))
            self._fh.flush()
            if op == "put":
                self._durable.add(key)
            else:
                self._durable.discard(key)
            self._wal_written += 1
            mine = self._wal_written
        # Group commit: don't return before an fsync covers this entry, but
        # let one fsync cover every entry flushed before it started. With a
        # single uncontended writer this is exactly one fsync per mutation —
        # the pre-batching behavior; under concurrency (replication makes the
        # leader's WAL the hot path) waiters coalesce behind the leader of
        # the current batch. fsync itself is a blocking syscall, so it runs
        # in the executor, off the store server's event loop (a stalled loop
        # delays every op and lease keepalive).
        while self._wal_synced < mine:
            async with self._sync_lock:
                if self._wal_synced >= mine:
                    break
                async with self._wal_lock:
                    if self._fh is None:
                        return
                    covers = self._wal_written
                    fileno = self._fh.fileno()
                # _sync_lock keeps the fd alive: close() takes it before
                # closing the log, so the executor fsync never races an
                # fd-number reuse.
                await asyncio.get_running_loop().run_in_executor(None, os.fsync, fileno)
                self._wal_synced = covers

    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        await super().put(key, value, lease_id=lease_id)
        if lease_id is None:
            await self._append("put", key, value)
        elif key in self._durable:
            # A previously durable key rewritten lease-bound: its lifetime is
            # now lease-governed (expiry bypasses delete()), so scrub the
            # stale WAL entry. Ephemeral-only keys never touch the WAL.
            await self._append("delete", key)

    async def put_if_absent(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        created = await super().put_if_absent(key, value, lease_id=lease_id)
        if created and lease_id is None:
            await self._append("put", key, value)
        return created

    async def delete(self, key: str) -> bool:
        existed = await super().delete(key)
        if existed and key in self._durable:
            await self._append("delete", key)
        return existed

    async def close(self) -> None:
        async with self._sync_lock:
            async with self._wal_lock:
                self.close_log()
        await super().close()

    def close_log(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
