"""Store replication: leader/follower log shipping with epoch fencing.

The deployment store (``runtime/store_server.py``) stays a single-writer
system — one *leader* serializes every mutation — but gains standby
*followers* that make leader death a survivable event:

- The leader streams every mutation — lease-less puts/deletes **and** lease
  create/keepalive/revoke — to each follower over the existing frame
  protocol (a follower opens an ``op="replicate"`` subscription; the
  connection becomes a one-way stream of records, exactly like a watch).
- Each record is stamped with ``(epoch, seq)``: the epoch is bumped on every
  leadership change, the sequence number is globally monotone. A follower
  applies records into its own ``MemoryStore``/``PersistentStore`` through
  the normal store API, so local reads, watches — and the WAL, when the
  backing store persists — all work unchanged.
- Lease deadlines are clock-relative and cannot be shipped: a follower
  re-arms each lease against *its own* monotonic clock on every replicated
  keepalive (``MemoryStore.adopt_lease``). A follower's deadline therefore
  trails the leader's by at most the replication lag — leases never expire
  *early* on a replica, which is what keeps worker instances registered
  across a failover.
- On leader death the freshest follower promotes: candidates rank by
  ``(epoch, seq)`` with the replica-list index as the deterministic
  tie-break, and a follower promotes only when it is the best *reachable*
  candidate. Promotion bumps the epoch; a stale ex-leader is fenced by it —
  its replicate handshakes and records are rejected by any peer that has
  seen a higher epoch, and on demotion it resyncs from the new leader's
  snapshot, discarding any divergent writes. There is never a window where
  two replicas both *win*: the rank order is total.

Single-replica deployments never construct a coordinator: ``StoreServer``
with ``repl is None`` takes exactly the pre-replication code paths.

Chaos seams: ``store.replicate`` fires per record on the follower's apply
path (drop/corrupt force a resync), ``store.promote`` fires at the top of
:meth:`ReplicationCoordinator.promote` (crash aborts the promotion and the
next-ranked candidate takes over on a later poll).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any

from dynamo_tpu.runtime.codec import FrameType, read_frame, write_frame
from dynamo_tpu.runtime.faults import FAULTS

logger = logging.getLogger(__name__)

#: Ops whose successful execution the leader ships to followers.
REPLICATED_OPS = ("put", "delete", "lease", "keepalive", "revoke")


class ReplicaDesync(Exception):
    """The follower's view diverged (gap / corrupt record): full resync."""


class StaleLeaderError(Exception):
    """The peer we follow announced an epoch older than ours: fence it."""


def parse_peer(url: str) -> tuple[str, int]:
    """``tcp://host:port`` -> ``(host, port)``."""
    rest = url.split("://", 1)[-1]
    host, port = rest.rsplit(":", 1)
    return host, int(port)


@dataclass
class ReplicaConfig:
    """Identity + knobs of one replica (see ``StoreSettings`` / DYN_STORE_*)."""

    url: str  # this replica's advertised tcp://host:port
    peers: tuple[str, ...]  # the full replica list, in priority order
    index: int  # this replica's position in ``peers``
    promote_after_s: float = 1.0  # leaderless window before an election
    poll_s: float = 0.25  # peer who_leads poll cadence
    epoch_grace_s: float = 0.0  # extra lease grace granted at promotion


async def _rpc(url: str, op: str, *, timeout: float = 1.0, **fields: Any) -> dict | None:
    """One-shot request to a peer replica; None when unreachable/errored."""
    host, port = parse_peer(url)
    try:
        reader, writer = await asyncio.wait_for(asyncio.open_connection(host, port), timeout)
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        write_frame(writer, FrameType.REQUEST, op=op, rid=0, **fields)
        await writer.drain()
        frame = await asyncio.wait_for(read_frame(reader), timeout)
        if frame is None or frame.type is not FrameType.DATA:
            return None
        return frame.payload
    except (OSError, asyncio.TimeoutError, ConnectionError):
        return None
    finally:
        writer.close()


class ReplicationCoordinator:
    """Replication + failover state machine attached to one ``StoreServer``.

    The server calls :meth:`record` after each applied mutation (leader) and
    :meth:`status` for ``who_leads``; the coordinator owns the follower link,
    elections, and the leader's usurper watchdog.
    """

    def __init__(self, server, config: ReplicaConfig) -> None:
        self.server = server
        self.cfg = config
        bootstrap_leader = config.index == 0
        self.role = "leader" if bootstrap_leader else "follower"
        self.epoch = 1 if bootstrap_leader else 0
        self.seq = 0  # last assigned (leader) / last applied (follower)
        self.leader_url: str | None = config.peers[0] if config.peers else None
        self.failovers = 0  # leadership changes this replica observed
        self.lag_s = 0.0  # follower: wall-clock age of the last applied record
        self._subs: list[asyncio.Queue] = []
        self._task: asyncio.Task | None = None
        self._closed = False

    # -- leader side -------------------------------------------------------

    def record(self, op: str, **fields: Any) -> None:
        """Stamp one applied mutation and fan it out to follower streams."""
        if self.role != "leader":
            return
        self.seq += 1
        rec = {"e": self.epoch, "s": self.seq, "ts": time.time(), "op": op, **fields}
        for q in list(self._subs):
            q.put_nowait(rec)

    def subscribe(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._subs.append(q)
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        if q in self._subs:
            self._subs.remove(q)

    async def export_snapshot(self) -> dict:
        """Full store state for a (re)joining follower.

        Lease deadlines are shipped as TTLs: the follower re-arms each lease
        from its own clock on adoption, which can only *extend* liveness.
        """
        store = self.server.store
        async with store._lock:  # noqa: SLF001 - replication is a store-internal plane
            return {
                "data": dict(store._data),
                "key_lease": dict(store._key_lease),
                "leases": {str(lid): store._lease_ttl[lid] for lid in store._leases},
            }

    def note_stale(self, seen_epoch: int) -> None:
        """A peer proved a higher epoch exists: fence ourselves (demote)."""
        if self.role == "leader" and seen_epoch > self.epoch:
            logger.warning(
                "store replica %s fenced: saw epoch %d > own %d; demoting",
                self.cfg.url, seen_epoch, self.epoch,
            )
            self.role = "follower"
            self.failovers += 1
            self._kick_subscribers()
            self._respawn()

    # -- follower side -----------------------------------------------------

    async def start(self) -> "ReplicationCoordinator":
        if self._task is None:
            loop = self._leader_watchdog() if self.role == "leader" else self._follower_loop()
            self._task = asyncio.create_task(loop)
        return self

    def _respawn(self) -> None:
        """Restart the role loop after a role change from outside the task."""
        if self._closed:
            return
        old = self._task
        self._task = None
        if old is not None and old is not asyncio.current_task():
            old.cancel()
        loop = self._leader_watchdog() if self.role == "leader" else self._follower_loop()
        self._task = asyncio.create_task(loop)

    async def _follower_loop(self) -> None:
        down_since: float | None = None
        clock = time.monotonic
        while not self._closed:
            leader = await self._find_leader()
            if leader is not None and leader != self.cfg.url:
                down_since = None
                try:
                    await self._follow(leader)
                except StaleLeaderError:
                    pass  # fence held; poll again for the real leader
                except ReplicaDesync as exc:
                    logger.warning("store replica %s desync (%s); resyncing", self.cfg.url, exc)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.info("replication link from %s dropped (%s)", leader, exc)
                down_since = down_since or clock()
            else:
                down_since = down_since or clock()
                if clock() - down_since >= self.cfg.promote_after_s and await self._should_promote():
                    try:
                        await self.promote()
                        return  # promote() respawned us as the leader watchdog
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        logger.warning("promotion of %s aborted (%s)", self.cfg.url, exc)
            await asyncio.sleep(self.cfg.poll_s)

    async def _find_leader(self) -> str | None:
        """The url of the current leader, by asking every peer (None if none).

        A leader claim is only believed at an epoch >= our own — the fence
        that stops a rebooted stale ex-leader from recapturing its followers.
        """
        if self.leader_url is not None and self.leader_url != self.cfg.url:
            info = await _rpc(self.leader_url, "who_leads", timeout=self.cfg.poll_s + 0.25)
            if info is not None and info.get("role") == "leader" and info.get("epoch", 0) >= self.epoch:
                return self.leader_url
        for peer in self.cfg.peers:
            if peer == self.cfg.url:
                continue
            info = await _rpc(peer, "who_leads", timeout=self.cfg.poll_s + 0.25)
            if info is None:
                continue
            if info.get("role") == "leader" and info.get("epoch", 0) >= self.epoch:
                return peer
            hint = info.get("leader")
            if hint and hint not in (self.cfg.url, peer):
                hinted = await _rpc(hint, "who_leads", timeout=self.cfg.poll_s + 0.25)
                if hinted is not None and hinted.get("role") == "leader" and hinted.get("epoch", 0) >= self.epoch:
                    return hint
        return None

    async def _should_promote(self) -> bool:
        """Am I the best-ranked reachable candidate? Rank: (epoch, seq, -index).

        The order is total (indices are unique), so at most one reachable
        follower can answer yes for any consistent view of the peer set.
        """
        mine = (self.epoch, self.seq, -self.cfg.index)
        for i, peer in enumerate(self.cfg.peers):
            if peer == self.cfg.url:
                continue
            info = await _rpc(peer, "who_leads", timeout=self.cfg.poll_s + 0.25)
            if info is None:
                continue
            if info.get("role") == "leader" and info.get("epoch", 0) >= self.epoch:
                return False  # a live leader exists after all
            theirs = (info.get("epoch", 0), info.get("seq", 0), -i)
            if theirs > mine:
                return False
        return True

    async def promote(self) -> None:
        """Become the leader: bump the epoch and grant every lease one fresh
        TTL of grace (replicated keepalives may trail by the replication lag,
        and their owners need a failover window to rediscover the leader)."""
        if FAULTS.armed:
            FAULTS.fire("store.promote")
        store = self.server.store
        async with store._lock:  # noqa: SLF001
            now = store._clock()
            for lid, ttl in store._lease_ttl.items():
                if lid in store._leases:
                    store._leases[lid] = max(
                        store._leases[lid], now + ttl + self.cfg.epoch_grace_s
                    )
        self.epoch += 1
        self.role = "leader"
        self.leader_url = self.cfg.url
        self.failovers += 1
        self.lag_s = 0.0
        logger.warning(
            "store replica %s promoted to leader (epoch %d, seq %d)",
            self.cfg.url, self.epoch, self.seq,
        )
        self._respawn()

    async def _follow(self, leader_url: str) -> None:
        """Hold one replicate stream from ``leader_url``: snapshot, then apply
        records until the stream drops or the fence trips."""
        host, port = parse_peer(leader_url)
        reader, writer = await asyncio.open_connection(host, port)
        try:
            write_frame(
                writer, FrameType.REQUEST, op="replicate", rid=0,
                epoch=self.epoch, url=self.cfg.url,
            )
            await writer.drain()
            first = await read_frame(reader)
            if first is None:
                raise ConnectionError("replicate handshake closed")
            if first.type is FrameType.ERROR:
                if first.fields.get("kind") == "stale_epoch":
                    raise StaleLeaderError(first.fields.get("error", "stale leader"))
                raise ConnectionError(first.fields.get("error", "replicate rejected"))
            head = first.payload
            if head.get("e", 0) < self.epoch:
                raise StaleLeaderError(f"leader epoch {head.get('e')} < own {self.epoch}")
            await self._apply_snapshot(head["snapshot"])
            self.epoch = head["e"]
            self.seq = head["s"]
            self.leader_url = leader_url
            self.lag_s = 0.0
            logger.info(
                "store replica %s following %s from (epoch %d, seq %d)",
                self.cfg.url, leader_url, self.epoch, self.seq,
            )
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    raise ConnectionError("replicate stream closed")
                rec = frame.payload
                if FAULTS.armed and FAULTS.fire("store.replicate") == "corrupt":
                    raise ReplicaDesync("injected corrupt replication record")
                if rec["e"] < self.epoch:
                    raise StaleLeaderError(f"record epoch {rec['e']} < own {self.epoch}")
                if rec["s"] <= self.seq:
                    continue  # already covered by the snapshot
                if rec["s"] != self.seq + 1:
                    raise ReplicaDesync(f"seq gap: {rec['s']} after {self.seq}")
                await self._apply_record(rec)
                self.epoch = rec["e"]
                self.seq = rec["s"]
                self.lag_s = max(0.0, time.time() - rec.get("ts", time.time()))
        finally:
            writer.close()

    async def _apply_snapshot(self, snap: dict) -> None:
        """Reconcile the local store to the leader's snapshot (not replace):
        unchanged keys are left alone so local watchers see real deltas only,
        plus idempotent re-puts for anything the stream may replay."""
        store = self.server.store
        for lid_s, ttl in snap.get("leases", {}).items():
            await store.adopt_lease(int(lid_s), float(ttl))
        want = snap.get("data", {})
        key_lease = snap.get("key_lease", {})
        have = await store.get_prefix("")
        for key in sorted(set(have) - set(want)):
            await store.delete(key)
        for key, value in want.items():
            lease_id = key_lease.get(key)
            if have.get(key) != value or store._key_lease.get(key) != lease_id:  # noqa: SLF001
                await store.put(key, value, lease_id=lease_id)
        live = {int(lid_s) for lid_s in snap.get("leases", {})}
        for lid in sorted(set(store._leases) - live):  # noqa: SLF001
            await store.revoke_lease(lid)

    async def _apply_record(self, rec: dict) -> None:
        store = self.server.store
        op = rec["op"]
        if op == "put":
            await store.put(rec["key"], rec["value"], lease_id=rec.get("lease_id"))
        elif op == "delete":
            await store.delete(rec["key"])
        elif op in ("lease", "keepalive"):
            await store.adopt_lease(rec["lease_id"], rec["ttl"])
        elif op == "revoke":
            await store.revoke_lease(rec["lease_id"])
        else:
            raise ReplicaDesync(f"unknown replicated op {op!r}")

    async def _leader_watchdog(self) -> None:
        """Leader-side fence: poll peers and demote on sight of a higher epoch
        (covers the partition-heal case where no follower dials us first)."""
        while not self._closed and self.role == "leader":
            await asyncio.sleep(max(self.cfg.poll_s * 4, 0.5))
            for peer in self.cfg.peers:
                if peer == self.cfg.url or self.role != "leader":
                    continue
                info = await _rpc(peer, "who_leads", timeout=self.cfg.poll_s + 0.25)
                if info is not None and info.get("epoch", 0) > self.epoch:
                    self.note_stale(info["epoch"])
                    return  # note_stale respawned us as a follower

    # -- shared ------------------------------------------------------------

    def status(self) -> dict:
        return {
            "role": self.role,
            "epoch": self.epoch,
            "seq": self.seq,
            "leader": self.cfg.url if self.role == "leader" else self.leader_url,
            "url": self.cfg.url,
            "lag_s": self.lag_s,
            "failovers": self.failovers,
        }

    def _kick_subscribers(self) -> None:
        for q in list(self._subs):
            q.put_nowait(None)  # sentinel: server closes the stream

    async def close(self) -> None:
        global _LOCAL
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._kick_subscribers()
        # A closed coordinator must stop advertising a role: leaving it in
        # the in-process registry would make replica_snapshot() shadow the
        # client-side failover view in FrontendMetrics.render().
        if _LOCAL is self:
            _LOCAL = None


#: In-process replica registry (metrics): the last coordinator constructed in
#: this process, surfaced by ``frontend/metrics.py`` as dynamo_store_role /
#: dynamo_store_epoch / dynamo_store_replication_lag_seconds.
_LOCAL: ReplicationCoordinator | None = None


def attach_replication(server, peers: list[str] | tuple[str, ...], index: int, **knobs: Any) -> ReplicationCoordinator:
    """Wire a coordinator onto a started ``StoreServer`` and register it for
    in-process observability. ``peers`` must include this replica's own url at
    position ``index``."""
    global _LOCAL
    cfg = ReplicaConfig(url=peers[index], peers=tuple(peers), index=index, **knobs)
    coord = ReplicationCoordinator(server, cfg)
    server.repl = coord
    _LOCAL = coord
    return coord


def replica_snapshot() -> dict | None:
    """Role/epoch/lag of the replica hosted in this process (None if none)."""
    if _LOCAL is None:
        return None
    return {
        "role": _LOCAL.role,
        "epoch": _LOCAL.epoch,
        "seq": _LOCAL.seq,
        "lag_s": _LOCAL.lag_s,
        "failovers": _LOCAL.failovers,
    }


__all__ = [
    "REPLICATED_OPS",
    "ReplicaConfig",
    "ReplicaDesync",
    "ReplicationCoordinator",
    "StaleLeaderError",
    "attach_replication",
    "parse_peer",
    "replica_snapshot",
]
