"""Request/response data-plane abstraction + in-memory implementation.

A ``Transport`` carries one operation: open a response stream on a remote
engine registered under a *subject* (the flattened endpoint address of one
instance). Workers bind subjects to engines; callers call ``generate``.

Design note vs the reference: the reference pushes requests through NATS and
opens a TCP connection *back* from worker to caller for the response stream
(`egress/addressed_router.rs:80-178`). With no broker dependency here, the
TCP transport (:mod:`dynamo_tpu.runtime.tcp`) uses a single caller->worker
connection for both directions — one less hop and no broker on the token hot
path. Queueing semantics (the other thing the broker provided) live in
:mod:`dynamo_tpu.runtime.queue` instead.

The in-memory transport fakes the full network contract in-process (including
serialization round-trips and stop/kill control frames) so distributed
pipelines are testable without sockets — the analog of the reference's
MockNetworkTransport test fixture (`lib/runtime/tests/common/mock.rs`).
"""

from __future__ import annotations

import abc
import asyncio
from typing import Any, AsyncIterator

import msgpack

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineError


class NoSuchSubjectError(KeyError):
    """The target instance does not serve this subject (stale discovery, dead worker)."""


class DuplexUnsupportedError(EngineError):
    """The transport or remote subject has no duplex data plane (wire v3)."""


class DuplexStream(abc.ABC):
    """Caller half of a persistent bidirectional stream (wire v3 data plane).

    ``send`` pushes one message — a small fields dict plus optional raw blob
    buffers carried outside msgpack — and ``recv`` returns the engine's next
    response dict (None once the engine side completes)."""

    @abc.abstractmethod
    async def send(self, fields: dict[str, Any], blobs: list[Any] | None = None) -> None: ...

    @abc.abstractmethod
    async def recv(self) -> dict[str, Any] | None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Transport(abc.ABC):
    """Binds engines to subjects (worker side) and opens streams (caller side)."""

    @abc.abstractmethod
    async def register_engine(self, subject: str, engine: AsyncEngine[Any, Any]) -> None: ...

    @abc.abstractmethod
    async def unregister_engine(self, subject: str) -> None: ...

    @abc.abstractmethod
    def generate(self, address: str, request: Any, context: Context) -> AsyncIterator[Any]:
        """Open a response stream on the engine at ``address`` (subject or URL)."""
        ...

    @abc.abstractmethod
    def address_of(self, subject: str) -> str:
        """The externally-dialable address for a locally-registered subject."""
        ...

    async def open_duplex(self, address: str, request: Any, context: Context) -> DuplexStream:
        """Open a duplex stream to an engine exposing a ``duplex`` method.

        Default: unsupported — callers fall back to the request/response
        plane (e.g. KV wire v3 striping falls back to chunked v2)."""
        raise DuplexUnsupportedError(f"{type(self).__name__} has no duplex data plane")

    async def close(self) -> None:  # pragma: no cover - default no-op
        pass


class InMemoryTransport(Transport):
    """In-process transport with network-faithful semantics.

    Payloads are round-tripped through msgpack so anything non-serializable
    fails here exactly as it would on the wire; cancellation crosses the
    "network" via the context chain exactly as STOP/KILL frames would.
    """

    def __init__(self, *, serialize: bool = True) -> None:
        self._engines: dict[str, AsyncEngine[Any, Any]] = {}
        self._serialize = serialize

    async def register_engine(self, subject: str, engine: AsyncEngine[Any, Any]) -> None:
        if subject in self._engines:
            raise ValueError(f"subject already registered: {subject}")
        self._engines[subject] = engine

    async def unregister_engine(self, subject: str) -> None:
        self._engines.pop(subject, None)

    def address_of(self, subject: str) -> str:
        return f"mem://{subject}"

    def _roundtrip(self, obj: Any) -> Any:
        if not self._serialize:
            return obj
        return msgpack.unpackb(msgpack.packb(obj, use_bin_type=True), raw=False)

    async def generate(self, address: str, request: Any, context: Context) -> AsyncIterator[Any]:
        subject = address.removeprefix("mem://")
        engine = self._engines.get(subject)
        if engine is None:
            raise NoSuchSubjectError(subject)
        remote_ctx = context.child()
        stream = engine.generate(self._roundtrip(request), remote_ctx)
        try:
            while True:
                try:
                    item = await anext(stream)
                except StopAsyncIteration:
                    break
                except Exception as exc:
                    # On the wire an engine failure arrives as an ERROR frame;
                    # keep the in-process contract identical.
                    raise EngineError(f"{type(exc).__name__}: {exc}") from exc
                if context.is_killed:
                    break
                yield self._roundtrip(item)
        finally:
            remote_ctx.kill()
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()

    async def open_duplex(self, address: str, request: Any, context: Context) -> DuplexStream:
        subject = address.removeprefix("mem://")
        engine = self._engines.get(subject)
        if engine is None:
            raise NoSuchSubjectError(subject)
        duplex_fn = getattr(engine, "duplex", None)
        if duplex_fn is None:
            raise DuplexUnsupportedError(f"subject has no duplex data plane: {subject}")
        remote_ctx = context.child()
        stream = _InMemoryDuplexStream(self._roundtrip, remote_ctx)
        engine_stream = duplex_fn(self._roundtrip(request), stream._inbound_iter(), remote_ctx)

        async def drive() -> None:
            try:
                async for item in engine_stream:
                    await stream._outbound.put(stream._roundtrip(item))
                await stream._outbound.put(None)
            except Exception as exc:
                await stream._outbound.put(
                    EngineError(f"{type(exc).__name__}: {exc}"))
            finally:
                aclose = getattr(engine_stream, "aclose", None)
                if aclose is not None:
                    await aclose()

        stream._task = asyncio.create_task(drive())
        return stream


class _InMemoryDuplexStream(DuplexStream):
    """In-process duplex with network-faithful serialization: fields round-trip
    through msgpack with the blob carried as one bytes field (the wire carries
    it as a raw body; bytes-equivalence is what matters to the receiver)."""

    def __init__(self, roundtrip: Any, remote_ctx: Context) -> None:
        self._roundtrip = roundtrip
        self._remote_ctx = remote_ctx
        self._inbound: asyncio.Queue[dict[str, Any] | None] = asyncio.Queue()
        self._outbound: asyncio.Queue[Any] = asyncio.Queue()
        self._task: asyncio.Task | None = None

    async def _inbound_iter(self) -> AsyncIterator[dict[str, Any]]:
        while True:
            item = await self._inbound.get()
            if item is None:
                return
            yield item

    async def send(self, fields: dict[str, Any], blobs: list[Any] | None = None) -> None:
        msg = dict(fields)
        if blobs:
            msg["blob"] = b"".join(bytes(b) for b in blobs)
        await self._inbound.put(self._roundtrip(msg))

    async def recv(self) -> dict[str, Any] | None:
        item = await self._outbound.get()
        if isinstance(item, EngineError):
            raise item
        return item

    async def close(self) -> None:
        await self._inbound.put(None)
        if self._task is not None:
            try:
                await asyncio.wait_for(asyncio.shield(self._task), timeout=5.0)
            except Exception:
                self._remote_ctx.kill()
                self._task.cancel()


class _EchoEngine(AsyncEngine[Any, Any]):
    """Diagnostic engine: streams the request back once (used in tests/smoke)."""

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        yield request


__all__ = [
    "Transport",
    "DuplexStream",
    "DuplexUnsupportedError",
    "InMemoryTransport",
    "NoSuchSubjectError",
    "EngineError",
]
