"""Request/response data-plane abstraction + in-memory implementation.

A ``Transport`` carries one operation: open a response stream on a remote
engine registered under a *subject* (the flattened endpoint address of one
instance). Workers bind subjects to engines; callers call ``generate``.

Design note vs the reference: the reference pushes requests through NATS and
opens a TCP connection *back* from worker to caller for the response stream
(`egress/addressed_router.rs:80-178`). With no broker dependency here, the
TCP transport (:mod:`dynamo_tpu.runtime.tcp`) uses a single caller->worker
connection for both directions — one less hop and no broker on the token hot
path. Queueing semantics (the other thing the broker provided) live in
:mod:`dynamo_tpu.runtime.queue` instead.

The in-memory transport fakes the full network contract in-process (including
serialization round-trips and stop/kill control frames) so distributed
pipelines are testable without sockets — the analog of the reference's
MockNetworkTransport test fixture (`lib/runtime/tests/common/mock.rs`).
"""

from __future__ import annotations

import abc
import asyncio
from typing import Any, AsyncIterator

import msgpack

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineError


class NoSuchSubjectError(KeyError):
    """The target instance does not serve this subject (stale discovery, dead worker)."""


class Transport(abc.ABC):
    """Binds engines to subjects (worker side) and opens streams (caller side)."""

    @abc.abstractmethod
    async def register_engine(self, subject: str, engine: AsyncEngine[Any, Any]) -> None: ...

    @abc.abstractmethod
    async def unregister_engine(self, subject: str) -> None: ...

    @abc.abstractmethod
    def generate(self, address: str, request: Any, context: Context) -> AsyncIterator[Any]:
        """Open a response stream on the engine at ``address`` (subject or URL)."""
        ...

    @abc.abstractmethod
    def address_of(self, subject: str) -> str:
        """The externally-dialable address for a locally-registered subject."""
        ...

    async def close(self) -> None:  # pragma: no cover - default no-op
        pass


class InMemoryTransport(Transport):
    """In-process transport with network-faithful semantics.

    Payloads are round-tripped through msgpack so anything non-serializable
    fails here exactly as it would on the wire; cancellation crosses the
    "network" via the context chain exactly as STOP/KILL frames would.
    """

    def __init__(self, *, serialize: bool = True) -> None:
        self._engines: dict[str, AsyncEngine[Any, Any]] = {}
        self._serialize = serialize

    async def register_engine(self, subject: str, engine: AsyncEngine[Any, Any]) -> None:
        if subject in self._engines:
            raise ValueError(f"subject already registered: {subject}")
        self._engines[subject] = engine

    async def unregister_engine(self, subject: str) -> None:
        self._engines.pop(subject, None)

    def address_of(self, subject: str) -> str:
        return f"mem://{subject}"

    def _roundtrip(self, obj: Any) -> Any:
        if not self._serialize:
            return obj
        return msgpack.unpackb(msgpack.packb(obj, use_bin_type=True), raw=False)

    async def generate(self, address: str, request: Any, context: Context) -> AsyncIterator[Any]:
        subject = address.removeprefix("mem://")
        engine = self._engines.get(subject)
        if engine is None:
            raise NoSuchSubjectError(subject)
        remote_ctx = context.child()
        stream = engine.generate(self._roundtrip(request), remote_ctx)
        try:
            while True:
                try:
                    item = await anext(stream)
                except StopAsyncIteration:
                    break
                except Exception as exc:
                    # On the wire an engine failure arrives as an ERROR frame;
                    # keep the in-process contract identical.
                    raise EngineError(f"{type(exc).__name__}: {exc}") from exc
                if context.is_killed:
                    break
                yield self._roundtrip(item)
        finally:
            remote_ctx.kill()
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()


class _EchoEngine(AsyncEngine[Any, Any]):
    """Diagnostic engine: streams the request back once (used in tests/smoke)."""

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        yield request


__all__ = [
    "Transport",
    "InMemoryTransport",
    "NoSuchSubjectError",
    "EngineError",
]
