"""The core streaming-engine abstraction and request lifecycle.

Every stage in a serving pipeline — preprocessor, router, backend, the JAX
engine itself — implements the same shape: ``generate(request, context) ->
async stream of responses``. Composition of stages is then uniform, and a
pipeline can be split across processes at any stage boundary by inserting the
network transport (which itself implements the same shape).

Capability parity: reference `lib/runtime/src/engine.rs:124-212` (AsyncEngine
trait + AsyncEngineContext stop/kill lifecycle) and
`lib/runtime/src/pipeline.rs` operator edges.
"""

from __future__ import annotations

import abc
import asyncio
import uuid
from typing import Any, AsyncIterator, Generic, TypeVar

Req = TypeVar("Req")
Resp = TypeVar("Resp")


class EngineError(RuntimeError):
    """Raised inside a response stream when the producing engine fails."""


class Context:
    """Per-request lifecycle handle flowing through every pipeline stage.

    Two levels of cancellation, matching the reference semantics:

    - ``stop_generating()`` — graceful: the engine should finish the current
      step, emit any final usage/stop metadata, and end the stream.
    - ``kill()`` — hard: tear the stream down immediately (implies stop).

    Contexts form a chain: child contexts (created when a stage issues its own
    downstream request) propagate cancellation downward.

    ``trace`` carries the distributed trace identity (a plain
    ``{"trace_id", "span_id"}`` dict — serializable form of
    :class:`dynamo_tpu.tracing.TraceContext`) through every stage: the
    frontend mints it, operators pass the context (or a child) downstream,
    and the network transport forwards it on the wire so spans on remote
    workers link back to the same trace.
    """

    def __init__(self, request_id: str | None = None, *, trace: dict | None = None) -> None:
        self.id: str = request_id or uuid.uuid4().hex
        self.trace: dict | None = trace
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()
        self._children: list[Context] = []

    # -- cancellation ------------------------------------------------------

    def stop_generating(self) -> None:
        self._stop.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._kill.set()
        self._stop.set()
        for c in self._children:
            c.kill()

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def is_killed(self) -> bool:
        return self._kill.is_set()

    async def wait_stopped(self) -> None:
        await self._stop.wait()

    async def wait_killed(self) -> None:
        await self._kill.wait()

    # -- chaining ----------------------------------------------------------

    def child(self) -> "Context":
        c = Context(request_id=self.id, trace=self.trace)
        if self.is_stopped:
            c.stop_generating()
        if self.is_killed:
            c.kill()
        self._children.append(c)
        return c


class AsyncEngine(abc.ABC, Generic[Req, Resp]):
    """A stage that turns one request into an async stream of responses."""

    @abc.abstractmethod
    def generate(self, request: Req, context: Context) -> AsyncIterator[Resp]:
        """Produce the response stream for ``request``.

        Implementations must observe ``context``: exit promptly after
        ``stop_generating()`` and immediately after ``kill()``.
        """
        raise NotImplementedError


class Operator(AsyncEngine[Req, Resp]):
    """A pipeline stage wrapping a downstream engine.

    Subclasses override :meth:`transform_request` (forward edge) and/or
    :meth:`transform_stream` (backward edge). Mirrors the reference's
    forward/backward Operator nodes (`lib/runtime/src/pipeline/nodes.rs`).
    """

    def __init__(self, downstream: AsyncEngine[Any, Any]) -> None:
        self.downstream = downstream

    async def transform_request(self, request: Req, context: Context) -> Any:
        return request

    def transform_stream(self, stream: AsyncIterator[Any], request: Req, context: Context) -> AsyncIterator[Resp]:
        return stream  # type: ignore[return-value]

    async def generate(self, request: Req, context: Context) -> AsyncIterator[Resp]:
        downstream_req = await self.transform_request(request, context)
        stream = self.downstream.generate(downstream_req, context)
        transformed = self.transform_stream(stream, request, context)
        try:
            async for item in transformed:
                yield item
        finally:
            for s in (transformed, stream):
                aclose = getattr(s, "aclose", None)
                if aclose is not None:
                    await aclose()


async def collect(stream: AsyncIterator[Resp]) -> list[Resp]:
    """Drain a response stream into a list (test/utility helper)."""
    return [item async for item in stream]
