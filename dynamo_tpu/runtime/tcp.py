"""TCP stream transport: the cross-process data plane.

Each worker process runs one asyncio TCP server; every registered subject is
reachable at ``tcp://host:port/subject``. A caller opens one connection per
request stream:

    caller -> worker   REQUEST {subject, id, p}
    worker -> caller   PROLOGUE            (accepted; or carries error detail)
    worker -> caller   DATA* then COMPLETE | ERROR
    caller -> worker   STOP | KILL         (any time; graceful / hard cancel)

Connection teardown is equivalent to KILL, so a dead caller can never leak a
running generation. Parity: reference response plane `tcp/server.rs` +
control messages `network.rs:49-73`; see transport.py for why this is a
single-connection design rather than broker+callback.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator
from urllib.parse import urlparse

from dynamo_tpu.runtime.codec import (
    Frame,
    FrameType,
    read_frame,
    write_blob_frame,
    write_frame,
)
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineError
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.transport import (
    DuplexUnsupportedError,
    NoSuchSubjectError,
    Transport,
)

logger = logging.getLogger(__name__)


class TcpTransport(Transport):
    def __init__(self, host: str = "127.0.0.1", port: int = 0, *, advertise_host: str | None = None) -> None:
        self._host = host
        self._port = port
        self._advertise_host = advertise_host or host
        self._engines: dict[str, AsyncEngine[Any, Any]] = {}
        self._server: asyncio.Server | None = None
        self._server_lock = asyncio.Lock()
        self._conn_tasks: set[asyncio.Task] = set()

    # -- worker side -------------------------------------------------------

    async def _ensure_server(self) -> None:
        async with self._server_lock:
            if self._server is None:
                self._server = await asyncio.start_server(self._handle_conn, self._host, self._port)
                self._port = self._server.sockets[0].getsockname()[1]

    async def register_engine(self, subject: str, engine: AsyncEngine[Any, Any]) -> None:
        if subject in self._engines:
            raise ValueError(f"subject already registered: {subject}")
        await self._ensure_server()
        self._engines[subject] = engine

    async def unregister_engine(self, subject: str) -> None:
        self._engines.pop(subject, None)

    def address_of(self, subject: str) -> str:
        if self._server is None:
            raise RuntimeError("transport server not started; register an engine first")
        return f"tcp://{self._advertise_host}:{self._port}/{subject}"

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        assert task is not None
        self._conn_tasks.add(task)
        try:
            await self._serve_stream(reader, writer)
        except Exception:
            logger.exception("connection handler failed")
        finally:
            self._conn_tasks.discard(task)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _serve_stream(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        req = await read_frame(reader)
        if req is None or req.type is not FrameType.REQUEST:
            return
        subject = req.fields.get("subject", "")
        engine = self._engines.get(subject)
        if engine is None:
            write_frame(writer, FrameType.PROLOGUE, ok=False, error=f"no such subject: {subject}")
            await writer.drain()
            return
        # The trace context crosses the process boundary here: spans emitted
        # by the engine behind this subject share the caller's trace_id.
        context = Context(request_id=req.fields.get("id"), trace=req.fields.get("trace"))
        if req.fields.get("duplex"):
            duplex_fn = getattr(engine, "duplex", None)
            if duplex_fn is None:
                write_frame(writer, FrameType.PROLOGUE, ok=False,
                            error=f"subject has no duplex data plane: {subject}")
                await writer.drain()
                return
            write_frame(writer, FrameType.PROLOGUE, ok=True)
            await writer.drain()
            await self._serve_duplex(duplex_fn, req, reader, writer, context)
            return
        write_frame(writer, FrameType.PROLOGUE, ok=True)

        async def watch_control() -> None:
            # Inbound control frames; EOF (caller vanished) => hard cancel.
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    context.kill()
                    return
                if frame.type is FrameType.STOP:
                    context.stop_generating()
                elif frame.type is FrameType.KILL:
                    context.kill()
                    return

        control_task = asyncio.create_task(watch_control())
        stream = engine.generate(req.payload, context)
        try:
            async for item in stream:
                if context.is_killed:
                    break
                write_frame(writer, FrameType.DATA, p=item)
                await writer.drain()
            if not context.is_killed:
                write_frame(writer, FrameType.COMPLETE)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            context.kill()
        except Exception as exc:  # engine failure -> ERROR frame
            logger.exception("engine stream failed (subject=%s)", subject)
            context.kill()
            try:
                write_frame(writer, FrameType.ERROR, error=f"{type(exc).__name__}: {exc}")
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass
        finally:
            control_task.cancel()
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()

    async def _serve_duplex(
        self,
        duplex_fn: Any,
        req: Frame,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        context: Context,
    ) -> None:
        """Serve one duplex stream: inbound DATA/blob frames are pumped into
        an async iterator handed to ``engine.duplex(request, inbound, ctx)``;
        each dict the engine yields goes back as a DATA frame. Connection
        teardown (either direction) kills the stream, same as ``generate``."""
        inbound: asyncio.Queue[dict[str, Any] | None] = asyncio.Queue()

        async def pump() -> None:
            while True:
                frame = await read_frame(reader)
                if frame is None or frame.type is FrameType.KILL:
                    context.kill()
                    await inbound.put(None)
                    return
                if frame.type is FrameType.COMPLETE:
                    await inbound.put(None)
                    return
                if frame.type is FrameType.DATA:
                    await inbound.put(frame.fields)
                elif frame.type is FrameType.STOP:
                    context.stop_generating()

        async def messages() -> AsyncIterator[dict[str, Any]]:
            while True:
                item = await inbound.get()
                if item is None:
                    return
                yield item

        pump_task = asyncio.create_task(pump())
        stream = duplex_fn(req.payload, messages(), context)
        try:
            async for item in stream:
                if context.is_killed:
                    break
                write_frame(writer, FrameType.DATA, p=item)
                await writer.drain()
            if not context.is_killed:
                write_frame(writer, FrameType.COMPLETE)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            context.kill()
        except Exception as exc:
            logger.exception("duplex stream failed (subject=%s)", req.fields.get("subject"))
            context.kill()
            try:
                write_frame(writer, FrameType.ERROR, error=f"{type(exc).__name__}: {exc}")
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass
        finally:
            pump_task.cancel()
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()

    # -- caller side -------------------------------------------------------

    async def open_duplex(self, address: str, request: Any, context: Context) -> "TcpDuplexStream":
        """Open a persistent duplex stream to ``tcp://host:port/subject``.

        Unlike ``generate`` (one request, a stream of responses), a duplex
        stream lets the caller keep sending frames — including raw blob
        frames — over one connection, with responses interleaved. This is the
        KV wire v3 data plane: one connection per stripe, no per-chunk
        connection setup.
        """
        url = urlparse(address)
        if url.scheme != "tcp":
            raise ValueError(f"not a tcp address: {address}")
        subject = url.path.lstrip("/")
        if FAULTS.armed:
            FAULTS.fire("tcp.connect")
        reader, writer = await asyncio.open_connection(url.hostname, url.port)
        try:
            extra = {"trace": context.trace} if context.trace else {}
            if FAULTS.armed:
                FAULTS.fire("tcp.write")
            write_frame(writer, FrameType.REQUEST, subject=subject, id=context.id,
                        duplex=True, p=request, **extra)
            await writer.drain()
            prologue = await read_frame(reader)
            if prologue is None:
                raise EngineError("connection closed before prologue")
            if prologue.type is not FrameType.PROLOGUE:
                raise EngineError(f"expected prologue, got {prologue.type}")
            if not prologue.fields.get("ok", False):
                err = prologue.fields.get("error", "rejected")
                if "no such subject" in err:
                    raise NoSuchSubjectError(err)
                if "no duplex data plane" in err:
                    raise DuplexUnsupportedError(err)
                raise EngineError(err)
        except BaseException:
            writer.close()
            raise
        return TcpDuplexStream(reader, writer)

    async def generate(self, address: str, request: Any, context: Context) -> AsyncIterator[Any]:
        url = urlparse(address)
        if url.scheme != "tcp":
            raise ValueError(f"not a tcp address: {address}")
        subject = url.path.lstrip("/")
        if FAULTS.armed:
            FAULTS.fire("tcp.connect")
        reader, writer = await asyncio.open_connection(url.hostname, url.port)

        async def forward_cancel() -> None:
            stop_wait = asyncio.create_task(context.wait_stopped())
            kill_wait = asyncio.create_task(context.wait_killed())
            try:
                await asyncio.wait({stop_wait, kill_wait}, return_when=asyncio.FIRST_COMPLETED)
                write_frame(writer, FrameType.KILL if context.is_killed else FrameType.STOP)
                await writer.drain()
                if not context.is_killed:
                    await kill_wait
                    write_frame(writer, FrameType.KILL)
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
                pass
            finally:
                stop_wait.cancel()
                kill_wait.cancel()

        cancel_task = asyncio.create_task(forward_cancel())
        try:
            extra = {"trace": context.trace} if context.trace else {}
            if FAULTS.armed:
                FAULTS.fire("tcp.write")
            write_frame(writer, FrameType.REQUEST, subject=subject, id=context.id, p=request, **extra)
            await writer.drain()
            prologue = await read_frame(reader)
            if prologue is None:
                raise EngineError("connection closed before prologue")
            if prologue.type is not FrameType.PROLOGUE:
                raise EngineError(f"expected prologue, got {prologue.type}")
            if not prologue.fields.get("ok", False):
                err = prologue.fields.get("error", "rejected")
                if "no such subject" in err:
                    raise NoSuchSubjectError(err)
                raise EngineError(err)
            while True:
                if FAULTS.armed:
                    FAULTS.fire("tcp.read")
                frame = await read_frame(reader)
                if frame is None:
                    if context.is_killed or context.is_stopped:
                        return
                    raise EngineError("connection closed mid-stream")
                if frame.type is FrameType.DATA:
                    yield frame.payload
                elif frame.type is FrameType.COMPLETE:
                    return
                elif frame.type is FrameType.ERROR:
                    raise EngineError(frame.fields.get("error", "remote engine failed"))
        finally:
            cancel_task.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for task in list(self._conn_tasks):
            task.cancel()


class TcpDuplexStream:
    """Caller half of a duplex stream: ``send`` frames (optionally with raw
    blob buffers), ``recv`` the engine's responses, ``close`` when done."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._reader = reader
        self._writer = writer

    async def send(self, fields: dict[str, Any], blobs: list[Any] | None = None) -> None:
        if FAULTS.armed:
            FAULTS.fire("tcp.write")
        if blobs:
            write_blob_frame(self._writer, FrameType.DATA, blobs, **fields)
        else:
            write_frame(self._writer, FrameType.DATA, **fields)
        await self._writer.drain()

    async def recv(self) -> dict[str, Any] | None:
        """One response payload dict; None when the engine side completed."""
        if FAULTS.armed:
            FAULTS.fire("tcp.read")
        frame = await read_frame(self._reader)
        if frame is None or frame.type is FrameType.COMPLETE:
            return None
        if frame.type is FrameType.ERROR:
            raise EngineError(frame.fields.get("error", "remote engine failed"))
        return frame.payload

    async def close(self) -> None:
        try:
            write_frame(self._writer, FrameType.COMPLETE)
            await self._writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass
