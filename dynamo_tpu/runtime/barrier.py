"""Leader/worker rendezvous barrier over the discovery store.

Multi-host engine bring-up (one mesh spanning hosts) needs a rendezvous:
the leader publishes bootstrap data (mesh coordinates, jax distributed
initialization address), N workers read it and check in, and everyone
proceeds once the roster is full. Lease-bound check-ins make the barrier
crash-safe: a worker dying during rendezvous releases its slot.

Parity: reference `lib/runtime/src/utils/leader_worker_barrier.rs:137,230`.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

from dynamo_tpu.runtime.component import DistributedRuntime


class BarrierTimeout(TimeoutError):
    pass


def _data_key(name: str) -> str:
    return f"barrier/{name}/data"


def _worker_prefix(name: str) -> str:
    return f"barrier/{name}/workers/"


async def leader_barrier(
    runtime: DistributedRuntime,
    name: str,
    data: Any,
    *,
    num_workers: int,
    timeout: float = 60.0,
) -> None:
    """Publish ``data`` and wait until ``num_workers`` workers checked in."""
    lease = await runtime.primary_lease()
    await runtime.store.put(_data_key(name), json.dumps(data).encode(), lease_id=lease.id)
    deadline = asyncio.get_event_loop().time() + timeout
    prefix = _worker_prefix(name)
    while True:
        present = await runtime.store.get_prefix(prefix)
        if len(present) >= num_workers:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise BarrierTimeout(f"barrier {name}: {len(present)}/{num_workers} workers after {timeout}s")
        await asyncio.sleep(0.05)


async def worker_barrier(
    runtime: DistributedRuntime,
    name: str,
    worker_id: str,
    *,
    timeout: float = 60.0,
) -> Any:
    """Wait for the leader's data, check in, and return the data."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        raw = await runtime.store.get(_data_key(name))
        if raw is not None:
            break
        if asyncio.get_event_loop().time() > deadline:
            raise BarrierTimeout(f"barrier {name}: no leader data after {timeout}s")
        await asyncio.sleep(0.05)
    lease = await runtime.primary_lease()
    await runtime.store.put(_worker_prefix(name) + worker_id, b"1", lease_id=lease.id)
    return json.loads(raw)
