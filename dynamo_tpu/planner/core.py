"""Planner decision logic: metrics -> target fleet sizes.

Pure and synchronous (the loop/connector wrap it), mirroring the reference's
`planner_core.py:162-285` structure: observe rates from cumulative worker
counters, predict next-interval load, divide by per-worker capacity from a
(profiled) WorkerProfile, correct by observed saturation, clamp to budget,
and apply hysteresis so the fleet doesn't flap.

SLA mode uses the profile's latency surfaces: pick the smallest fleet whose
interpolated TTFT/ITL meet the targets at the predicted load — the same
shape as the reference's pre-deployment profiling + interpolation
(`perf_interpolation.py`, `profile_sla.py`).
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Mapping

from dynamo_tpu.planner.predictor import make_predictor
from dynamo_tpu.protocols.kv import ForwardPassMetrics

logger = logging.getLogger(__name__)


@dataclass
class WorkerProfile:
    """Per-worker capacity, from the profiler sweep (dynamo_tpu.profiler).

    Latency surfaces are piecewise-linear: points of (load_fraction, seconds).
    """

    prefill_tokens_per_sec: float = 20000.0
    decode_tokens_per_sec: float = 2000.0
    max_concurrent: int = 64
    ttft_curve: list[tuple[float, float]] = field(default_factory=lambda: [(0.0, 0.05), (1.0, 0.5)])
    itl_curve: list[tuple[float, float]] = field(default_factory=lambda: [(0.0, 0.01), (1.0, 0.1)])
    # Tail-latency surfaces (p95/p99) from the same sweep. Empty by default:
    # the SLA planner keeps sizing on medians; tails are informational until
    # an SLO policy consumes them (``ttft_at(..., pct=...)``).
    ttft_p95_curve: list[tuple[float, float]] = field(default_factory=list)
    ttft_p99_curve: list[tuple[float, float]] = field(default_factory=list)
    itl_p95_curve: list[tuple[float, float]] = field(default_factory=list)
    itl_p99_curve: list[tuple[float, float]] = field(default_factory=list)

    @staticmethod
    def _interp(curve: list[tuple[float, float]], x: float) -> float:
        if not curve:
            return 0.0
        pts = sorted(curve)
        if x <= pts[0][0]:
            return pts[0][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if x <= x1:
                return y0 + (y1 - y0) * (x - x0) / max(x1 - x0, 1e-9)
        return pts[-1][1]

    def ttft_at(self, load_fraction: float, *, pct: int = 50) -> float:
        curve = {95: self.ttft_p95_curve, 99: self.ttft_p99_curve}.get(pct) or self.ttft_curve
        return self._interp(curve, load_fraction)

    def itl_at(self, load_fraction: float, *, pct: int = 50) -> float:
        curve = {95: self.itl_p95_curve, 99: self.itl_p99_curve}.get(pct) or self.itl_curve
        return self._interp(curve, load_fraction)

    def to_json(self) -> str:
        import json

        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "WorkerProfile":
        import json

        d = json.loads(text)
        # Absent curves keep the dataclass defaults (an empty curve would
        # interpolate to 0.0 latency and blind the SLA mode).
        for key in (
            "ttft_curve", "itl_curve",
            "ttft_p95_curve", "ttft_p99_curve", "itl_p95_curve", "itl_p99_curve",
        ):
            if key in d:
                d[key] = [tuple(p) for p in d[key]]
        return cls(**{k: v for k, v in d.items() if k in {f.name for f in dataclasses.fields(cls)}})


@dataclass
class PlannerConfig:
    mode: str = "load"  # "load" | "sla"
    min_workers: int = 1
    max_workers: int = 8
    min_prefill_workers: int = 0
    max_prefill_workers: int = 8
    target_utilization: float = 0.7  # load mode: keep fleets at this fraction
    ttft_slo_seconds: float = 0.5  # sla mode
    itl_slo_seconds: float = 0.05
    # Which latency percentile the SLA mode sizes against: 50 (median
    # curves), or 95/99 (the profiler's tail curves, when present — an SLO
    # stated on the tail needs tail-aware sizing; median curves hide the
    # saturation knee). Falls back to the median curve when the requested
    # tail curve wasn't profiled.
    slo_percentile: int = 50
    scale_down_headroom: float = 0.3  # hysteresis: only shrink below (target - headroom)
    interval_seconds: float = 10.0
    # Load model: "linear" (ramps), "seasonal" (repeating peaks; falls back
    # to linear when no period is detected), "moving_average", "constant".
    predictor: str = "linear"


@dataclass
class PlanDecision:
    decode_workers: int
    prefill_workers: int
    predicted_prefill_tps: float
    predicted_decode_tps: float


class Planner:
    def __init__(self, config: PlannerConfig, profile: WorkerProfile) -> None:
        self.config = config
        self.profile = profile
        self._prefill_pred = make_predictor(config.predictor)
        self._decode_pred = make_predictor(config.predictor)
        self._last_counters: dict[int, tuple[int, int]] = {}
        self._last_decision: PlanDecision | None = None

    # -- observation -------------------------------------------------------

    def observe(self, metrics: Mapping[int, ForwardPassMetrics], dt_seconds: float) -> tuple[float, float]:
        """Feed one scrape; returns (prefill_tps, decode_tps) this interval."""
        prefill_tokens = decode_tokens = 0
        for wid, m in metrics.items():
            last = self._last_counters.get(wid, (0, 0))
            prefill_tokens += max(0, m.prompt_tokens_total - last[0])
            decode_tokens += max(0, m.generated_tokens_total - last[1])
            self._last_counters[wid] = (m.prompt_tokens_total, m.generated_tokens_total)
        # Drop counters of departed workers.
        for wid in list(self._last_counters):
            if wid not in metrics:
                del self._last_counters[wid]
        dt = max(dt_seconds, 1e-6)
        prefill_tps, decode_tps = prefill_tokens / dt, decode_tokens / dt
        self._prefill_pred.observe(prefill_tps)
        self._decode_pred.observe(decode_tps)
        return prefill_tps, decode_tps

    # -- decision ----------------------------------------------------------

    def decide(self, *, disaggregated: bool = True) -> PlanDecision:
        c, p = self.config, self.profile
        prefill_tps = self._prefill_pred.predict()
        decode_tps = self._decode_pred.predict()

        if c.mode == "sla":
            pct = c.slo_percentile
            decode = self._smallest_meeting_slo(
                decode_tps, p.decode_tokens_per_sec,
                lambda f: p.itl_at(f, pct=pct), c.itl_slo_seconds, c.max_workers,
            )
            prefill = self._smallest_meeting_slo(
                prefill_tps, p.prefill_tokens_per_sec,
                lambda f: p.ttft_at(f, pct=pct), c.ttft_slo_seconds, c.max_prefill_workers,
            )
        else:
            decode = -(-decode_tps // max(p.decode_tokens_per_sec * c.target_utilization, 1e-6))
            prefill = -(-prefill_tps // max(p.prefill_tokens_per_sec * c.target_utilization, 1e-6))

        decode = int(min(max(decode, c.min_workers), c.max_workers))
        prefill = int(min(max(prefill, c.min_prefill_workers), c.max_prefill_workers)) if disaggregated else 0

        # Hysteresis: only scale down when clearly over-provisioned.
        if self._last_decision is not None:
            prev = self._last_decision
            if decode < prev.decode_workers:
                needed = decode_tps / max(p.decode_tokens_per_sec, 1e-6)
                if needed > (prev.decode_workers - 1) * (c.target_utilization - c.scale_down_headroom):
                    decode = prev.decode_workers
            if prefill < prev.prefill_workers:
                needed = prefill_tps / max(p.prefill_tokens_per_sec, 1e-6)
                if needed > (prev.prefill_workers - 1) * (c.target_utilization - c.scale_down_headroom):
                    prefill = prev.prefill_workers

        decision = PlanDecision(decode, prefill, prefill_tps, decode_tps)
        self._last_decision = decision
        return decision

    @staticmethod
    def _smallest_meeting_slo(load_tps, per_worker_tps, latency_at, slo, max_workers) -> int:
        for n in range(1, max_workers + 1):
            frac = load_tps / max(n * per_worker_tps, 1e-6)
            if frac <= 1.0 and latency_at(frac) <= slo:
                return n
        return max_workers


@dataclasses.dataclass
class PlannerLoopStats:
    iterations: int = 0
    scale_events: int = 0
