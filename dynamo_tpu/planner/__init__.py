"""Planner: the autoscaler for worker fleets.

Observes the metrics plane, predicts near-future load, and resizes the
prefill/decode fleets through a connector. Mirrors the reference planner
(`components/planner`, SURVEY.md §2 row 42): load-based and SLA-based
policies, pluggable load predictors, pre-profiled performance
interpolation, and local/k8s connectors.

- :mod:`dynamo_tpu.planner.predictor` — constant / moving-average / linear-
  trend / seasonal load predictors.
- :mod:`dynamo_tpu.planner.core` — pure decision logic (testable without a
  cluster): rates from the metrics plane -> target replica counts.
- :mod:`dynamo_tpu.planner.connector` — applies targets: in-process worker
  fleets (tests, single node) or subprocess fleets via the launch CLI.
"""

from dynamo_tpu.planner.connector import LocalProcessConnector, PlannerLoop
from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile
from dynamo_tpu.planner.predictor import (
    ConstantPredictor,
    LinearTrendPredictor,
    MovingAveragePredictor,
    SeasonalPredictor,
    make_predictor,
)

__all__ = [
    "Planner",
    "PlannerConfig",
    "WorkerProfile",
    "LocalProcessConnector",
    "PlannerLoop",
    "ConstantPredictor",
    "MovingAveragePredictor",
    "LinearTrendPredictor",
    "SeasonalPredictor",
    "make_predictor",
]
