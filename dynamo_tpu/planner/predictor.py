"""Load predictors: estimate the next interval's request/token rates.

Parity: reference `utils/load_predictor.py:62-106` (Constant / ARIMA /
Prophet). The heavy statistical models are replaced by two dependency-free
fits: a linear trend (ramps — what ARIMA's differencing term buys) and a
seasonal-naive-with-drift model over an autocorrelation-detected period
(repeating peaks — what Prophet's seasonality buys). On the minute-scale
horizons autoscalers act on, these capture the two shapes that matter.
"""

from __future__ import annotations

from collections import deque


class ConstantPredictor:
    """Predicts the last observation."""

    def __init__(self) -> None:
        self._last = 0.0

    def observe(self, value: float) -> None:
        self._last = value

    def predict(self) -> float:
        return self._last


class MovingAveragePredictor:
    def __init__(self, window: int = 8) -> None:
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._values.append(value)

    def predict(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0


class LinearTrendPredictor:
    """Least-squares linear fit over the window, extrapolated one step.

    Never predicts negative load; falls back to the mean with < 3 samples.
    """

    def __init__(self, window: int = 12) -> None:
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._values.append(value)

    def predict(self) -> float:
        n = len(self._values)
        if n == 0:
            return 0.0
        if n < 3:
            return sum(self._values) / n
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self._values) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._values))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


class SeasonalPredictor:
    """Seasonal-naive-with-drift over an autocorrelation-detected period.

    Periodic load (diurnal cycles compressed to scrape-interval scale,
    batch-job waves) is the case auto-scaling exists for and the one a
    linear fit provably mispredicts: at the trough before a repeating peak
    the trend points down, so the fleet scales up a full period late. This
    model:

    1. detrends the window (least-squares line, so a ramp doesn't masquerade
       as correlation at every lag);
    2. picks the lag ``p`` in [min_period, n//2] with the highest normalized
       autocorrelation of the residuals;
    3. if that correlation clears ``threshold``, predicts the value one
       period ago plus the period-over-period drift (mean of the last cycle
       minus mean of the one before);
    4. otherwise falls back to the linear-trend prediction — aperiodic load
       degrades to exactly the old behavior.

    Pure Python on a bounded window (O(window²) per predict at window=64 is
    ~4k multiplies — nothing at planner tick rates).

    Parity: reference ARIMA/Prophet predictors
    (`components/planner/src/dynamo/planner/utils/load_predictor.py:62-106`).
    """

    def __init__(self, window: int = 64, min_period: int = 3, threshold: float = 0.3) -> None:
        self._values: deque[float] = deque(maxlen=window)
        # The aperiodic fallback is a REAL LinearTrendPredictor at its own
        # default (short) window, observed in lockstep — so "degrades to the
        # linear predictor" is literal, recent-ramp sensitivity included
        # (a full-window refit would dilute a late ramp ~5x).
        self._fallback = LinearTrendPredictor()
        self.min_period = min_period
        self.threshold = threshold
        #: Introspection: the period used by the last predict() (None = fell
        #: back to trend).
        self.last_period: int | None = None

    def observe(self, value: float) -> None:
        self._values.append(value)
        self._fallback.observe(value)

    def predict(self) -> float:
        y = list(self._values)
        n = len(y)
        self.last_period = None
        if n < 2 * self.min_period:
            return self._fallback.predict()

        # Detrend: residuals of the least-squares line (so a ramp doesn't
        # read as correlation at every lag).
        mean_x = (n - 1) / 2.0
        mean_y = sum(y) / n
        var = sum((x - mean_x) ** 2 for x in range(n))
        cov = sum((x - mean_x) * (v - mean_y) for x, v in enumerate(y))
        slope = cov / var if var else 0.0
        resid = [v - (mean_y + slope * (x - mean_x)) for x, v in enumerate(y)]
        energy = sum(r * r for r in resid)
        if energy <= 1e-12:  # perfectly linear window: nothing seasonal
            return self._fallback.predict()

        best_p, best_r = 0, 0.0
        for p in range(self.min_period, n // 2 + 1):
            r = sum(resid[i] * resid[i + p] for i in range(n - p)) / energy
            if r > best_r:
                best_p, best_r = p, r
        if best_p == 0 or best_r < self.threshold:
            # best_p == 0: no lag had positive correlation (possible when
            # threshold <= 0, which would otherwise index y[n]).
            return self._fallback.predict()

        self.last_period = best_p
        # Next index is n; its in-cycle twin is y[n - p]. Drift = how much
        # the latest full cycle sits above the one before (best_p <= n//2,
        # so two full cycles are always in the window).
        base = y[n - best_p]
        drift = (sum(y[n - best_p:]) - sum(y[n - 2 * best_p : n - best_p])) / best_p
        return max(0.0, base + drift)


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving_average": MovingAveragePredictor,
    "linear": LinearTrendPredictor,
    "seasonal": SeasonalPredictor,
}


def make_predictor(name: str):
    """Planner-config predictor selection (PlannerConfig.predictor)."""
    try:
        return PREDICTORS[name]()
    except KeyError:
        raise ValueError(f"unknown predictor {name!r}; choose from {sorted(PREDICTORS)}") from None
