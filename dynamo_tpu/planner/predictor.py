"""Load predictors: estimate the next interval's request/token rates.

Parity: reference `utils/load_predictor.py:62-106` (Constant / ARIMA /
Prophet). The heavy statistical models are replaced by a linear-trend fit —
on the minute-scale horizons autoscalers act on, trend extrapolation
captures what matters (ramps) without the dependency weight.
"""

from __future__ import annotations

from collections import deque


class ConstantPredictor:
    """Predicts the last observation."""

    def __init__(self) -> None:
        self._last = 0.0

    def observe(self, value: float) -> None:
        self._last = value

    def predict(self) -> float:
        return self._last


class MovingAveragePredictor:
    def __init__(self, window: int = 8) -> None:
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._values.append(value)

    def predict(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0


class LinearTrendPredictor:
    """Least-squares linear fit over the window, extrapolated one step.

    Never predicts negative load; falls back to the mean with < 3 samples.
    """

    def __init__(self, window: int = 12) -> None:
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._values.append(value)

    def predict(self) -> float:
        n = len(self._values)
        if n == 0:
            return 0.0
        if n < 3:
            return sum(self._values) / n
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self._values) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._values))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))
