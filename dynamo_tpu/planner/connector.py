"""Planner actuation: decisions -> live fleet changes.

``LocalProcessConnector`` manages worker OS processes the way the
reference's circus-based local connector does
(`components/planner/.../local_connector.py:105-197`, `circusd.py`): each
decode/prefill worker is a ``python -m dynamo_tpu.launch --role ...``
subprocess joined to the deployment's store. Scaling up spawns processes;
scaling down terminates the youngest (lease expiry then removes the
instance from discovery, the router index drops its blocks — the same
teardown path as a crash, exercised by the failure tests).

``PlannerLoop`` closes the control loop: scrape the metrics plane ->
observe/predict/decide (`planner/core.py`) -> apply via a connector.
Parity: reference `planner_core.py:285` run loop + `planner_sla.py`.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
import threading
import time
from typing import Protocol

from dynamo_tpu.planner.core import PlanDecision, Planner
from dynamo_tpu.router.metrics import KvMetricsAggregator

logger = logging.getLogger(__name__)


class Connector(Protocol):
    async def apply(self, decision: PlanDecision) -> None: ...
    async def close(self) -> None: ...


class DeploymentConnector:
    """Scales by editing the declarative GraphDeployment record.

    The planner's decision becomes a spec change on the deployment object
    (replicas per service); the operator's watch reconciles the fleet. This
    is the reference's kubernetes-connector shape
    (`kubernetes_connector.py:25-46`: patch the DynamoGraphDeployment CRD,
    let the controller act) on this framework's control plane — the planner
    never touches processes, so it works identically against the local
    ProcessBackend and a k8s rollout of the rendered manifests.
    """

    def __init__(
        self,
        store,
        deployment: str,
        *,
        decode_service: str = "Worker",
        prefill_service: str | None = None,
    ) -> None:
        self.store = store
        self.deployment = deployment
        self.decode_service = decode_service
        self.prefill_service = prefill_service
        self.scale_events = 0

    async def apply(self, decision: PlanDecision) -> None:
        from dynamo_tpu.deploy.objects import STORE_PREFIX, DeploymentPhase, GraphDeployment

        raw = await self.store.get(STORE_PREFIX + self.deployment)
        if raw is None:
            logger.warning("deployment %s missing; cannot apply decision", self.deployment)
            return
        dep = GraphDeployment.from_bytes(raw)
        if dep.phase == DeploymentPhase.DELETING.value:
            return
        want: dict[str, int] = {self.decode_service: max(decision.decode_workers, 0)}
        if self.prefill_service is not None:
            want[self.prefill_service] = max(decision.prefill_workers, 0)
        changed = False
        for service, replicas in want.items():
            section = dep.config.setdefault(service, {})
            if int(section.get("replicas", -1)) != replicas:
                section["replicas"] = replicas
                changed = True
        if not changed:
            return
        dep.generation += 1
        dep.phase = DeploymentPhase.PENDING.value
        # A delete may have started or finalized since our read — putting now
        # would cancel the teardown / resurrect the record. Re-read and drop
        # the decision if the record is gone or marked DELETING.
        fresh = await self.store.get(dep.key)
        if fresh is None or GraphDeployment.from_bytes(fresh).phase == DeploymentPhase.DELETING.value:
            logger.info("deployment %s deleted while scaling; dropping decision", self.deployment)
            return
        await self.store.put(dep.key, dep.to_bytes())
        self.scale_events += 1
        logger.info("deployment %s scaled: %s (gen %d)", self.deployment, want, dep.generation)

    async def close(self) -> None:
        pass


class LocalProcessConnector:
    """Scales decode/prefill fleets as launch.py subprocesses."""

    def __init__(
        self,
        *,
        model: str,
        store_url: str,
        host: str = "127.0.0.1",
        mock: bool = False,
        extra_args: list[str] | None = None,
        spawn_timeout: float = 60.0,
    ) -> None:
        self.model = model
        self.store_url = store_url
        self.host = host
        self.mock = mock
        self.extra_args = list(extra_args or [])
        self.spawn_timeout = spawn_timeout
        self._decode: list[subprocess.Popen] = []
        self._prefill: list[subprocess.Popen] = []
        self.scale_events = 0

    # -- process management ------------------------------------------------

    def _spawn(self, role: str) -> subprocess.Popen:
        import os

        import dynamo_tpu

        cmd = [
            sys.executable, "-m", "dynamo_tpu.launch",
            "--role", role, "--model", self.model,
            "--store", self.store_url, "--host", self.host,
        ]
        if self.mock:
            cmd.append("--mock")
        cmd += self.extra_args
        # The child must resolve this package regardless of the planner's
        # cwd (the launch CLI may be run from anywhere).
        env = dict(os.environ)
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(dynamo_tpu.__file__)))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        logger.info("spawned %s worker pid=%d", role, proc.pid)
        return proc

    async def _wait_ready(self, proc: subprocess.Popen) -> None:
        """Wait (bounded) for the worker's READY line, then keep its pipe
        drained for life — an undrained 64KB pipe would eventually block the
        worker's own log writes and wedge it mid-serve."""

        def read() -> None:
            while True:
                line = proc.stdout.readline() if proc.stdout else ""
                if not line:  # EOF: the child exited before READY
                    raise RuntimeError(f"worker pid={proc.pid} exited rc={proc.poll()} before READY")
                if line.startswith("READY"):
                    return

        try:
            await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(None, read), self.spawn_timeout
            )
        except (asyncio.TimeoutError, TimeoutError):
            # Killing the child EOFs the pipe, unblocking the reader thread.
            proc.kill()
            raise TimeoutError(f"worker pid={proc.pid} not ready in {self.spawn_timeout}s") from None
        threading.Thread(target=self._drain, args=(proc,), daemon=True).start()

    @staticmethod
    def _drain(proc: subprocess.Popen) -> None:
        try:
            while proc.stdout and proc.stdout.readline():
                pass
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    @staticmethod
    def _reap(fleet: list[subprocess.Popen]) -> None:
        fleet[:] = [p for p in fleet if p.poll() is None]

    async def _scale(self, fleet: list[subprocess.Popen], target: int, role: str) -> None:
        self._reap(fleet)
        if len(fleet) < target:
            # Spawn the whole deficit, then wait for readiness concurrently:
            # cold starts (JAX init) overlap instead of serializing while the
            # load spike that triggered the scale-up goes unserved.
            procs = [self._spawn(role) for _ in range(target - len(fleet))]
            results = await asyncio.gather(
                *(self._wait_ready(p) for p in procs), return_exceptions=True
            )
            failures: list[BaseException] = []
            for p, r in zip(procs, results):
                if isinstance(r, BaseException):
                    logger.error("%s worker pid=%d failed to start: %s", role, p.pid, r)
                    if p.poll() is None:
                        p.kill()
                    failures.append(r)
                else:
                    fleet.append(p)
                    self.scale_events += 1
            if failures:
                raise failures[0]
        while len(fleet) > target:
            proc = fleet.pop()  # youngest first (coldest cache)
            logger.info("stopping %s worker pid=%d", role, proc.pid)
            proc.terminate()
            self.scale_events += 1
        for p in list(fleet):
            if p.poll() is not None:
                logger.warning("%s worker pid=%d died (rc=%s)", role, p.pid, p.returncode)

    # -- Connector ---------------------------------------------------------

    async def apply(self, decision: PlanDecision) -> None:
        await self._scale(self._decode, decision.decode_workers, "worker")
        await self._scale(self._prefill, decision.prefill_workers, "prefill")

    def live_counts(self) -> tuple[int, int]:
        self._reap(self._decode)
        self._reap(self._prefill)
        return len(self._decode), len(self._prefill)

    async def close(self) -> None:
        procs = self._decode + self._prefill
        self._decode, self._prefill = [], []
        for p in procs:
            if p.poll() is None:
                p.terminate()

        def wait_all() -> None:  # blocking waits stay off the event loop
            for p in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=5)

        await asyncio.get_running_loop().run_in_executor(None, wait_all)


class PlannerLoop:
    """Periodic scrape -> decide -> actuate loop."""

    def __init__(
        self,
        planner: Planner,
        aggregator: KvMetricsAggregator,
        connector: Connector,
        *,
        disaggregated: bool = False,
    ) -> None:
        self.planner = planner
        self.aggregator = aggregator
        self.connector = connector
        self.disaggregated = disaggregated
        self.iterations = 0
        self._task: asyncio.Task | None = None
        self._last_tick = time.monotonic()

    async def tick(self) -> PlanDecision:
        """One control iteration (the run loop calls this; tests drive it)."""
        now = time.monotonic()
        dt, self._last_tick = now - self._last_tick, now
        self.planner.observe(self.aggregator.snapshot(), dt or self.planner.config.interval_seconds)
        decision = self.planner.decide(disaggregated=self.disaggregated)
        await self.connector.apply(decision)
        self.iterations += 1
        return decision

    async def start(self) -> "PlannerLoop":
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="planner-loop")
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.planner.config.interval_seconds)
            try:
                await self.tick()
            except Exception:
                logger.exception("planner iteration failed")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.connector.close()
