"""Vectorized token sampling: greedy / temperature / top-k / top-p, batched.

All requests in a decode batch are sampled in one fused device computation —
per-request parameters arrive as arrays, and greedy requests are expressed as
``temperature == 0``. Runs entirely on device; only the sampled token ids
return to the host.

Parity: the reference delegates sampling to the wrapped engine; sampling
parameter schema follows its `PreprocessedRequest` sampling options
(`lib/llm/src/protocols/common/mod.rs` SamplingOptions / StopConditions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import NEG_INF


def _mask_top_k(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Keep the top-k logits per row (top_k <= 0 means disabled)."""
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
    k = jnp.where(top_k <= 0, vocab, top_k)
    k = jnp.clip(k, 1, vocab)
    # Threshold = k-th largest logit per row.
    thresh = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    return jnp.where(logits >= thresh, logits, NEG_INF)


def _mask_top_p(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set of tokens with cumulative
    probability >= top_p (top_p >= 1 means disabled)."""
    sort_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Token i is kept if the cumulative mass *before* it is < top_p.
    keep_sorted = (cum - probs) < top_p[:, None]
    keep_sorted = keep_sorted.at[:, 0].set(True)  # always keep the argmax
    masked_sorted = jnp.where(keep_sorted, sorted_logits, NEG_INF)
    # Unsort back to vocab order.
    inv_idx = jnp.argsort(sort_idx, axis=-1)
    return jnp.take_along_axis(masked_sorted, inv_idx, axis=-1)


def sample_tokens(
    logits: jnp.ndarray,  # f32[B, vocab]
    keys: jax.Array,  # PRNG keys [B] (one per row: per-request seed determinism)
    temperature: jnp.ndarray,  # f32[B]; 0 => greedy
    top_k: jnp.ndarray,  # i32[B]; <=0 => disabled
    top_p: jnp.ndarray,  # f32[B]; >=1 => disabled
) -> jnp.ndarray:
    """Sample one token per row; returns i32[B]."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    scaled = _mask_top_k(scaled, top_k)
    scaled = _mask_top_p(scaled, top_p)
    sampled = jax.vmap(lambda k, row: jax.random.categorical(k, row))(keys, scaled).astype(jnp.int32)

    return jnp.where(temperature > 0, sampled, greedy)
