"""Vectorized token sampling: greedy / temperature / top-k / top-p, batched.

All requests in a decode batch are sampled in one fused device computation —
per-request parameters arrive as arrays, and greedy requests are expressed as
``temperature == 0``. Runs entirely on device; only the sampled token ids
return to the host.

Implementation note: sampling never sorts the vocabulary. A full
``jnp.sort``/``argsort`` over a 128k-wide vocab row costs two orders of
magnitude more device time than the whole transformer decode step — and so
does ``lax.top_k``, which lowers to the same full sort on TPU (measured
~4 ms/step at batch 32 on v5e, dominating the decode step). The sampler
instead reduces to a ``CANDIDATES``-wide window with ``lax.approx_max_k``
(TPU-native PartialReduce, ~40x cheaper; exact top-k on CPU) and applies
temperature / top-k / top-p / categorical inside that window, mapping the
winner back through the gathered indices. Greedy decoding does not go
through the window at all — it is an exact ``argmax`` over the full row, so
the approximate reduction can never change a greedy token. Requests asking
for ``top_k > CANDIDATES``, or for a nucleus whose mass needs more than
``CANDIDATES`` tokens, are truncated to the candidate window (the same
capping serving samplers apply in practice).

Parity: the reference delegates sampling to the wrapped engine; sampling
parameter schema follows its `PreprocessedRequest` sampling options
(`lib/llm/src/protocols/common/mod.rs` SamplingOptions / StopConditions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import NEG_INF

# Candidate window for non-greedy sampling. 256 covers every practical
# top-k setting and >0.999 of nucleus mass for peaked LLM distributions.
CANDIDATES = 256


def sample_tokens(
    logits: jnp.ndarray,  # f32[B, vocab]
    keys: jax.Array,  # PRNG keys [B] (one per row: per-request seed determinism)
    temperature: jnp.ndarray,  # f32[B]; 0 => greedy
    top_k: jnp.ndarray,  # i32[B]; <=0 => disabled
    top_p: jnp.ndarray,  # f32[B]; >=1 => disabled
    history: jnp.ndarray | None = None,  # i32[B, H] generated-so-far (pad -1)
    frequency_penalty: jnp.ndarray | None = None,  # f32[B]
    presence_penalty: jnp.ndarray | None = None,  # f32[B]
) -> jnp.ndarray:
    """Sample one token per row; returns i32[B].

    Frequency/presence penalties follow the OpenAI semantics over *generated*
    tokens (``logit -= freq * count + pres * (count > 0)``), computed inside
    the candidate window: counting 256 candidates against the history costs
    B*256*H comparisons — noise next to the forward pass — where a full
    [B, vocab] count tensor would not fit the per-step budget. A penalized
    greedy row takes the penalized window argmax instead of the exact
    full-row argmax (the true winner is in the window unless penalties
    demote all 256 candidates at once).
    """
    logits = logits.astype(jnp.float32)
    cand = min(CANDIDATES, logits.shape[-1])
    top_logits, top_idx = jax.lax.approx_max_k(logits, cand)  # [B, cand], descending

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # exact, sort-free

    penalized = history is not None and frequency_penalty is not None and presence_penalty is not None
    if penalized:
        # counts[b, c] = occurrences of candidate c in row b's history.
        counts = (history[:, None, :] == top_idx[:, :, None]).sum(-1).astype(jnp.float32)
        top_logits = top_logits - (
            frequency_penalty[:, None] * counts
            + presence_penalty[:, None] * (counts > 0)
        )
        # Penalties break the window's descending order, which the top-k rank
        # mask and top-p cumulative mass below depend on. Re-sort within the
        # window (256-wide: trivial next to the forward pass).
        order = jnp.argsort(-top_logits, axis=-1)
        top_logits = jnp.take_along_axis(top_logits, order, axis=-1)
        top_idx = jnp.take_along_axis(top_idx, order, axis=-1)
        has_pen = (frequency_penalty != 0) | (presence_penalty != 0)
        greedy = jnp.where(has_pen, top_idx[:, 0].astype(jnp.int32), greedy)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = top_logits / safe_temp[:, None]

    # top-k: candidates are descending, so rank >= k is out (0 => disabled).
    ranks = jnp.arange(cand, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k <= 0, cand, jnp.minimum(top_k, cand))
    scaled = jnp.where(ranks < k[:, None], scaled, NEG_INF)

    # top-p: keep tokens while the cumulative mass before them is < top_p;
    # the argmax is always kept.
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]
    keep = keep.at[:, 0].set(True)
    scaled = jnp.where(keep, scaled, NEG_INF)

    choice = jax.vmap(lambda key, row: jax.random.categorical(key, row))(keys, scaled)
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature > 0, sampled, greedy)


def token_logprobs(
    logits: jnp.ndarray,  # f32[B, vocab] RAW model logits
    tokens: jnp.ndarray,  # i32[B] sampled token per row
    k: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """OpenAI-style logprobs: (chosen_lp f32[B], top_ids i32[B, k],
    top_lps f32[B, k]) under log-softmax of the RAW logits — the model's
    distribution, before temperature/penalties (the convention the major
    serving stacks report; sampling modifiers change what is PICKED, not
    what the model believed)."""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    lps = logits - logz
    chosen = jnp.take_along_axis(lps, tokens[:, None].astype(jnp.int32), axis=-1)[:, 0]
    top_lps, top_ids = jax.lax.top_k(lps, k)
    return chosen, top_ids.astype(jnp.int32), top_lps
