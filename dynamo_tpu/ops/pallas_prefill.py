"""Pallas TPU chunked-prefill (flash) attention over the paged KV cache.

Prefill is the TTFT-critical phase: every query token of the chunk attends
causally to the sequence's full paged history (earlier chunks, prefix-cache
hits, or KV migrated from another worker) plus the chunk itself, which the
engine has already scattered into the cache before attention runs
(``models/llama.py:layer_step`` writes K/V first). The XLA reference
formulation (``ops/attention.py:paged_attention_reference``) materializes
the gathered K/V **and** the full ``[B, n_kv, g, T, S]`` f32 logits tensor
in HBM — at ISL 3000 that is hundreds of MB of HBM round-trips per layer.
This kernel is the flash formulation: KV pages stream HBM -> VMEM with
double-buffered async DMA, the T x S score tile lives only in VMEM, and the
online-softmax state (m, l, acc) is the only thing carried.

Design (shares the decode kernel's cache geometry, differs where the
bottleneck differs):

- Cache layout is the engine's flat ``[num_pages, page_size, W]`` with
  ``W = n_kv * head_dim`` — one page is one contiguous DMA slab covering
  all KV heads (see ``ops/pallas_paged.py`` for why this layout).
- Grid is ``(batch, q_blocks)``; each step owns a ``tq``-token query block
  of one sequence. Queries are staged by the caller as
  ``[n_kv, tq * group, head_dim]`` (t-major rows), so each KV head's group
  of query heads is one contiguous row block.
- Per step, a ``fori_loop`` walks the KV page-blocks this query block can
  see (**causal early exit**: the loop bound is
  ``cdiv(min(kv_len, start + (qi+1)*tq), block_tokens)``, so early query
  blocks never touch late pages). DMA is double-buffered within the step:
  block i+1 is in flight while block i is reduced.
- Compute is **per KV head** (a python-unrolled loop over ``n_kv``): head
  group ``kv``'s queries ``[tq*g, hd]`` contract against the slab's lane
  strip ``[bk, kv*hd:(kv+1)*hd]``. Unlike the decode kernel's
  block-diagonal trick (which wastes ``n_kv``x MXU flops — free when
  DMA-bound, not here: prefill attention is MXU-bound at long context),
  this does only the useful flops.
- Causality needs no position tensor in the kernel: prefill chunks are
  contiguous, so query ``row r`` of block ``qi`` has absolute position
  ``start + qi*tq + r // g`` — ``start`` (per-row chunk offset, scalar
  prefetch) is all it takes, and chunked prefill / prefix resumption are
  exact.

Replaces the prefill-phase attention kernels inside vLLM/TRT-LLM that the
reference wraps (SURVEY.md §2 row 30, §7 hard part (a)).

Tests: ``tests/test_pallas_prefill.py`` (interpret mode vs the reference
formulation, incl. chunked continuation); ``tests_tpu/test_on_device.py``
(Mosaic-compiled parity + perf on the real chip).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128

# jax >= 0.4.34 renamed TPUCompilerParams -> CompilerParams; support both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _block_tokens(page_size: int, width: int) -> int:
    """KV tokens per compute block, budgeted against scoped VMEM (~16 MB):
    the double-buffered K+V slabs cost ``8 * bk * width`` bytes, capped at
    ~4 MB; at most 512 tokens (diminishing DMA-amortization returns)."""
    cap = (4 * 2**20) // (8 * width)
    pages = max(1, min(512, cap) // page_size)
    return pages * page_size


def _tq_for(group: int, t: int, n_kv: int, head_dim: int) -> int:
    """Query-block tokens, budgeted so the per-row VMEM state fits.

    Each score-tile row carries, per KV head, two lane-padded f32 [rows,1]
    softmax stats (~1 KB) plus f32 acc / bf16 q / f32 o strips (~10 bytes
    per head_dim lane); cap the total at ~4 MB, and at 256 rows (score
    tile size)."""
    per_row = n_kv * (1024 + 10 * head_dim)
    rows = max(group, min(256, (4 * 2**20) // per_row))
    tq = max(1, rows // group)
    if tq >= t:
        return t  # whole-array block: Mosaic allows any size
    # Partial blocks need tq * group (the sublane dim) divisible by 8.
    step = 8 // math.gcd(group, 8)
    return min(t, max(step, tq // step * step))


def _prefill_kernel(
    # scalar prefetch (SMEM)
    kv_lens_ref,  # i32[B] attendable keys per row (chunk included; >= 1)
    starts_ref,  # i32[B] absolute position of the row's first query token
    tables_ref,  # i32[B * pages_per_seq]
    # blocked operands
    q_ref,  # [n_kv, tq * g, hd] pre-scaled, cache dtype
    k_hbm,  # [P, page_size, W] in HBM/ANY
    v_hbm,
    o_ref,  # f32[n_kv, tq * g, hd]
    # scratch
    k_buf,  # [2, bk, W] VMEM
    v_buf,
    k_sem,
    v_sem,
    *,
    tq: int,
    group: int,
    pages_per_seq: int,
    pages_per_block: int,
    page_size: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    bk = pages_per_block * page_size
    kv_len = jnp.maximum(kv_lens_ref[b], 1)
    start = starts_ref[b]
    # Causal bound: this query block's last token sits at absolute position
    # start + (qi+1)*tq - 1, so no key block past that is ever needed.
    kend = jnp.clip(start + (qi + 1) * tq, 1, kv_len)
    # Rows whose real span is shorter than the batch's T (mixed steps fuse
    # 1-token decode rows with chunk rows; their output past the span is
    # discarded) skip query blocks that hold no real token: the block's
    # first query sits at start + qi*tq, so past kv_len-1 there is nothing
    # to compute — and nothing to DMA (each skipped block saves the full
    # KV walk up to kv_len).
    has_work = start + qi * tq < kv_len
    num_blocks = jnp.where(has_work, pl.cdiv(kend, bk), 0)
    # Clamp page lookups to the row's own used range (not just the table
    # width) so sentinel-filled table tails can never be dereferenced.
    last_page = jnp.maximum(kv_len - 1, 0) // page_size

    def page_index(i, j):
        idx = jnp.minimum(i * pages_per_block + j, last_page)
        return tables_ref[b * pages_per_seq + idx]

    def start_block(slot, i):
        for j in range(pages_per_block):
            page = page_index(i, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).start()

    def wait_block(slot, i):
        for j in range(pages_per_block):
            page = page_index(i, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).wait()

    # The first DMA must not start for a skipped block: its semaphore would
    # never be waited here and would alias the next grid step's wait.
    @pl.when(num_blocks > 0)
    def _():
        start_block(0, 0)

    n_kv, rows, hd = q_ref.shape
    q_all = q_ref[...]  # [n_kv, tq*g, hd] pre-scaled, cache dtype
    # Absolute position of each query row (t-major: row r is chunk token
    # r // g), shared by every KV head.
    qpos = (
        start
        + qi * tq
        + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // group
    )  # [rows, 1]

    def body(i, carry):
        # carry: per-KV-head (m [rows,1], l [rows,1], acc [rows,hd]) tuples —
        # a flat pytree, because Mosaic has no scatter for stacked updates.
        cur = i % 2

        @pl.when(i + 1 < num_blocks)
        def _():
            start_block(1 - cur, i + 1)

        wait_block(cur, i)
        k = k_buf[cur]  # [bk, W]
        v = v_buf[cur]
        if k.dtype.itemsize < 2:  # fp8 cache: matmul in bf16
            k = k.astype(jnp.bfloat16)
            v = v.astype(jnp.bfloat16)
        kpos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)  # [1, bk]
        mask = jnp.logical_and(kpos <= qpos, kpos < kv_len)  # [rows, bk]

        out = []
        for kv in range(n_kv):
            m, l, acc = carry[kv]
            ks = k[:, kv * hd : (kv + 1) * hd]  # [bk, hd] lane strip
            vs = v[:, kv * hd : (kv + 1) * hd]
            s = jax.lax.dot_general(
                q_all[kv], ks, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # f32[rows, bk]
            s = jnp.where(mask, s, NEG_INF)
            mk = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - mk)
            alpha = jnp.exp(m - mk)
            lk = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            ak = alpha * acc + jax.lax.dot_general(
                p.astype(vs.dtype), vs, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            out.append((mk, lk, ak))
        return tuple(out)

    init = tuple(
        (
            jnp.full((rows, 1), NEG_INF, jnp.float32),
            jnp.zeros((rows, 1), jnp.float32),
            jnp.zeros((rows, hd), jnp.float32),
        )
        for _ in range(n_kv)
    )
    final = jax.lax.fori_loop(0, num_blocks, body, init)
    for kv in range(n_kv):
        _, l, acc = final[kv]
        # Skipped blocks carry l == 0 (no softmax mass): write zeros, not
        # 0/0 NaNs — the caller discards these rows either way, but NaNs
        # must never be produced where a debug check could trip on them.
        o_ref[kv] = jnp.where(l > 0.0, acc / jnp.maximum(l, 1e-30), 0.0)


def prefill_supported(q: jnp.ndarray, k_cache: jnp.ndarray) -> bool:
    """Same geometry contract as the decode kernel (shared predicate): even
    GQA grouping and a 128-lane-aligned page slab width. The decode
    kernel's multi-query T cap does NOT apply — this kernel tiles the
    query axis, so chunk width is unbounded."""
    from dynamo_tpu.ops.pallas_paged import decode_kernel_supported

    return decode_kernel_supported(q.shape[-2], q.shape[-1], k_cache.shape[2])


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_prefill_attention(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, T] absolute position of each query token
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Prefill-phase (T > 1) paged flash attention; returns [B, T, H, hd].

    ``positions`` rows must be contiguous (``positions[b, t] = start_b + t``
    for real tokens) — true for every engine prefill row, chunked or not,
    including mid-prompt continuations after a prefix-cache hit (start > 0)
    and the 1-token decode rows a mixed step fuses in (start = kv_len - 1:
    exactly one real query). Batch-padding rows and T-padding tails produce
    zeros/garbage the caller already discards (their logits are never
    gathered); query blocks wholly past a row's real span are skipped in
    the kernel, so short rows don't re-walk their KV history."""
    b, t, n_heads, head_dim = q.shape
    num_pages, page_size, width = k_cache.shape
    n_kv = width // head_dim
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    tq = _tq_for(group, t, n_kv, head_dim)
    bk = _block_tokens(page_size, width)
    ppb = bk // page_size
    qb = pl.cdiv(t, tq)

    kv_lens = jnp.max(positions, axis=1) + 1  # i32[B]; padding rows -> 1
    starts = positions[:, 0]

    q_dtype = k_cache.dtype if k_cache.dtype.itemsize >= 2 else jnp.bfloat16
    # Stage queries [B, n_kv, T*g, hd] t-major, pre-scaled, in cache dtype.
    qs = (q.astype(jnp.float32) * scale).reshape(b, t, n_kv, group, head_dim)
    qs = qs.transpose(0, 2, 1, 3, 4).reshape(b, n_kv, t * group, head_dim)
    qs = qs.astype(q_dtype)

    rows = tq * group
    q_spec = pl.BlockSpec(
        (None, n_kv, rows, head_dim), lambda bb, qq, *_: (bb, 0, qq, 0)
    )
    kernel = functools.partial(
        _prefill_kernel,
        tq=tq,
        group=group,
        pages_per_seq=pages_per_seq,
        pages_per_block=ppb,
        page_size=page_size,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # kv_lens, starts, flat block table
            grid=(b, qb),
            in_specs=[
                q_spec,
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((2, bk, width), k_cache.dtype),
                pltpu.VMEM((2, bk, width), v_cache.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, t * group, head_dim), jnp.float32),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(
        kv_lens,
        starts,
        block_tables.reshape(-1),
        qs,
        k_cache,
        v_cache,
    )
    o = out.reshape(b, n_kv, t, group, head_dim).transpose(0, 2, 1, 3, 4)
    return o.reshape(b, t, n_heads, head_dim).astype(q.dtype)
