"""Pallas TPU decode kernel for MLA (DeepSeek latent attention).

In the absorbed formulation MLA decode IS multi-query attention: every
query head attends to ONE shared K/V stream — key ``[c ; k_rope]``
(latent width r_kv + rope width dr) and value ``c`` — so the paged cache
holds just ``r_kv + dr`` lanes per token (`models/mla.py`). The XLA gather
formulation materializes the gathered latents and reads them three times
per step (gather write, score einsum, output einsum): measured 0.21x of
the HBM roofline on v5e at DeepSeek-V3 MLA geometry (BENCH r04). This
kernel streams each page from HBM exactly once — double-buffered DMA,
online softmax, accumulation in latent space — the same structure as the
GQA decode kernel (`pallas_paged.py`), with two differences:

- TWO key streams per block: scores are ``q_lat @ c^T + q_rope @ r^T``
  (the rope part is a narrow 64-lane contraction riding the same DMA wave).
- The value IS the latent: ``acc += p @ c`` — no separate V stream at all,
  so HBM traffic per token is r_kv + dr bytes where GQA pays 2 * H_kv * hd.

Reference counterpart: none — the reference outsources kernels to
vLLM/TRT-LLM (SURVEY.md §2 row 30); this is the TPU-native equivalent of
their MLA/MQA decode kernels (flash-MLA class).
"""

from __future__ import annotations

import functools
import os

import jax

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(*args, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(*args, **kw)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128

# jax >= 0.4.34 renamed TPUCompilerParams -> CompilerParams; support both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def mla_decode_supported(r_kv: int, r_width: int) -> bool:
    """Geometry the kernel handles: both streams lane-aligned (the rope
    stream is pre-padded to a 128-lane tile by ``mla_cache_widths`` —
    Mosaic cannot DMA sub-tile HBM slices)."""
    return r_kv % LANES == 0 and r_width % LANES == 0


def _mla_decode_kernel(
    # scalar prefetch (SMEM)
    lengths_ref,  # i32[B]
    tables_ref,  # i32[B * pages_per_seq]
    # blocked operands
    q_lat_ref,  # [n_heads, r_kv]  pre-scaled, cache dtype
    q_rope_ref,  # [n_heads, dr]
    c_hbm,  # [P, page_size, r_kv] in HBM/ANY
    r_hbm,  # [P, page_size, dr]
    o_ref,  # f32[n_heads, r_kv]
    # scratch
    c_buf,  # [2, block_tokens, r_kv] VMEM
    r_buf,  # [2, block_tokens, dr] VMEM
    c_sem,
    r_sem,
    *,
    batch: int,
    pages_per_seq: int,
    pages_per_block: int,
    page_size: int,
):
    b = pl.program_id(0)
    bk = pages_per_block * page_size
    length = lengths_ref[b]
    num_blocks = pl.cdiv(length, bk)

    def blocks_of(bb):
        return pl.cdiv(jnp.maximum(lengths_ref[bb], 1), bk)

    start_parity = (
        jax.lax.fori_loop(0, b, lambda bb, acc: acc + blocks_of(bb), jnp.int32(0)) % 2
    )

    def page_index(bb, ii, j):
        last = jnp.maximum(lengths_ref[bb] - 1, 0) // page_size
        idx = jnp.minimum(ii * pages_per_block + j, last)
        return tables_ref[bb * pages_per_seq + idx]

    def start_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                c_hbm.at[page], c_buf.at[slot, rows, :], c_sem.at[slot]
            ).start()
            pltpu.make_async_copy(
                r_hbm.at[page], r_buf.at[slot, rows, :], r_sem.at[slot]
            ).start()

    def wait_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                c_hbm.at[page], c_buf.at[slot, rows, :], c_sem.at[slot]
            ).wait()
            pltpu.make_async_copy(
                r_hbm.at[page], r_buf.at[slot, rows, :], r_sem.at[slot]
            ).wait()

    def next_indices(ii):
        advance = ii + 1 >= num_blocks
        nb = jnp.where(advance, b + 1, b)
        ni = jnp.where(advance, 0, ii + 1)
        is_last_overall = jnp.logical_and(nb >= batch, advance)
        return jnp.minimum(nb, batch - 1), ni, is_last_overall

    @pl.when(b == 0)
    def _():
        start_block(0, 0, 0)

    n_heads, r_kv = q_lat_ref.shape
    q_lat = q_lat_ref[...]
    q_rope = q_rope_ref[...]

    def body(i, carry):
        m, l, acc = carry
        cur = (start_parity + i) % 2
        nb, ni, is_last = next_indices(i)

        @pl.when(jnp.logical_not(is_last))
        def _():
            start_block(1 - cur, nb, ni)

        wait_block(cur, b, i)

        c = c_buf[cur]  # [bk, r_kv] cache dtype
        r = r_buf[cur]  # [bk, dr]
        if c.dtype.itemsize < 2:  # fp8 cache: DMA at 1 B/elem, matmul in bf16
            c = c.astype(jnp.bfloat16)
            r = r.astype(jnp.bfloat16)
        # MQA: one shared K stream; scores are the latent contraction plus
        # the narrow rope contraction (both MXU, f32 accumulation).
        s = jax.lax.dot_general(
            q_lat, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + jax.lax.dot_general(
            q_rope, r, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[H, bk]
        kpos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        # The value IS the latent stream.
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(c.dtype), c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # f32[H, r_kv]
        return m_new, l_new, acc_new

    m0 = jnp.full((n_heads, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_heads, 1), jnp.float32)
    acc0 = jnp.zeros((n_heads, r_kv), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    o_ref[...] = acc / l


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def mla_paged_decode(
    q_lat: jnp.ndarray,  # [B, n_heads, r_kv] absorbed queries (NOT scaled)
    q_rope: jnp.ndarray,  # [B, n_heads, dr] rope queries (NOT scaled)
    c_cache: jnp.ndarray,  # [P, page_size, r_kv] latent pages
    r_cache: jnp.ndarray,  # [P, page_size, dr] rope-key pages
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, 1] decode-token position
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Paged MLA decode; returns latent-space output f32[B, n_heads, r_kv]
    (callers apply the absorbed W_uv up-projection)."""
    from dynamo_tpu.ops.pallas_paged import _pages_per_block

    b, n_heads, r_kv = q_lat.shape
    num_pages, page_size, _ = c_cache.shape
    pages_per_seq = block_tables.shape[1]
    dr = r_cache.shape[2]
    ppb = _pages_per_block(pages_per_seq, page_size, r_kv + dr, c_cache.dtype.itemsize)
    bk = ppb * page_size

    lengths = positions[:, 0] + 1

    q_dtype = c_cache.dtype if c_cache.dtype.itemsize >= 2 else jnp.bfloat16
    q_lat_s = (q_lat.astype(jnp.float32) * scale).astype(q_dtype)
    q_rope_s = (q_rope.astype(jnp.float32) * scale).astype(q_dtype)

    kernel = functools.partial(
        _mla_decode_kernel,
        batch=b,
        pages_per_seq=pages_per_seq,
        pages_per_block=ppb,
        page_size=page_size,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b,),
            in_specs=[
                pl.BlockSpec((None, n_heads, r_kv), lambda bb, *_: (bb, 0, 0)),
                pl.BlockSpec((None, n_heads, dr), lambda bb, *_: (bb, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((None, n_heads, r_kv), lambda bb, *_: (bb, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, bk, r_kv), c_cache.dtype),
                pltpu.VMEM((2, bk, dr), r_cache.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_heads, r_kv), jnp.float32),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(
        lengths,
        block_tables.reshape(-1),
        q_lat_s,
        q_rope_s,
        c_cache,
        r_cache,
    )
    return out


def mla_paged_decode_sharded(
    q_lat: jnp.ndarray,  # [B, n_heads, r_kv]
    q_rope: jnp.ndarray,  # [B, n_heads, r_width]
    c_cache: jnp.ndarray,
    r_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    mesh,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """MLA decode kernel under a device mesh: tp shards the QUERY heads,
    dp the batch; the latent/rope caches are replicated (MQA — every head
    reads the same stream; `parallel/sharding.cache_shardings` places the
    MLA cache replicated for exactly this reason). No collectives inside:
    each device streams the full cache once for its head slice — the same
    total HBM traffic as single-chip, split across chips' own HBM copies."""
    from jax.sharding import PartitionSpec as P

    batch_axis = "dp" if "dp" in mesh.axis_names else None
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    q_spec = P(batch_axis, tp_axis, None)
    row_spec = P(batch_axis, None)

    def body(ql, qr, cc, rc, bt, pos):
        return mla_paged_decode(
            ql, qr, cc, rc, bt, pos, scale=scale, interpret=interpret
        )

    return _shard_map(
        body, mesh=mesh,
        in_specs=(q_spec, q_spec, P(), P(), row_spec, row_spec),
        out_specs=q_spec,
        check_vma=False,  # pallas out_shape carries no vma metadata
    )(q_lat, q_rope, c_cache, r_cache, block_tables, positions)


from dynamo_tpu.ops.pallas_paged import interpret_mode  # noqa: E402  (shared flag)
