"""Pallas TPU decode kernel for MLA (DeepSeek latent attention).

In the absorbed formulation MLA decode IS multi-query attention: every
query head attends to ONE shared K/V stream — key ``[c ; k_rope]``
(latent width r_kv + rope width dr) and value ``c`` — so the paged cache
holds just ``r_kv + dr`` lanes per token (`models/mla.py`). The XLA gather
formulation materializes the gathered latents and reads them three times
per step (gather write, score einsum, output einsum): measured 0.21x of
the HBM roofline on v5e at DeepSeek-V3 MLA geometry (BENCH r04). This
kernel streams each page from HBM exactly once — an N-deep DMA ring,
online softmax, accumulation in latent space — the same split-K,
multi-query structure as the GQA decode kernel (`pallas_paged.py`, whose
helpers it shares; see ``docs/KERNELS.md``), with two differences:

- TWO key streams per block: scores are ``q_lat @ c^T + q_rope @ r^T``
  (the rope part is a narrow 128-lane contraction riding the same DMA wave).
- The value IS the latent: ``acc += p @ c`` — no separate V stream at all,
  so HBM traffic per token is r_kv + dr bytes where GQA pays 2 * H_kv * hd.

Because MLA is already MQA, multi-query verify rows need no block-diagonal
staging: T_q query tokens per sequence are a plain ``[T_q * n_heads, r_kv]``
row stack, each row masked to its own token's causal horizon — speculative
verify batches run on this kernel instead of the gather formulation.

Reference counterpart: none — the reference outsources kernels to
vLLM/TRT-LLM (SURVEY.md §2 row 30); this is the TPU-native equivalent of
their MLA/MQA decode kernels (flash-MLA class).
"""

from __future__ import annotations

import functools

import jax

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(*args, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(*args, **kw)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas_paged import (  # shared kernel helpers
    _auto_num_splits,
    _dma_depth,
    _lse_combine,
    _max_verify_t,
    _pages_per_block,
    interpret_mode,  # noqa: F401  (re-exported: models/mla.py imports it here)
)

NEG_INF = -1e30
LANES = 128

# jax >= 0.4.34 renamed TPUCompilerParams -> CompilerParams; support both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def mla_decode_supported(
    r_kv: int,
    r_width: int,
    t_q: int = 1,
    n_heads: int = 1,
    *,
    interpret: bool = False,
) -> bool:
    """Geometry the kernel handles: both streams lane-aligned (the rope
    stream is pre-padded to a 128-lane tile by ``mla_cache_widths`` —
    Mosaic cannot DMA sub-tile HBM slices). Interpret mode (CPU tests /
    dryruns) relaxes only the lane alignment. ``t_q`` > 1 (multi-query
    verify rows) is capped by the VMEM row budget."""
    if not interpret and (r_kv % LANES != 0 or r_width % LANES != 0):
        return False
    return t_q <= _max_verify_t(max(1, n_heads), r_kv + r_width)


def _mla_decode_kernel(
    # scalar prefetch (SMEM)
    lengths_ref,  # i32[B] per-sequence walk length (max row position + 1)
    tables_ref,  # i32[B * pages_per_seq]
    qpos_ref,  # i32[B * t_q] absolute position of each query token
    # blocked operands
    q_lat_ref,  # [t_q * n_heads, r_kv]  pre-scaled, cache dtype
    q_rope_ref,  # [t_q * n_heads, r_width]
    c_hbm,  # [P, page_size, r_kv] in HBM/ANY
    r_hbm,  # [P, page_size, r_width]
    acc_ref,  # f32[t_q * n_heads, r_kv] — this (b, split)'s partial
    m_ref,  # f32[t_q * n_heads, LANES]
    l_ref,  # f32[t_q * n_heads, LANES]
    # scratch
    c_buf,  # [dma_depth, block_tokens, r_kv] VMEM ring
    r_buf,  # [dma_depth, block_tokens, r_width]
    c_sem,
    r_sem,
    *,
    batch: int,
    pages_per_seq: int,
    pages_per_block: int,
    page_size: int,
    blocks_per_split: int,
    t_q: int,
    n_heads: int,
    dma_depth: int,
):
    b = pl.program_id(0)
    sp = pl.program_id(1)
    bk = pages_per_block * page_size

    def blocks_of(bb):
        return pl.cdiv(jnp.maximum(lengths_ref[bb], 1), bk)

    nb_total = blocks_of(b)
    # Static split boundaries (see pallas_paged._decode_kernel): a row's
    # accumulation order never depends on other rows' runtime lengths.
    first = sp * blocks_per_split
    nb_here = jnp.clip(nb_total - first, 0, blocks_per_split)

    g0 = (
        jax.lax.fori_loop(0, b, lambda bb, acc: acc + blocks_of(bb), jnp.int32(0))
        + jnp.minimum(first, nb_total)
    )

    def page_index(bb, ii, j):
        last = jnp.maximum(lengths_ref[bb] - 1, 0) // page_size
        idx = jnp.minimum(ii * pages_per_block + j, last)
        return tables_ref[bb * pages_per_seq + idx]

    def start_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                c_hbm.at[page], c_buf.at[slot, rows, :], c_sem.at[slot]
            ).start()
            pltpu.make_async_copy(
                r_hbm.at[page], r_buf.at[slot, rows, :], r_sem.at[slot]
            ).start()

    def wait_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                c_hbm.at[page], c_buf.at[slot, rows, :], c_sem.at[slot]
            ).wait()
            pltpu.make_async_copy(
                r_hbm.at[page], r_buf.at[slot, rows, :], r_sem.at[slot]
            ).wait()

    def next_block(bb, ii):
        advance = ii + 1 >= blocks_of(jnp.minimum(bb, batch - 1))
        nb = jnp.where(advance, bb + 1, bb)
        ni = jnp.where(advance, 0, ii + 1)
        return nb, ni

    def start_ahead(slot, bb, ii):
        @pl.when(bb < batch)
        def _():
            start_block(slot, bb, ii)

    @pl.when(jnp.logical_and(b == 0, sp == 0))
    def _():
        bb, ii = jnp.int32(0), jnp.int32(0)
        for g in range(dma_depth - 1):
            start_ahead(g % dma_depth, bb, ii)
            bb, ii = next_block(bb, ii)

    r_rows, r_kv = q_lat_ref.shape
    q_lat = q_lat_ref[...]
    q_rope = q_rope_ref[...]

    # Row r scores query token r // n_heads against that token's own
    # causal horizon (multi-query verify rows; t_q == 1 reduces to the
    # plain decode mask).
    row_t = jax.lax.broadcasted_iota(jnp.int32, (r_rows, 1), 0) // n_heads
    qpos = jnp.zeros((r_rows, 1), jnp.int32)
    for tt in range(t_q):
        qpos = jnp.where(row_t == tt, qpos_ref[b * t_q + tt], qpos)

    def body(i, carry):
        m, l, acc = carry
        ii = first + i
        g = g0 + i
        slot = g % dma_depth
        bb, nxt = b, ii
        for _ in range(dma_depth - 1):
            bb, nxt = next_block(bb, nxt)
        start_ahead((g + dma_depth - 1) % dma_depth, bb, nxt)

        wait_block(slot, b, ii)

        c = c_buf[slot]  # [bk, r_kv] cache dtype
        r = r_buf[slot]  # [bk, r_width]
        if c.dtype.itemsize < 2:  # fp8 cache: DMA at 1 B/elem, matmul in bf16
            c = c.astype(jnp.bfloat16)
            r = r.astype(jnp.bfloat16)
        # MQA: one shared K stream; scores are the latent contraction plus
        # the narrow rope contraction (both MXU, f32 accumulation).
        s = jax.lax.dot_general(
            q_lat, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + jax.lax.dot_general(
            q_rope, r, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[R, bk]
        kpos = ii * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kpos <= qpos
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Explicit p mask: an all-masked block (possible under per-row
        # horizons) has s == m_new == NEG_INF and exp(0) would corrupt l.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        # The value IS the latent stream.
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(c.dtype), c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # f32[R, r_kv]
        return m_new, l_new, acc_new

    m0 = jnp.full((r_rows, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((r_rows, 1), jnp.float32)
    acc0 = jnp.zeros((r_rows, r_kv), jnp.float32)
    m_fin, l_fin, acc_fin = jax.lax.fori_loop(0, nb_here, body, (m0, l0, acc0))
    acc_ref[...] = acc_fin
    m_ref[...] = jnp.broadcast_to(m_fin, (r_rows, LANES))
    l_ref[...] = jnp.broadcast_to(l_fin, (r_rows, LANES))


@functools.partial(jax.jit, static_argnames=("scale", "interpret", "num_splits"))
def mla_paged_decode(
    q_lat: jnp.ndarray,  # [B, T, n_heads, r_kv] or [B, n_heads, r_kv] (T = 1)
    q_rope: jnp.ndarray,  # [B, T, n_heads, r_width] or [B, n_heads, r_width]
    c_cache: jnp.ndarray,  # [P, page_size, r_kv] latent pages
    r_cache: jnp.ndarray,  # [P, page_size, r_width] rope-key pages
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, T] absolute position of each query token
    *,
    scale: float,
    interpret: bool = False,
    num_splits: int = 0,  # 0 = auto (DYN_DECODE_SPLITS override)
) -> jnp.ndarray:
    """Paged MLA decode/verify; returns latent-space output
    f32[B, T, n_heads, r_kv] (3D in, 3D out for the T = 1 decode shape;
    callers apply the absorbed W_uv up-projection). Positions may be gappy
    per row — causality is per query token."""
    squeeze = q_lat.ndim == 3
    if squeeze:
        q_lat = q_lat[:, None]
        q_rope = q_rope[:, None]
    b, t_q, n_heads, r_kv = q_lat.shape
    num_pages, page_size, _ = c_cache.shape
    pages_per_seq = block_tables.shape[1]
    r_width = r_cache.shape[2]
    depth = _dma_depth()
    ppb = _pages_per_block(
        pages_per_seq, page_size, r_kv + r_width, c_cache.dtype.itemsize, depth
    )
    bk = ppb * page_size
    max_blocks = -(-(pages_per_seq * page_size) // bk)
    splits = num_splits if num_splits > 0 else _auto_num_splits(b, max_blocks)
    splits = max(1, min(splits, max_blocks))
    bps = -(-max_blocks // splits)

    # Walk covers the row's farthest token; rows mask their own horizon.
    lengths = jnp.max(positions, axis=1) + 1

    q_dtype = c_cache.dtype if c_cache.dtype.itemsize >= 2 else jnp.bfloat16
    r_rows = t_q * n_heads
    q_lat_s = (q_lat.astype(jnp.float32) * scale).astype(q_dtype).reshape(b, r_rows, r_kv)
    q_rope_s = (q_rope.astype(jnp.float32) * scale).astype(q_dtype).reshape(b, r_rows, r_width)

    kernel = functools.partial(
        _mla_decode_kernel,
        batch=b,
        pages_per_seq=pages_per_seq,
        pages_per_block=ppb,
        page_size=page_size,
        blocks_per_split=bps,
        t_q=t_q,
        n_heads=n_heads,
        dma_depth=depth,
    )
    acc_spec = pl.BlockSpec((None, None, r_rows, r_kv), lambda bb, ss, *_: (bb, ss, 0, 0))
    ml_spec = pl.BlockSpec((None, None, r_rows, LANES), lambda bb, ss, *_: (bb, ss, 0, 0))
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, splits),
            in_specs=[
                pl.BlockSpec((None, r_rows, r_kv), lambda bb, ss, *_: (bb, 0, 0)),
                pl.BlockSpec((None, r_rows, r_width), lambda bb, ss, *_: (bb, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[acc_spec, ml_spec, ml_spec],
            scratch_shapes=[
                pltpu.VMEM((depth, bk, r_kv), c_cache.dtype),
                pltpu.VMEM((depth, bk, r_width), r_cache.dtype),
                pltpu.SemaphoreType.DMA((depth,)),
                pltpu.SemaphoreType.DMA((depth,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, splits, r_rows, r_kv), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, r_rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, r_rows, LANES), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths,
        block_tables.reshape(-1),
        positions.reshape(-1),
        q_lat_s,
        q_rope_s,
        c_cache,
        r_cache,
    )
    out = _lse_combine(acc, m[..., 0], l[..., 0])  # [B, R, r_kv]
    out = out.reshape(b, t_q, n_heads, r_kv)
    return out[:, 0] if squeeze else out


def mla_paged_decode_sharded(
    q_lat: jnp.ndarray,  # [B, T, n_heads, r_kv] or [B, n_heads, r_kv]
    q_rope: jnp.ndarray,
    c_cache: jnp.ndarray,
    r_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    mesh,
    scale: float,
    interpret: bool = False,
    num_splits: int = 0,
) -> jnp.ndarray:
    """MLA decode kernel under a device mesh: tp shards the QUERY heads,
    dp the batch; the latent/rope caches are replicated (MQA — every head
    reads the same stream; `parallel/sharding.cache_shardings` places the
    MLA cache replicated for exactly this reason). No collectives inside:
    each device streams the full cache once for its head slice — the same
    total HBM traffic as single-chip, split across chips' own HBM copies."""
    from jax.sharding import PartitionSpec as P

    batch_axis = "dp" if "dp" in mesh.axis_names else None
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    if q_lat.ndim == 4:  # multi-query verify rows: heads on axis 2
        q_spec = P(batch_axis, None, tp_axis, None)
    else:
        q_spec = P(batch_axis, tp_axis, None)
    row_spec = P(batch_axis, None)

    def body(ql, qr, cc, rc, bt, pos):
        return mla_paged_decode(
            ql, qr, cc, rc, bt, pos, scale=scale, interpret=interpret,
            num_splits=num_splits,
        )

    return _shard_map(
        body, mesh=mesh,
        in_specs=(q_spec, q_spec, P(), P(), row_spec, row_spec),
        out_specs=q_spec,
        check_vma=False,  # pallas out_shape carries no vma metadata
    )(q_lat, q_rope, c_cache, r_cache, block_tables, positions)
