"""Decode-phase paged attention via the TPU Pallas kernel.

Wraps ``jax.experimental.pallas.ops.tpu.paged_attention`` — a public JAX op
that streams KV pages HBM->VMEM per (sequence, kv-head) with double
buffering and online softmax, never materializing the gathered K/V the
reference formulation builds. This is the HBM-bandwidth-bound hot loop of
serving; the cache layout ([n_kv, pages, page_size, head_dim] per layer) is
chosen engine-wide to be this kernel's native layout.

Kernel contract (decode, T == 1):
    q:            [B, n_heads, head_dim]   (pre-scaled here)
    k/v_pages:    [n_kv, total_pages, page_size, head_dim]
    lengths:      i32[B]  context length per sequence
    page_indices: i32[B, pages_per_seq]
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.cache
def _kernel():
    from jax.experimental.pallas.ops.tpu.paged_attention import paged_attention

    return paged_attention


def decode_attention_supported(q: jnp.ndarray, k_cache: jnp.ndarray) -> bool:
    """TPU backend, even grouping, and lane-aligned head_dim (the kernel's
    block shapes need head_dim % 128 == 0; smaller head dims take the XLA
    gather path until the small-head-dim kernel lands)."""
    if jax.default_backend() != "tpu":
        return False
    n_heads, head_dim = q.shape[2], q.shape[3]
    n_kv = k_cache.shape[0]
    return n_heads % n_kv == 0 and head_dim % 128 == 0


def _pick_pages_per_block(pages_per_seq: int) -> int:
    # Largest power-of-two divisor of pages_per_seq, capped at 8: keeps the
    # per-step VMEM footprint bounded while amortizing DMA issue overhead.
    for cand in (8, 4, 2, 1):
        if pages_per_seq % cand == 0:
            return cand
    return 1


def paged_decode_attention(
    q: jnp.ndarray,  # [B, 1, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [n_kv, pages, page_size, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, 1] — decode token's absolute position
    *,
    scale: float,
) -> jnp.ndarray:
    b, t, n_heads, head_dim = q.shape
    assert t == 1, "pallas decode path is T == 1 only"
    lengths = positions[:, 0] + 1  # context includes the token being decoded
    q3 = (q[:, 0].astype(jnp.float32) * scale).astype(q.dtype)
    out = _kernel()(
        q3,
        k_cache,
        v_cache,
        lengths,
        block_tables,
        pages_per_compute_block=_pick_pages_per_block(block_tables.shape[1]),
    )  # [B, n_heads, head_dim]
    return out[:, None].astype(q.dtype)
