"""Pallas TPU paged-attention kernel.

Streams a sequence's KV pages HBM -> VMEM and computes online-softmax
attention without materializing the full gathered K/V, the way the
reference's wrapped engines use vLLM's paged-attention CUDA kernel
(SURVEY.md §7 hard part (a)).

Strategy per (batch row, kv head): loop over that row's pages with
``jax.lax.fori_loop`` inside the kernel, using PrefetchScalarGridSpec so the
block table is available to index maps that stage each page into VMEM.

Until the tuned kernel lands (tracked in kernels TODO), this module exposes
the same signature backed by the reference formulation so TPU runs work
end-to-end; ``paged_attention_pallas`` is swapped in behind the same call
site. The kernel below is implemented for decode (T == 1), the HBM-bound hot
loop; prefill (T > 1) uses the XLA formulation, which is MXU-bound and
already near roofline after fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import paged_attention_reference


def paged_attention_pallas(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    scale: float,
) -> jnp.ndarray:
    try:
        from dynamo_tpu.ops.pallas_decode import decode_attention_supported, paged_decode_attention
    except ImportError:
        return paged_attention_reference(q, k_cache, v_cache, block_tables, positions, scale=scale)

    if q.shape[1] == 1 and decode_attention_supported(q, k_cache):
        return paged_decode_attention(q, k_cache, v_cache, block_tables, positions, scale=scale)
    return paged_attention_reference(q, k_cache, v_cache, block_tables, positions, scale=scale)
