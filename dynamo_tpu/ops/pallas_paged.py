"""Pallas TPU paged-attention decode kernel.

The HBM-bandwidth-bound hot loop of serving: for each decoding sequence,
attention must read that sequence's entire paged KV history once. This
kernel streams KV pages HBM -> VMEM with double-buffered async DMA and
computes online-softmax attention on the fly — the gathered K/V is never
materialized (the XLA reference formulation in ``ops/attention.py`` builds
a [B, S, n_kv, hd] gather per layer per step, which at batch 32 / 1k-token
contexts is tens of MB of extra HBM traffic per layer per decode step).

Design (fresh, built around the engine's page-major cache layout):

- Cache layout is the engine's flat ``[num_pages, page_size, n_kv * head_dim]``
  per layer (``ops/attention.py``): one page is a single contiguous
  ``page_size * n_kv * head_dim`` slab covering **all KV heads**, so each
  page needs exactly one DMA descriptor (~16 KB for Llama-3.2-1B) instead
  of one small copy per (head, page). DMA-descriptor issue rate, not
  bandwidth, is what limits a paged gather at page granularity — this
  layout is the difference between ~14 GB/s and saturating HBM.
- The trailing extent ``n_kv * head_dim`` is a multiple of 128 lanes for
  every serving config (8 x 64, 8 x 128, ...), satisfying Mosaic's DMA
  alignment even at head_dim 64 (Llama-3.2-1B) where a head-major layout
  cannot be sliced.
- Grid is ``(batch,)``; all KV heads of a sequence are processed together.
  GQA is one **block-diagonal matmul**: queries are staged as
  ``[n_heads, n_kv * head_dim]`` with head h's values in its own KV head's
  column strip, so ``scores = q_bd @ kv_slab.T`` yields every head's logits
  against its own KV head in a single MXU contraction (the off-strip
  products are computed and discarded — MXU cycles are free in a
  DMA-bound kernel). The weighted-value product accumulates the full
  ``[n_heads, n_kv * head_dim]`` strip; the caller extracts each head's
  diagonal strip with one fused XLA gather at the end.
- Per grid step, a ``fori_loop`` walks the sequence's page-blocks
  (``pages_per_block`` pages per iteration) carrying the online-softmax
  state (m, l, acc) — no scratch accumulators. The DMA pipeline is
  double-buffered **across grid steps**: while block i of sequence b is
  being reduced, the next block (possibly sequence b+1's first) is in
  flight. Buffer parity is a pure function of the global block index (a
  prefix count over earlier sequences), so there is no mutable cross-step
  state and the kernel is interpret-mode exact.

Replaces the role of vLLM's paged-attention CUDA kernel in the reference
stack (SURVEY.md §2 row 30, §7 hard part (a); `lib/llm/src/kernels/` is the
reference's only first-party kernel code).

Tests: ``tests/test_pallas_paged.py`` (interpret mode on CPU vs the
reference formulation); ``tests_tpu/test_on_device.py`` (Mosaic-compiled
parity on the real chip).
"""

from __future__ import annotations

import functools
import logging
import os
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.attention import paged_attention_reference

logger = logging.getLogger(__name__)

NEG_INF = -1e30
LANES = 128

# jax >= 0.4.34 renamed TPUCompilerParams -> CompilerParams; support both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

# Kernel-fallback observability: a config typo (odd GQA grouping, a page
# slab width off the 128-lane grid) silently costs ~5x decode throughput if
# the dispatch drops to the gather formulation. The dispatch runs at jit
# trace time, so each entry counts *compiled programs* that fell back (one
# per shape signature — exactly the "once per config" the operator needs),
# warns on first occurrence, and is exported by the frontend /metrics
# endpoint (frontend/metrics.py:FrontendMetrics.render).
FALLBACK_COUNTS: dict[str, int] = {}
_fallback_lock = threading.Lock()
_warned_signatures: set[str] = set()


def _record_fallback(phase: str, q: jnp.ndarray, k_cache: jnp.ndarray) -> None:
    sig = (
        f"{phase}:heads={q.shape[-2]},head_dim={q.shape[-1]},"
        f"slab_width={k_cache.shape[2]}"
    )
    with _fallback_lock:
        FALLBACK_COUNTS[sig] = FALLBACK_COUNTS.get(sig, 0) + 1
        warn = sig not in _warned_signatures
        _warned_signatures.add(sig)
    if warn:
        logger.warning(
            "paged-attention Pallas kernel does not support this shape, "
            "falling back to the XLA gather formulation (~5x slower %s): %s",
            phase,
            sig,
        )


def fallback_snapshot() -> dict[str, int]:
    """Race-free copy for metrics scrapes (trace threads mutate the dict)."""
    with _fallback_lock:
        return dict(FALLBACK_COUNTS)


def interpret_mode() -> bool:
    """DYNAMO_PALLAS_INTERPRET=1 runs every Pallas kernel (GQA decode,
    prefill flash, MLA decode) through the interpreter — CPU-executable, so
    multi-chip tests/dryruns cover the kernel path on a virtual mesh."""
    return os.environ.get("DYNAMO_PALLAS_INTERPRET", "") == "1"


def _pages_per_block(
    pages_per_seq: int, page_size: int, width: int | None = None, itemsize: int = 2
) -> int:
    """Pages per compute block: target ~1024 tokens per block, capped by the
    kernel's scoped-VMEM budget.

    Deep blocks amortize the fori_loop/online-softmax overhead and batch
    more DMA issues per wait (measured +45% decode throughput vs 2-page
    blocks at serving shapes). But the double-buffered K+V tiles
    (2 slots x 2 streams x bk x width) live in scoped VMEM with a hard
    ~16 MiB limit — wide slabs (e.g. 16 kv-heads x 128 = 2048 lanes) blow
    it at the 1024-token target (observed: OLMoE decode failing AOT
    compile with "scoped vmem ... exceeded"), so when ``width`` is given
    the block shrinks to keep the tiles within an 8 MiB budget. No
    divisibility requirement — the tail block clamps its page indices and
    masks by length."""
    target = max(1, 1024 // page_size)
    if width is not None:
        budget = 8 * 2**20
        max_tokens = max(page_size, budget // (4 * width * itemsize))
        target = min(target, max(1, max_tokens // page_size))
    return max(1, min(pages_per_seq, target))


def _decode_kernel(
    # scalar prefetch (SMEM, shared by all grid steps)
    lengths_ref,  # i32[B]
    tables_ref,  # i32[B * pages_per_seq]
    # blocked operands
    q_ref,  # f32[n_heads, W] block-diagonal queries, W = n_kv * head_dim
    k_hbm,  # [P, page_size, W] in HBM/ANY (page-major, heads flattened)
    v_hbm,
    o_ref,  # f32[n_heads, W] — full strip; caller extracts diagonals
    # scratch
    k_buf,  # [2, block_tokens, W] VMEM
    v_buf,
    k_sem,  # DMA sems [2]
    v_sem,
    *,
    batch: int,
    pages_per_seq: int,
    pages_per_block: int,
    page_size: int,
):
    b = pl.program_id(0)
    bk = pages_per_block * page_size  # tokens per compute block
    length = lengths_ref[b]
    num_blocks = pl.cdiv(length, bk)

    def blocks_of(bb):
        return pl.cdiv(jnp.maximum(lengths_ref[bb], 1), bk)

    # Double-buffer parity is a pure function of the global block index (no
    # mutable cross-step state): count the blocks of earlier sequences.
    start_parity = (
        jax.lax.fori_loop(0, b, lambda bb, acc: acc + blocks_of(bb), jnp.int32(0)) % 2
    )

    def page_index(bb, ii, j):
        # The tail block may reach past the sequence's allocated pages:
        # clamp to the row's own used range (not just the table width) so
        # the DMA never dereferences entries the engine didn't fill —
        # sentinel-filled tables (-1 tails) are safe, not just zero-filled
        # ones. Clamped tokens are masked out by the length check.
        last = jnp.maximum(lengths_ref[bb] - 1, 0) // page_size
        idx = jnp.minimum(ii * pages_per_block + j, last)
        return tables_ref[bb * pages_per_seq + idx]

    def start_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).start()

    def wait_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).wait()

    def next_indices(ii):
        """Global-order successor of block (b, ii): next block of this
        sequence, else the next sequence's block 0 (clamped at grid end)."""
        advance = ii + 1 >= num_blocks
        nb = jnp.where(advance, b + 1, b)
        ni = jnp.where(advance, 0, ii + 1)
        is_last_overall = jnp.logical_and(nb >= batch, advance)
        return jnp.minimum(nb, batch - 1), ni, is_last_overall

    # First grid step primes its own first block; every other step's block 0
    # was prefetched by its predecessor.
    @pl.when(b == 0)
    def _():
        start_block(0, 0, 0)

    n_heads, width = q_ref.shape
    # Keep matmul operands in the cache dtype (bf16): the MXU multiplies
    # bf16 natively with f32 accumulation — an f32 formulation costs multiple
    # MXU passes AND a whole-block VPU astype per K/V block, which measured
    # ~3x slower than HBM DMA on v5e (the kernel must stay DMA-bound).
    q_bd = q_ref[...]  # [H, W] block-diagonal, pre-scaled, cache dtype

    def body(i, carry):
        m, l, acc = carry
        cur = (start_parity + i) % 2
        nb, ni, is_last = next_indices(i)

        @pl.when(jnp.logical_not(is_last))
        def _():
            start_block(1 - cur, nb, ni)

        wait_block(cur, b, i)

        k = k_buf[cur]  # [bk, W] cache dtype
        v = v_buf[cur]
        if k.dtype.itemsize < 2:  # fp8 cache: DMA at 1 B/elem, matmul in bf16
            k = k.astype(jnp.bfloat16)
            v = v.astype(jnp.bfloat16)
        # Block-diagonal q: head h only overlaps its own KV head's strip, so
        # this one contraction is every head's logits against its KV head.
        s = jax.lax.dot_general(
            q_bd, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[H, bk]
        kpos = i * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))  # [H, 1]
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[H, W]; head h's answer lives in its own KV head's strip
        return m_new, l_new, acc_new

    m0 = jnp.full((n_heads, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_heads, 1), jnp.float32)
    acc0 = jnp.zeros((n_heads, width), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    o_ref[...] = acc / l


def decode_supported(q: jnp.ndarray, k_cache: jnp.ndarray) -> bool:
    """Shapes this kernel handles on hardware: even GQA grouping and a
    128-lane-aligned page slab width (n_kv * head_dim).

    ``k_cache`` is the engine's flat page-major layout ``[P, page_size, W]``
    with ``W = n_kv * head_dim`` (``models/llama.py:init_kv_cache``)."""
    n_heads, head_dim = q.shape[-2], q.shape[-1]
    width = k_cache.shape[2]
    if width % head_dim != 0:
        return False
    n_kv = width // head_dim
    return n_heads % n_kv == 0 and width % LANES == 0


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q: jnp.ndarray,  # [B, 1, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim] (page-major, flat)
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, 1] absolute position of the decode token
    *,
    scale: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode-phase (T == 1) paged attention; returns [B, 1, n_heads, hd].

    Cache layout matches the engine exactly ([P, ps, W] flat slabs), so the
    layer-stacked cache can be passed as-is with per-layer offset tables."""
    b, t, n_heads, head_dim = q.shape
    assert t == 1, "decode kernel is T == 1 only"
    num_pages, page_size, width = k_cache.shape
    n_kv = width // head_dim
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    ppb = _pages_per_block(pages_per_seq, page_size, width, k_cache.dtype.itemsize)
    bk = ppb * page_size

    kf, vf = k_cache, v_cache

    lengths = positions[:, 0] + 1  # history + the token being decoded

    # Block-diagonal query staging: head kv*G+g occupies lane strip
    # [kv*hd, (kv+1)*hd). One einsum against eye(n_kv); XLA fuses it.
    # Scale in f32, then store in the cache dtype so the kernel's matmuls
    # run at native MXU bf16 rate.
    q3 = q[:, 0].astype(jnp.float32) * scale  # [B, H, hd]
    eye = jnp.eye(n_kv, dtype=jnp.float32)
    # Queries never drop below bf16 (an fp8 cache quantizes K/V storage, not
    # the live queries).
    q_dtype = k_cache.dtype if k_cache.dtype.itemsize >= 2 else jnp.bfloat16
    q_bd = jnp.einsum(
        "bkgd,kK->bkgKd", q3.reshape(b, n_kv, group, head_dim), eye
    ).reshape(b, n_heads, width).astype(q_dtype)

    spec = pl.BlockSpec((None, n_heads, width), lambda bb, *_: (bb, 0, 0))
    kernel = functools.partial(
        _decode_kernel,
        batch=b,
        pages_per_seq=pages_per_seq,
        pages_per_block=ppb,
        page_size=page_size,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths, flat block table
            grid=(b,),
            in_specs=[
                spec,
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=spec,
            scratch_shapes=[
                pltpu.VMEM((2, bk, width), k_cache.dtype),
                pltpu.VMEM((2, bk, width), v_cache.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_heads, width), jnp.float32),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(
        lengths,
        block_tables.reshape(-1),
        q_bd,
        kf,
        vf,
    )
    # Extract each head's diagonal strip: head kv*G+g reads lanes
    # [kv*hd, (kv+1)*hd). Fused einsum against the same eye.
    o5 = out.reshape(b, n_kv, group, n_kv, head_dim)
    o = jnp.einsum("bkgKd,kK->bkgd", o5, eye)
    return o.reshape(b, 1, n_heads, head_dim).astype(q.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim] (flat page-major)
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    scale: float,
    contiguous_positions: bool = True,
) -> jnp.ndarray:
    """TPU dispatch: decode kernel for T == 1, prefill flash kernel for
    T > 1, XLA gather formulation as the (counted, warned) fallback.

    The prefill kernel requires per-row contiguous positions
    (``positions[b, t] = start_b + t``) — true for every engine prefill,
    chunked or not. A T > 1 caller with gappy per-token positions (e.g. a
    speculative-verify batch) must pass ``contiguous_positions=False`` to
    get the exact reference formulation instead. When ``positions`` is a
    concrete array (outside jit) the contract is verified for real; under
    tracing the declaration is trusted — it is static routing, a traced
    check would force compiling both kernels behind a cond."""
    if q.shape[1] > 1 and contiguous_positions and not isinstance(
        jnp.asarray(positions), jax.core.Tracer
    ):
        import numpy as np

        def _row_ok(row) -> bool:
            # A valid engine row is a contiguous run starting anywhere,
            # padded with trailing zeros (runner._pad fill) — position 0 can
            # legitimately appear only at the row start. Pure-padding rows
            # are all zeros.
            nz = np.nonzero(row)[0]
            last = int(nz[-1]) if nz.size else 0
            return bool(
                (np.diff(row[: last + 1]) == 1).all() and not row[last + 1:].any()
            )

        pos = np.asarray(positions)
        bad = [i for i in range(pos.shape[0]) if not _row_ok(pos[i])]
        if bad:
            raise ValueError(
                f"paged_attention_pallas: positions are not per-row contiguous "
                f"(rows {bad}); pass contiguous_positions=False for gappy "
                f"layouts (speculative verify, sliding window)"
            )
    interpret = interpret_mode()
    if q.shape[1] == 1:
        if decode_supported(q, k_cache):
            return paged_decode_attention(
                q, k_cache, v_cache, block_tables, positions, scale=scale,
                interpret=interpret,
            )
        _record_fallback("decode", q, k_cache)
    else:
        from dynamo_tpu.ops.pallas_prefill import (
            paged_prefill_attention,
            prefill_supported,
        )

        if contiguous_positions and prefill_supported(q, k_cache):
            return paged_prefill_attention(
                q, k_cache, v_cache, block_tables, positions, scale=scale,
                interpret=interpret,
            )
        _record_fallback("prefill", q, k_cache)
    return paged_attention_reference(
        q, k_cache, v_cache, block_tables, positions, scale=scale
    )
