"""Pallas TPU paged-attention decode kernel (split-K, multi-query).

The HBM-bandwidth-bound hot loop of serving: for each decoding sequence,
attention must read that sequence's entire paged KV history once. This
kernel streams KV pages HBM -> VMEM with an N-deep ring of async DMAs and
computes online-softmax attention on the fly — the gathered K/V is never
materialized (the XLA reference formulation in ``ops/attention.py`` builds
a [B, S, n_kv, hd] gather per layer per step, which at batch 32 / 1k-token
contexts is tens of MB of extra HBM traffic per layer per decode step).

Design (fresh, built around the engine's page-major cache layout):

- Cache layout is the engine's flat ``[num_pages, page_size, n_kv * head_dim]``
  per layer (``ops/attention.py``): one page is a single contiguous
  ``page_size * n_kv * head_dim`` slab covering **all KV heads**, so each
  page needs exactly one DMA descriptor (~16 KB for Llama-3.2-1B) instead
  of one small copy per (head, page). DMA-descriptor issue rate, not
  bandwidth, is what limits a paged gather at page granularity — this
  layout is the difference between ~14 GB/s and saturating HBM.
- The trailing extent ``n_kv * head_dim`` is a multiple of 128 lanes for
  every serving config (8 x 64, 8 x 128, ...), satisfying Mosaic's DMA
  alignment even at head_dim 64 (Llama-3.2-1B) where a head-major layout
  cannot be sliced.
- **Multi-query rows** (speculative verify): the kernel accepts T_q >= 1
  query tokens per sequence, staged as ``[T_q * n_heads, W]`` block-diagonal
  strips. Causality is a per-ROW mask ``kpos <= position[b, t]`` — exact
  for gappy verify layouts, and for T_q = 1 it reduces bit-for-bit to the
  plain decode mask (``kpos < length``). A K+1-wide verify row therefore
  attends exactly as K+1 sequential decodes would, on the same DMA-
  pipelined path instead of the ~5x-slower XLA gather formulation.
- GQA is one **block-diagonal matmul**: row (t, h) carries head h's query
  in its own KV head's column strip, so ``scores = q_bd @ kv_slab.T``
  yields every (token, head) pair's logits against its KV head in a single
  MXU contraction (off-strip products are computed and discarded — MXU
  cycles are free in a DMA-bound kernel). The weighted-value product
  accumulates the full ``[T_q * n_heads, W]`` strip; the caller extracts
  each head's diagonal strip with one fused XLA gather at the end.
- **Split-K grid** ``(batch, num_splits)`` (Flash-Decoding style): each
  split walks its static slice of the sequence's page-block list carrying
  partial online-softmax state (m, l, acc) and writes per-split outputs;
  a small log-sum-exp combine (:func:`_lse_combine`) merges them. Split
  boundaries are functions of STATIC shapes only (pages bucket, page
  size, block size) — never of runtime lengths — so the per-row float
  accumulation order is identical whether a row is scored as a T_q = 1
  decode or inside a T_q = K+1 verify batch. ``num_splits`` is auto-chosen
  from batch x context (``DYN_DECODE_SPLITS`` overrides) so low-batch
  long-context decode keeps multiple DMA streams in flight instead of one
  sequential block walk per sequence.
- The DMA pipeline is an N-deep ring (``DYN_DECODE_DMA_DEPTH``, default
  4) **across grid steps**: while block g is being reduced, blocks
  g+1..g+depth-1 (possibly a later split's or sequence's) are in flight.
  Ring slot is a pure function of the global block index (a prefix count
  over earlier sequences and splits), so there is no mutable cross-step
  state and the kernel is interpret-mode exact.

Replaces the role of vLLM's paged-attention CUDA kernel in the reference
stack (SURVEY.md §2 row 30, §7 hard part (a); `lib/llm/src/kernels/` is the
reference's only first-party kernel code). See ``docs/KERNELS.md`` for the
full design note.

Tests: ``tests/test_pallas_paged.py`` (interpret mode on CPU vs the
reference formulation); ``tests_tpu/test_on_device.py`` (Mosaic-compiled
parity on the real chip).
"""

from __future__ import annotations

import functools
import logging
import os
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.attention import paged_attention_reference

logger = logging.getLogger(__name__)

NEG_INF = -1e30
LANES = 128

# jax >= 0.4.34 renamed TPUCompilerParams -> CompilerParams; support both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

# Kernel-fallback observability: a config typo (odd GQA grouping, a page
# slab width off the 128-lane grid) silently costs ~5x decode throughput if
# the dispatch drops to the gather formulation. The dispatch runs at jit
# trace time, so each entry counts *compiled programs* that fell back (one
# per shape signature — exactly the "once per config" the operator needs),
# warns on first occurrence, and is exported by the frontend /metrics
# endpoint (frontend/metrics.py:FrontendMetrics.render). Phases: ``decode``
# (T == 1), ``verify`` (T > 1 gappy rows — speculative verify), ``prefill``
# (T > 1 contiguous), ``sliding_window``, ``mla_decode``/``mla_verify``.
FALLBACK_COUNTS: dict[str, int] = {}
_fallback_lock = threading.Lock()
_warned_signatures: set[str] = set()


def _record_fallback(phase: str, q: jnp.ndarray, k_cache: jnp.ndarray) -> None:
    sig = (
        f"{phase}:heads={q.shape[-2]},head_dim={q.shape[-1]},"
        f"slab_width={k_cache.shape[2]}"
    )
    with _fallback_lock:
        FALLBACK_COUNTS[sig] = FALLBACK_COUNTS.get(sig, 0) + 1
        warn = sig not in _warned_signatures
        _warned_signatures.add(sig)
    if warn:
        logger.warning(
            "paged-attention Pallas kernel does not support this shape, "
            "falling back to the XLA gather formulation (~5x slower %s): %s",
            phase,
            sig,
        )


def fallback_snapshot() -> dict[str, int]:
    """Race-free copy for metrics scrapes (trace threads mutate the dict)."""
    with _fallback_lock:
        return dict(FALLBACK_COUNTS)


def interpret_mode() -> bool:
    """DYNAMO_PALLAS_INTERPRET=1 runs every Pallas kernel (GQA decode,
    prefill flash, MLA decode) through the interpreter — CPU-executable, so
    multi-chip tests/dryruns cover the kernel path on a virtual mesh."""
    return os.environ.get("DYNAMO_PALLAS_INTERPRET", "") == "1"


def _dma_depth() -> int:
    """Ring depth of the KV DMA pipeline (slots per stream).

    Depth 2 is the classic double buffer; deeper rings keep more page
    blocks in flight across split/sequence boundaries, hiding the issue
    latency of short tail blocks. ``DYN_DECODE_DMA_DEPTH`` overrides
    (min 2). Resolved at trace time — a static program parameter."""
    try:
        depth = int(os.environ.get("DYN_DECODE_DMA_DEPTH", "4"))
    except ValueError:
        depth = 4
    return max(2, depth)


def _max_verify_t(n_heads: int, width: int) -> int:
    """Largest T_q the multi-query kernel accepts per row.

    The staged queries, accumulator, and m/l state all scale with
    ``R = T_q * n_heads`` rows of ``width`` lanes in VMEM; past this cap a
    verify batch (e.g. a mixed step whose prefill chunks widened T to the
    chunk size) falls back to the gather formulation — recorded under the
    ``verify`` phase. ``DYN_VERIFY_T_MAX`` overrides the default of 32."""
    try:
        cap = int(os.environ.get("DYN_VERIFY_T_MAX", "32"))
    except ValueError:
        cap = 32
    # q (2B) + acc (4B f32) rows must fit a ~4 MiB slice of scoped VMEM.
    vmem_cap = (4 * 2**20) // max(1, n_heads * width * 6)
    return max(1, min(cap, vmem_cap))


def _auto_num_splits(batch: int, max_blocks: int) -> int:
    """Split-K factor: sequence-axis parallelism for the grid.

    At batch >= 8 the batch grid dimension already keeps the DMA engines
    busy; below that, split the block walk so low-batch long-context decode
    exposes ~8 concurrent walks (Flash-Decoding's regime). Clamped to the
    static block count — an all-empty split is wasted grid real estate.
    ``DYN_DECODE_SPLITS`` overrides (resolved at trace time)."""
    env = os.environ.get("DYN_DECODE_SPLITS", "")
    if env:
        try:
            return max(1, min(int(env), max_blocks))
        except ValueError:
            pass
    if batch >= 8:
        return 1
    return max(1, min(max_blocks, 8 // max(1, batch)))


def _pages_per_block(
    pages_per_seq: int,
    page_size: int,
    width: int | None = None,
    itemsize: int = 2,
    dma_depth: int = 2,
) -> int:
    """Pages per compute block: target ~1024 tokens per block, capped by the
    kernel's scoped-VMEM budget.

    Deep blocks amortize the fori_loop/online-softmax overhead and batch
    more DMA issues per wait (measured +45% decode throughput vs 2-page
    blocks at serving shapes). But the ring-buffered K+V tiles
    (dma_depth slots x 2 streams x bk x width) live in scoped VMEM with a
    hard ~16 MiB limit — wide slabs (e.g. 16 kv-heads x 128 = 2048 lanes)
    blow it at the 1024-token target (observed: OLMoE decode failing AOT
    compile with "scoped vmem ... exceeded"), so when ``width`` is given
    the block shrinks to keep the tiles within an 8 MiB budget (deeper
    rings trade block depth for pipeline depth at constant VMEM). No
    divisibility requirement — the tail block clamps its page indices and
    masks by length."""
    target = max(1, 1024 // page_size)
    if width is not None:
        budget = 8 * 2**20
        max_tokens = max(page_size, budget // (2 * dma_depth * width * itemsize))
        target = min(target, max(1, max_tokens // page_size))
    return max(1, min(pages_per_seq, target))


def _lse_combine(acc: jnp.ndarray, m: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """Merge per-split online-softmax partials along the split axis.

    ``acc`` f32[B, S, R, W] (unnormalized weighted values), ``m``/``l``
    f32[B, S, R] (running max / normalizer). Returns f32[B, R, W].

    An empty split carries (m=NEG_INF, l=0, acc=0): its rescale factor
    ``exp(NEG_INF - M)`` underflows to exactly 0.0, so it contributes
    nothing — and with a single split the combine is exactly ``acc / l``
    (alpha = exp(0) = 1 and the singleton sums are identity), keeping the
    non-split decode path bit-identical."""
    m_max = jnp.max(m, axis=1, keepdims=True)  # [B, 1, R]
    alpha = jnp.exp(m - m_max)  # [B, S, R]
    denom = jnp.sum(alpha * l, axis=1)  # [B, R]
    num = jnp.sum(acc * alpha[..., None], axis=1)  # [B, R, W]
    return num / denom[..., None]


def _decode_kernel(
    # scalar prefetch (SMEM, shared by all grid steps)
    lengths_ref,  # i32[B] per-sequence walk length (max row position + 1)
    tables_ref,  # i32[B * pages_per_seq]
    qpos_ref,  # i32[B * t_q] absolute position of each query token
    # blocked operands
    q_ref,  # [t_q * n_heads, W] block-diagonal queries, W = n_kv * head_dim
    k_hbm,  # [P, page_size, W] in HBM/ANY (page-major, heads flattened)
    v_hbm,
    acc_ref,  # f32[t_q * n_heads, W] — this (b, split)'s partial strip
    m_ref,  # f32[t_q * n_heads, LANES] — running max (broadcast on lanes)
    l_ref,  # f32[t_q * n_heads, LANES] — running normalizer
    # scratch
    k_buf,  # [dma_depth, block_tokens, W] VMEM ring
    v_buf,
    k_sem,  # DMA sems [dma_depth]
    v_sem,
    *,
    batch: int,
    pages_per_seq: int,
    pages_per_block: int,
    page_size: int,
    blocks_per_split: int,
    t_q: int,
    n_heads: int,
    dma_depth: int,
):
    b = pl.program_id(0)
    sp = pl.program_id(1)
    bk = pages_per_block * page_size  # tokens per compute block

    def blocks_of(bb):
        return pl.cdiv(jnp.maximum(lengths_ref[bb], 1), bk)

    nb_total = blocks_of(b)
    # Split sp walks block-in-sequence indices [first, first + nb_here).
    # Boundaries derive from the STATIC blocks_per_split, so a row's
    # accumulation order never depends on other rows' runtime lengths.
    first = sp * blocks_per_split
    nb_here = jnp.clip(nb_total - first, 0, blocks_per_split)

    # Ring slot is a pure function of the global block index (no mutable
    # cross-step state): blocks of earlier sequences plus earlier splits
    # of this one. Splits partition each sequence's walk, so the global
    # order is plain (sequence, block-in-sequence) lexicographic.
    g0 = (
        jax.lax.fori_loop(0, b, lambda bb, acc: acc + blocks_of(bb), jnp.int32(0))
        + jnp.minimum(first, nb_total)
    )

    def page_index(bb, ii, j):
        # The tail block may reach past the sequence's allocated pages:
        # clamp to the row's own used range (not just the table width) so
        # the DMA never dereferences entries the engine didn't fill —
        # sentinel-filled tables (-1 tails) are safe, not just zero-filled
        # ones. Clamped tokens are masked out by the position check.
        last = jnp.maximum(lengths_ref[bb] - 1, 0) // page_size
        idx = jnp.minimum(ii * pages_per_block + j, last)
        return tables_ref[bb * pages_per_seq + idx]

    def start_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).start()

    def wait_block(slot, bb, ii):
        for j in range(pages_per_block):
            page = page_index(bb, ii, j)
            rows = pl.ds(j * page_size, page_size)
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, rows, :], k_sem.at[slot]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, rows, :], v_sem.at[slot]
            ).wait()

    def next_block(bb, ii):
        """Global-order successor of block (bb, ii): the sequence's next
        block, else the next sequence's block 0. bb may walk past the last
        sequence — start_ahead guards on bb < batch before dereferencing."""
        advance = ii + 1 >= blocks_of(jnp.minimum(bb, batch - 1))
        nb = jnp.where(advance, bb + 1, bb)
        ni = jnp.where(advance, 0, ii + 1)
        return nb, ni

    def start_ahead(slot, bb, ii):
        @pl.when(bb < batch)
        def _():
            start_block(slot, bb, ii)

    # The very first grid step primes ring slots 0..depth-2; every later
    # block is started depth-1 blocks ahead of its consumption by the body
    # that consumes block g - depth + 1 (empty splits consume no global
    # indices, so the lookahead chain passes through them untouched).
    @pl.when(jnp.logical_and(b == 0, sp == 0))
    def _():
        bb, ii = jnp.int32(0), jnp.int32(0)
        for g in range(dma_depth - 1):
            start_ahead(g % dma_depth, bb, ii)
            bb, ii = next_block(bb, ii)

    r_rows, width = q_ref.shape
    # Keep matmul operands in the cache dtype (bf16): the MXU multiplies
    # bf16 natively with f32 accumulation — an f32 formulation costs multiple
    # MXU passes AND a whole-block VPU astype per K/V block, which measured
    # ~3x slower than HBM DMA on v5e (the kernel must stay DMA-bound).
    q_bd = q_ref[...]  # [R, W] block-diagonal, pre-scaled, cache dtype

    # Row r scores query token r // n_heads: its causal horizon is that
    # token's own absolute position (per-row mask — exact for gappy verify
    # layouts; for t_q == 1 identical to the plain kpos < length mask).
    row_t = jax.lax.broadcasted_iota(jnp.int32, (r_rows, 1), 0) // n_heads
    qpos = jnp.zeros((r_rows, 1), jnp.int32)
    for tt in range(t_q):
        qpos = jnp.where(row_t == tt, qpos_ref[b * t_q + tt], qpos)

    def body(i, carry):
        m, l, acc = carry
        ii = first + i  # block-in-sequence index
        g = g0 + i  # global block index
        slot = g % dma_depth
        # Start the block depth-1 ahead in the global walk; its ring slot's
        # previous occupant (block g - 1) was consumed last iteration.
        bb, nxt = b, ii
        for _ in range(dma_depth - 1):
            bb, nxt = next_block(bb, nxt)
        start_ahead((g + dma_depth - 1) % dma_depth, bb, nxt)

        wait_block(slot, b, ii)

        k = k_buf[slot]  # [bk, W] cache dtype
        v = v_buf[slot]
        if k.dtype.itemsize < 2:  # fp8 cache: DMA at 1 B/elem, matmul in bf16
            k = k.astype(jnp.bfloat16)
            v = v.astype(jnp.bfloat16)
        # Block-diagonal q: row (t, h) only overlaps head h's KV strip, so
        # this one contraction is every (token, head)'s logits.
        s = jax.lax.dot_general(
            q_bd, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[R, bk]
        kpos = ii * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kpos <= qpos  # per-row causal horizon
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))  # [R, 1]
        # Mask p explicitly: in an all-masked block s == m_new == NEG_INF
        # and exp(s - m_new) would be 1, corrupting l/acc. Where any real
        # key exists, where() selects exactly what exp(NEG_INF - m_new)
        # underflows to (0.0) — bit-identical to the unmasked formulation.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # f32[R, W]; row (t, h)'s answer lives in head h's strip
        return m_new, l_new, acc_new

    m0 = jnp.full((r_rows, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((r_rows, 1), jnp.float32)
    acc0 = jnp.zeros((r_rows, width), jnp.float32)
    m_fin, l_fin, acc_fin = jax.lax.fori_loop(0, nb_here, body, (m0, l0, acc0))
    # Unnormalized partials out; the host-side _lse_combine merges splits.
    # An empty split writes (NEG_INF, 0, 0) — annihilated by the combine.
    acc_ref[...] = acc_fin
    m_ref[...] = jnp.broadcast_to(m_fin, (r_rows, LANES))
    l_ref[...] = jnp.broadcast_to(l_fin, (r_rows, LANES))


def decode_kernel_supported(
    n_heads: int,
    head_dim: int,
    width: int,
    t_q: int = 1,
    *,
    interpret: bool = False,
) -> bool:
    """Pure-shape form of :func:`decode_supported` (no arrays needed —
    the engine's dispatch-path telemetry calls this from host code).

    Hardware requires even GQA grouping and a 128-lane-aligned page slab
    width; interpret mode (CPU tests / dryruns) relaxes only the lane
    alignment — Mosaic's DMA constraint, which the interpreter doesn't
    have. ``t_q`` > 1 (multi-query verify rows) is additionally capped by
    the VMEM row budget (:func:`_max_verify_t`)."""
    if width % head_dim != 0:
        return False
    n_kv = width // head_dim
    if n_heads % n_kv != 0:
        return False
    if not interpret and width % LANES != 0:
        return False
    return t_q <= _max_verify_t(n_heads, width)


def decode_supported(q: jnp.ndarray, k_cache: jnp.ndarray, *, interpret: bool = False) -> bool:
    """Shapes the decode/verify kernel handles for ``q [B, T, H, hd]``
    against the engine's flat page-major cache ``[P, page_size, W]`` with
    ``W = n_kv * head_dim`` (``models/llama.py:init_kv_cache``)."""
    n_heads, head_dim = q.shape[-2], q.shape[-1]
    t_q = q.shape[1] if q.ndim == 4 else 1
    return decode_kernel_supported(
        n_heads, head_dim, k_cache.shape[2], t_q, interpret=interpret
    )


@functools.partial(jax.jit, static_argnames=("scale", "interpret", "num_splits"))
def paged_decode_attention(
    q: jnp.ndarray,  # [B, T_q, n_heads, head_dim] (T_q = 1 decode, K+1 verify)
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim] (page-major, flat)
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, T_q] absolute position of each query token
    *,
    scale: float,
    interpret: bool = False,
    num_splits: int = 0,  # 0 = auto (_auto_num_splits / DYN_DECODE_SPLITS)
) -> jnp.ndarray:
    """Decode/verify paged attention; returns [B, T_q, n_heads, hd].

    Positions may be gappy per row (speculative verify batches, padding
    columns) — causality is per query token. Cache layout matches the
    engine exactly ([P, ps, W] flat slabs), so the layer-stacked cache can
    be passed as-is with per-layer offset tables."""
    b, t_q, n_heads, head_dim = q.shape
    num_pages, page_size, width = k_cache.shape
    n_kv = width // head_dim
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    depth = _dma_depth()
    ppb = _pages_per_block(pages_per_seq, page_size, width, k_cache.dtype.itemsize, depth)
    bk = ppb * page_size
    # Static upper bound on a sequence's block walk — split boundaries must
    # NOT depend on runtime lengths (bit-parity between T_q = 1 and verify).
    max_blocks = -(-(pages_per_seq * page_size) // bk)
    splits = num_splits if num_splits > 0 else _auto_num_splits(b, max_blocks)
    splits = max(1, min(splits, max_blocks))
    bps = -(-max_blocks // splits)

    kf, vf = k_cache, v_cache

    # Walk length covers the row's farthest query token (max, not last:
    # padding columns carry position 0); rows mask their own horizon.
    lengths = jnp.max(positions, axis=1) + 1

    # Block-diagonal query staging: row t * n_heads + (kv * G + g) occupies
    # lane strip [kv*hd, (kv+1)*hd). One einsum against eye(n_kv); XLA
    # fuses it. Scale in f32, then store in the cache dtype so the kernel's
    # matmuls run at native MXU bf16 rate.
    q5 = q.astype(jnp.float32) * scale  # [B, T, H, hd]
    eye = jnp.eye(n_kv, dtype=jnp.float32)
    # Queries never drop below bf16 (an fp8 cache quantizes K/V storage, not
    # the live queries).
    q_dtype = k_cache.dtype if k_cache.dtype.itemsize >= 2 else jnp.bfloat16
    r_rows = t_q * n_heads
    q_bd = jnp.einsum(
        "btkgd,kK->btkgKd", q5.reshape(b, t_q, n_kv, group, head_dim), eye
    ).reshape(b, r_rows, width).astype(q_dtype)

    q_spec = pl.BlockSpec((None, r_rows, width), lambda bb, ss, *_: (bb, 0, 0))
    acc_spec = pl.BlockSpec((None, None, r_rows, width), lambda bb, ss, *_: (bb, ss, 0, 0))
    ml_spec = pl.BlockSpec((None, None, r_rows, LANES), lambda bb, ss, *_: (bb, ss, 0, 0))
    kernel = functools.partial(
        _decode_kernel,
        batch=b,
        pages_per_seq=pages_per_seq,
        pages_per_block=ppb,
        page_size=page_size,
        blocks_per_split=bps,
        t_q=t_q,
        n_heads=n_heads,
        dma_depth=depth,
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # lengths, flat block table, query positions
            grid=(b, splits),
            in_specs=[
                q_spec,
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[acc_spec, ml_spec, ml_spec],
            scratch_shapes=[
                pltpu.VMEM((depth, bk, width), k_cache.dtype),
                pltpu.VMEM((depth, bk, width), v_cache.dtype),
                pltpu.SemaphoreType.DMA((depth,)),
                pltpu.SemaphoreType.DMA((depth,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, splits, r_rows, width), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, r_rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, r_rows, LANES), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(
        lengths,
        block_tables.reshape(-1),
        positions.reshape(-1),
        q_bd,
        kf,
        vf,
    )
    out = _lse_combine(acc, m[..., 0], l[..., 0])  # [B, R, W]
    # Extract each row's diagonal strip: row (t, kv*G+g) reads lanes
    # [kv*hd, (kv+1)*hd). Fused einsum against the same eye.
    o6 = out.reshape(b, t_q, n_kv, group, n_kv, head_dim)
    o = jnp.einsum("btkgKd,kK->btkgd", o6, eye)
    return o.reshape(b, t_q, n_heads, head_dim).astype(q.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim] (flat page-major)
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    scale: float,
    contiguous_positions: bool = True,
) -> jnp.ndarray:
    """TPU dispatch: decode kernel for T == 1, prefill flash kernel for
    contiguous T > 1, the same decode kernel in multi-query form for gappy
    T > 1 (speculative verify), XLA gather formulation as the (counted,
    warned) fallback.

    The prefill kernel requires per-row contiguous positions
    (``positions[b, t] = start_b + t``) — true for every engine prefill,
    chunked or not. A T > 1 caller with gappy per-token positions (a
    speculative-verify batch) must pass ``contiguous_positions=False``:
    that routes to the multi-query decode kernel, whose per-row causal
    mask is exact for any position layout (and to the reference
    formulation only when the shape is outside the kernel's support).
    When ``positions`` is a concrete array (outside jit) the contiguity
    contract is verified for real; under tracing the declaration is
    trusted — it is static routing, a traced check would force compiling
    both kernels behind a cond."""
    if q.shape[1] > 1 and contiguous_positions and not isinstance(
        jnp.asarray(positions), jax.core.Tracer
    ):
        import numpy as np

        def _row_ok(row) -> bool:
            # A valid engine row is a contiguous run starting anywhere,
            # padded with trailing zeros (runner._pad fill) — position 0 can
            # legitimately appear only at the row start. Pure-padding rows
            # are all zeros.
            nz = np.nonzero(row)[0]
            last = int(nz[-1]) if nz.size else 0
            return bool(
                (np.diff(row[: last + 1]) == 1).all() and not row[last + 1:].any()
            )

        pos = np.asarray(positions)
        bad = [i for i in range(pos.shape[0]) if not _row_ok(pos[i])]
        if bad:
            raise ValueError(
                f"paged_attention_pallas: positions are not per-row contiguous "
                f"(rows {bad}); pass contiguous_positions=False for gappy "
                f"layouts (speculative verify, sliding window)"
            )
    interpret = interpret_mode()
    if q.shape[1] == 1:
        if decode_supported(q, k_cache, interpret=interpret):
            return paged_decode_attention(
                q, k_cache, v_cache, block_tables, positions, scale=scale,
                interpret=interpret,
            )
        _record_fallback("decode", q, k_cache)
    elif not contiguous_positions:
        # Speculative verify: gappy per-row positions, T = K+1 (or the
        # chunk width in a mixed step). The multi-query kernel's per-row
        # mask makes it exact here — the batched verify that used to pay
        # gather-path cost runs on the DMA-pipelined kernel.
        if decode_supported(q, k_cache, interpret=interpret):
            return paged_decode_attention(
                q, k_cache, v_cache, block_tables, positions, scale=scale,
                interpret=interpret,
            )
        _record_fallback("verify", q, k_cache)
    else:
        from dynamo_tpu.ops.pallas_prefill import (
            paged_prefill_attention,
            prefill_supported,
        )

        if prefill_supported(q, k_cache):
            return paged_prefill_attention(
                q, k_cache, v_cache, block_tables, positions, scale=scale,
                interpret=interpret,
            )
        _record_fallback("prefill", q, k_cache)
    return paged_attention_reference(
        q, k_cache, v_cache, block_tables, positions, scale=scale
    )
