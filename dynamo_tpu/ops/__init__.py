"""TPU-native compute ops for the first-party JAX engine.

The reference outsources all model compute to wrapped engines (vLLM/TRT-LLM);
here the kernels are first-party:

- :mod:`dynamo_tpu.ops.norm`, :mod:`dynamo_tpu.ops.rope` — elementwise ops XLA
  fuses into the surrounding matmuls.
- :mod:`dynamo_tpu.ops.attention` — paged attention over a block-table KV
  cache. Pure-JAX gather formulation (runs anywhere, used in CPU CI) with a
  Pallas TPU kernel selected on TPU backends.
- :mod:`dynamo_tpu.ops.sampling` — vectorized greedy/temperature/top-k/top-p
  token sampling.
"""

from dynamo_tpu.ops.norm import rms_norm
from dynamo_tpu.ops.rope import apply_rope, rope_frequencies
from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.sampling import sample_tokens

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
    "paged_attention",
    "sample_tokens",
]
