"""RMSNorm. Computed in float32 regardless of input dtype, cast back on exit —
the standard numerically-safe pattern for bf16 TPU models."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, *, eps: float = 1e-5,
             plus_one: bool = False) -> jnp.ndarray:
    """``plus_one``: Gemma's zero-centered convention — the checkpoint
    stores w with output ``normed * (1 + w)``, added in f32 (HF computes
    ``output * (1.0 + weight.float())``)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    w32 = weight.astype(jnp.float32)
    if plus_one:
        w32 = w32 + 1.0
    return (normed * w32).astype(dtype)
