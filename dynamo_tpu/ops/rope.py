"""Rotary position embeddings (RoPE), including Llama-3-style frequency scaling.

Applied at arbitrary absolute positions (paged decode needs per-token
positions, not a contiguous range). Uses the "split halves" convention of the
Llama family: the head dim is split into two halves that rotate together.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    *,
    theta: float = 10000.0,
    scaling: dict | None = None,
) -> np.ndarray:
    """Inverse frequencies [head_dim//2], with optional Llama-3 rope scaling.

    ``scaling`` follows the HF config schema: ``{"rope_type": "llama3",
    "factor": f, "low_freq_factor": lo, "high_freq_factor": hi,
    "original_max_position_embeddings": n}``.
    """
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    rope_type = (scaling or {}).get("rope_type", (scaling or {}).get("type"))
    if rope_type in (None, "none", "default"):
        pass
    elif rope_type == "llama3":
        factor = float(scaling["factor"])
        lo = float(scaling["low_freq_factor"])
        hi = float(scaling["high_freq_factor"])
        orig = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * np.pi / inv_freq
        # Three bands: long wavelengths fully scaled, short untouched, smooth ramp between.
        smooth = (orig / wavelen - lo) / (hi - lo)
        smooth = np.clip(smooth, 0.0, 1.0)
        scaled = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = np.where(wavelen > orig / lo, inv_freq / factor, scaled)
    elif rope_type == "linear":
        inv_freq = inv_freq / float(scaling["factor"])
    elif rope_type == "yarn":
        # NTK-by-parts interpolation (YaRN): dims whose wavelength fits the
        # original context keep extrapolated freqs, long-wavelength dims get
        # fully interpolated, a smooth ramp in between (beta_fast/beta_slow).
        factor = float(scaling["factor"])
        orig = float(scaling.get("original_max_position_embeddings", 4096))
        beta_fast = float(scaling.get("beta_fast", 32.0))
        beta_slow = float(scaling.get("beta_slow", 1.0))
        dims = np.arange(0, head_dim, 2, dtype=np.float64)

        def corr_dim(num_rot: float) -> float:
            return (head_dim * np.log(orig / (num_rot * 2.0 * np.pi))) / (2.0 * np.log(theta))

        low = max(np.floor(corr_dim(beta_fast)), 0.0)
        high = min(np.ceil(corr_dim(beta_slow)), head_dim - 1.0)
        ramp = np.clip((dims / 2.0 - low) / max(high - low, 1e-3), 0.0, 1.0)
        extrapolation = 1.0 - ramp  # 1 where we keep original freqs
        inv_freq = inv_freq / factor * ramp + inv_freq * extrapolation
    else:
        raise ValueError(
            f"unsupported rope scaling type {rope_type!r} (supported: llama3, linear, yarn) — "
            f"serving with unscaled frequencies would silently corrupt long-context output"
        )
    return inv_freq.astype(np.float32)


def rope_attention_factor(scaling: dict | None) -> float:
    """YaRN attention-temperature scaling (mscale).

    YaRN scales the rotated q/k embeddings by ``0.1*ln(s) + 1`` (the paper's
    ``sqrt(1/t)``), so attention logits grow by its square; HF exposes an
    explicit ``attention_factor`` override. Models apply the square to q once
    — equivalent to scaling both rotated tensors, one multiply cheaper.
    Non-yarn scaling types don't temperature-correct (factor 1.0).
    """
    if not scaling or scaling.get("rope_type", scaling.get("type")) != "yarn":
        return 1.0
    explicit = scaling.get("attention_factor")
    if explicit is not None:
        return float(explicit)
    factor = float(scaling.get("factor", 1.0))
    return 0.1 * float(np.log(factor)) + 1.0 if factor > 1.0 else 1.0


def apply_mrope(
    x: jnp.ndarray,  # [B, T, H, hd]
    positions3: jnp.ndarray,  # i32[B, 3, T] — (temporal, height, width)
    inv_freq: jnp.ndarray,  # [hd/2]
    sections: tuple[int, ...],  # e.g. (16, 24, 24), sums to hd/2
) -> jnp.ndarray:
    """Multimodal 3D rope (Qwen2-VL): frequency dims are partitioned into
    ``sections``; section j's dims take their rotation angle from coordinate
    axis j. Text tokens carry equal coords on all three axes, for which this
    reduces exactly to :func:`apply_rope`. Mirrors HF
    ``apply_multimodal_rotary_pos_emb`` (modeling_qwen2_vl.py:156) in the
    half-split convention."""
    angles3 = positions3[..., None].astype(jnp.float32) * inv_freq  # [B, 3, T, hd/2]
    oh = np.zeros((3, inv_freq.shape[0]), np.float32)
    start = 0
    for j, s in enumerate(sections):
        oh[j, start : start + s] = 1.0
        start += s
    angles = jnp.einsum("bctf,cf->btf", angles3, jnp.asarray(oh))
    cos = jnp.cos(angles)[..., None, :]  # [B, T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x32 = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [..., T, n_heads, head_dim] at absolute ``positions`` [..., T]."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x32 = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
