"""Paged attention over a block-table KV cache.

One op serves both phases: prefill is the ``T > 1`` case, decode the ``T = 1``
case, and prefix-cache reuse / chunked prefill fall out naturally because
queries always attend to the *paged* cache (which may hold tokens computed by
an earlier chunk, an earlier turn, or a different worker after KV migration)
rather than to an in-flight contiguous K/V tensor.

Layout (per layer): ``k_cache, v_cache: [num_pages, page_size, W]`` with
``W = n_kv * head_dim`` — **page-major, heads flattened into lanes**: one
page is one contiguous ``page_size x W`` slab covering every KV head. This
is the native layout of the Pallas decode kernel (``pallas_paged.py``): a
single large DMA per page (all heads at once) instead of one small DMA per
(head, page), a 128-lane-aligned padding-free TPU tiling even for head_dim
64, and no relayout copies anywhere on the hot path (per-head views are
reshapes of gathered intermediates only). A sequence's pages are
listed in its row of ``block_tables: i32[B, pages_per_seq]``; absolute token
position ``p`` lives at page ``block_tables[b, p // page_size]``, offset
``p % page_size``. Page 0 is a reserved null page: padding writes land there
and it is never allocated to a sequence.

Two implementations:

- :func:`paged_attention_reference` — pure-JAX gather formulation. Materializes
  the gathered K/V ``[B, S, n_kv, hd]`` per layer; fine for CPU CI and small
  contexts, memory-bound for long ones.
- a Pallas TPU kernel (``dynamo_tpu.ops.pallas_paged``) that streams pages
  from HBM into VMEM through an N-deep DMA ring and never materializes the
  gather. The kernel runs T = 1 decode, gappy T > 1 speculative-verify rows
  (multi-query block-diagonal form), and split-K sequence partitioning for
  low-batch long-context decode (selected automatically on TPU backends;
  see that module and ``docs/KERNELS.md``).

Reference capability being replaced: the paged-attention kernels inside vLLM /
TRT-LLM that the reference wraps (SURVEY.md §2 row 30, §7 hard part (a)).
"""

from __future__ import annotations

import functools

import jax

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(*args, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(*args, **kw)
import jax.numpy as jnp

NEG_INF = -1e30  # large-but-finite: avoids NaN from (-inf) - (-inf) in masked softmax


def gather_pages(cache: jnp.ndarray, block_tables: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """Gather per-sequence K or V: [pages, ps, W] x [B, N] -> [B, N*ps, kv, hd].

    The per-head split is a reshape of the *gathered* intermediate (layout
    chosen by XLA, fusable) — never of the cache itself.
    """
    b, n = block_tables.shape
    _, ps, w = cache.shape
    gathered = cache[block_tables.reshape(-1)]  # [B*N, ps, W]
    return gathered.reshape(b, n * ps, n_kv, w // n_kv)


def paged_attention_reference(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_pages, page_size, n_kv * head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # i32[B, pages_per_seq]
    positions: jnp.ndarray,  # i32[B, T] absolute position of each query token
    *,
    scale: float | None = None,
    sliding_window: int = 0,  # >0: keys older than q_pos - (w-1) are masked
) -> jnp.ndarray:
    """Causal paged attention; returns [B, T, n_heads, head_dim].

    Key absolute position within a sequence is its index in the gathered page
    order; causal masking is ``key_pos <= query_pos``. Padding query rows
    produce garbage that callers discard (their logits are never gathered).
    """
    b, t, n_heads, head_dim = q.shape
    n_kv = k_cache.shape[2] // head_dim
    group = n_heads // n_kv
    if scale is None:
        scale = head_dim**-0.5

    k = gather_pages(k_cache, block_tables, n_kv)  # [B, S, n_kv, hd]
    v = gather_pages(v_cache, block_tables, n_kv)
    s = k.shape[1]
    if k.dtype.itemsize < 2:  # fp8 KV cache: matmuls run in the query dtype
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)

    # GQA-native: fold query heads as [kv, group] and contract against the
    # un-repeated KV — no G-times materialization, f32 only as the einsum
    # accumulation type (no f32 copies of the gathered cache).
    qg = (q * scale).astype(q.dtype).reshape(b, t, n_kv, group, head_dim)
    logits = jnp.einsum("btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32)
    key_pos = jnp.arange(s, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= positions[:, :, None]  # [B, T, S]
    if sliding_window > 0:
        # HF window semantics: a query at p attends to keys in
        # [p - (w - 1), p] — the page pool still HOLDS older pages (parity
        # with vLLM's non-rolled paged SWA); masking alone preserves exact
        # logits. Out-of-window page reclamation is an allocator policy on
        # top, not an attention change.
        mask = mask & (key_pos[None, None, :] > positions[:, :, None] - sliding_window)
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", weights.astype(v.dtype), v, preferred_element_type=jnp.float32)
    return out.reshape(b, t, n_heads, head_dim).astype(q.dtype)


def write_kv(
    k_cache: jnp.ndarray,  # [num_pages, page_size, n_kv * head_dim]
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, T, n_kv, head_dim]
    new_v: jnp.ndarray,
    slot_mapping: jnp.ndarray,  # i32[B, T] flat slot = page_id * page_size + offset (0 for padding)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V into the paged cache; returns the updated cache arrays.

    Under jit with donated cache buffers this lowers to an in-place scatter.
    Padding tokens carry slot 0 (the null page) — harmless overlapping writes.
    Page-major layout makes this a plain row scatter: flat token slot indexes
    the leading [pages * ps] axis directly; the head flatten touches only the
    small new_k/new_v activations.
    """
    num_pages, page_size, w = k_cache.shape
    flat_shape = (num_pages * page_size, w)
    slots = slot_mapping.reshape(-1)
    nk = new_k.reshape(-1, w).astype(k_cache.dtype)  # [B*T, W]
    nv = new_v.reshape(-1, w).astype(v_cache.dtype)
    kf = k_cache.reshape(flat_shape).at[slots].set(nk)
    vf = v_cache.reshape(flat_shape).at[slots].set(nv)
    return kf.reshape(k_cache.shape), vf.reshape(v_cache.shape)


@functools.partial(jax.jit, static_argnames=("scale", "impl"))
def _dispatch(q, k_cache, v_cache, block_tables, positions, scale, impl):
    if impl == "pallas":
        from dynamo_tpu.ops.pallas_paged import paged_attention_pallas

        return paged_attention_pallas(q, k_cache, v_cache, block_tables, positions, scale=scale)
    return paged_attention_reference(q, k_cache, v_cache, block_tables, positions, scale=scale)


def default_impl() -> str:
    return "pallas" if jax.default_backend() == "tpu" else "reference"


def paged_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    scale: float | None = None,
    impl: str | None = None,
    contiguous_positions: bool = True,
    sliding_window: int = 0,
) -> jnp.ndarray:
    """Backend-dispatching paged attention (see module docstring).

    ``contiguous_positions`` declares that every real row of ``positions``
    steps by exactly 1 (engine prefill, chunked or not). Callers with gappy
    per-row positions — speculative verify, sliding window — MUST pass
    False: the T > 1 Pallas prefill kernel derives its causal mask and KV
    lengths from row start/end only and silently computes wrong attention
    on gappy layouts. False routes T > 1 to the multi-query decode kernel
    instead, whose per-row causal mask is exact for any layout (reference
    formulation only when the shape is outside kernel support — counted
    under the ``verify`` fallback phase)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl is None:
        impl = default_impl()
    if impl == "reference" or sliding_window > 0:
        if sliding_window > 0 and impl == "pallas":
            # Make the downgrade VISIBLE: an operator asking for the kernel
            # gets the reference formulation until a windowed kernel
            # variant exists (counted + one-time warned like every other
            # kernel fallback; exported at /metrics).
            from dynamo_tpu.ops.pallas_paged import _record_fallback

            _record_fallback("sliding_window", q, k_cache)
        # SWA uses the reference formulation: the Pallas kernels derive
        # causality from block walks that assume a full prefix (windowed
        # block skipping is a future kernel variant).
        return paged_attention_reference(
            q, k_cache, v_cache, block_tables, positions, scale=scale,
            sliding_window=sliding_window,
        )
    from dynamo_tpu.ops.pallas_paged import paged_attention_pallas

    return paged_attention_pallas(
        q, k_cache, v_cache, block_tables, positions, scale=scale,
        contiguous_positions=contiguous_positions,
    )


def paged_attention_sharded(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [P, page_size, n_kv * head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    mesh,
    scale: float | None = None,
    impl: str | None = None,
    contiguous_positions: bool = True,
) -> jnp.ndarray:
    """Paged attention under a device mesh: tp shards heads, dp the batch.

    GSPMD cannot partition a ``pallas_call`` — left alone it replicates the
    operands (an all-gather of the whole KV cache) and runs the full kernel
    per device. This wrapper makes the production tp layout explicit with
    ``shard_map``: each device runs the kernel on its KV-head slice of the
    cache (``W_local = n_kv/tp * head_dim`` lanes) and its dp slice of the
    batch; no collectives anywhere — heads are embarrassingly parallel in
    attention, and the GQA q-head group moves with its KV head.

    Kernel-support predicates apply to the LOCAL shapes: pick tp so
    ``(n_kv/tp) * head_dim`` stays a multiple of 128 lanes.

    Reference counterpart: vLLM's paged kernels under tensor parallelism
    (SURVEY.md §7 hard parts (a)+(b) combined).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    tp_axis = "tp" if "tp" in mesh.axis_names else None
    from jax.sharding import PartitionSpec as P

    q_spec = P(batch_axis, None, tp_axis, None)
    cache_spec = P(None, None, tp_axis)
    row_spec = P(batch_axis, None)

    def body(q, kc, vc, bt, pos):
        return paged_attention(q, kc, vc, bt, pos, scale=scale, impl=impl,
                               contiguous_positions=contiguous_positions)

    return _shard_map(
        body, mesh=mesh,
        in_specs=(q_spec, cache_spec, cache_spec, row_spec, row_spec),
        out_specs=q_spec,
        # pallas_call's out_shape carries no vma metadata; the body has no
        # cross-device communication to check anyway (heads/batch are
        # embarrassingly parallel here).
        check_vma=False,
    )(q, k_cache, v_cache, block_tables, positions)
