"""ModelManager + ModelWatcher: dynamic model discovery for the frontend.

The manager holds, per model name, the client pipeline the HTTP handlers
call: ``OpenAIPreprocessor -> Backend -> (router/client engine)``. The
watcher keeps the manager in sync with the discovery store: workers publish
their ModelDeploymentCard under ``models/{name}`` bound to their lease, so a
model appears when its first worker comes up and vanishes (lease expiry /
delete) when the last one dies.

Parity: reference ModelManager (`http/service/model_manager.rs:33`) and
ModelWatcher (`discovery/watcher.rs:69-282`), SURVEY.md §3 call stack A.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.backend import Backend
from dynamo_tpu.model_card import MODEL_PREFIX, ModelDeploymentCard
from dynamo_tpu.preprocessor import OpenAIPreprocessor
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.discovery import WatchEventType
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.tokenizer import load_tokenizer

logger = logging.getLogger(__name__)


class ClientEngine(AsyncEngine[Any, Any]):
    """Adapts a runtime endpoint Client to the AsyncEngine shape."""

    def __init__(self, client) -> None:
        self.client = client

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self.client.generate(request, context)


@dataclass
class ModelEntry:
    card: ModelDeploymentCard
    pipeline: AsyncEngine[Any, Any]
    client: Any = None  # runtime Client when discovery-built (None for local engines)
    aux: list[Any] = field(default_factory=list)  # closeables (kv subscriber, aggregator)


class ModelManager:
    def __init__(self) -> None:
        self._models: dict[str, ModelEntry] = {}

    def register(
        self, card: ModelDeploymentCard, pipeline: AsyncEngine[Any, Any], *, client: Any = None, aux: list[Any] | None = None
    ) -> None:
        self._models[card.name] = ModelEntry(card, pipeline, client, aux or [])
        logger.info("model registered: %s (%s)", card.name, card.model_type)

    async def remove(self, name: str) -> None:
        entry = self._models.pop(name, None)
        if entry is not None:
            if entry.client is not None:
                await entry.client.close()
            for a in entry.aux:
                await a.close()
        logger.info("model removed: %s", name)

    def get(self, name: str) -> ModelEntry | None:
        return self._models.get(name)

    def names(self) -> list[str]:
        return sorted(self._models)

    def cards(self) -> list[ModelDeploymentCard]:
        return [e.card for e in self._models.values()]


async def build_pipeline(
    runtime: DistributedRuntime,
    card: ModelDeploymentCard,
    *,
    router_factory: Callable[[DistributedRuntime, ModelDeploymentCard], Any] | None = None,
) -> tuple[AsyncEngine[Any, Any], Any, list[Any]]:
    """Construct the frontend-side pipeline for a discovered model.

    Returns (pipeline, client, aux_closeables). ``card.router_mode == "kv"``
    builds the KV-aware routing stack automatically; ``router_factory``
    (async, returning (engine, client, aux)) overrides for custom policies.
    """
    # Real tokenizer files take a while to parse — keep it off the event loop.
    tokenizer = await asyncio.get_running_loop().run_in_executor(None, load_tokenizer, card.tokenizer)
    engine: AsyncEngine | None = None
    client = None
    aux: list[Any] = []
    ns, comp, ep = card.endpoint
    if router_factory is not None:
        engine, client, aux = await router_factory(runtime, card)
    elif card.router_mode == "kv":
        from dynamo_tpu.router.router import build_kv_router

        engine, subscriber, aggregator = await build_kv_router(
            runtime, namespace=ns, component=comp, endpoint=ep, block_size=card.kv_page_size
        )
        client = engine.client
        aux = [subscriber, aggregator]
    if engine is None:
        mode = card.router_mode if card.router_mode in ("round_robin", "random") else "round_robin"
        client = runtime.namespace(ns).component(comp).endpoint(ep).client(router_mode=mode)
        engine = ClientEngine(client)
    backend = Backend(engine, tokenizer)
    encoder = None
    image_token_id = card.extra.get("image_token_id")
    if image_token_id is not None:
        from dynamo_tpu.encode import make_encoder

        # Vision-language model: route this model's images through the
        # encode-worker fleet (reference encode_worker handoff).
        encoder = make_encoder(runtime, ns)
    pre = OpenAIPreprocessor(
        backend,
        tokenizer,
        chat_template=card.chat_template,
        default_max_tokens=max(1, min(card.context_length // 2, 4096)),
        max_embed_tokens=max(1, min(card.context_length, 2048)),
        encoder=encoder,
        image_token_id=image_token_id,
        video_token_id=card.extra.get("video_token_id"),
    )
    return pre, client, aux


class ModelWatcher:
    """Keeps a ModelManager synchronized with the discovery store."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        manager: ModelManager,
        *,
        router_factory: Callable[[DistributedRuntime, ModelDeploymentCard], AsyncEngine | None] | None = None,
    ) -> None:
        self.runtime = runtime
        self.manager = manager
        self.router_factory = router_factory
        self._task: asyncio.Task | None = None
        # Cards are per-instance records (models/{name}/{lease}); a model is
        # removed only when its last record vanishes.
        self._card_keys: dict[str, set[str]] = {}

    async def start(self) -> "ModelWatcher":
        if self._task is None:
            # Seed from the current store state, then follow the watch.
            prefix = MODEL_PREFIX + "/"
            for key, value in (await self.runtime.store.get_prefix(prefix)).items():
                await self._on_put(key, value)
            self._task = asyncio.create_task(self._watch(), name="model-watcher")
        return self

    async def _on_put(self, key: str, value: bytes) -> None:
        card = ModelDeploymentCard.from_bytes(value)
        self._card_keys.setdefault(card.name, set()).add(key)
        if self.manager.get(card.name) is not None:
            return  # another worker instance of an already-known model
        pipeline, client, aux = await build_pipeline(self.runtime, card, router_factory=self.router_factory)
        self.manager.register(card, pipeline, client=client, aux=aux)

    async def _on_delete(self, key: str) -> None:
        name = ModelDeploymentCard.name_of_key(key)
        keys = self._card_keys.get(name)
        if keys is not None:
            keys.discard(key)
            if keys:
                return  # other workers still serve this model
            del self._card_keys[name]
        await self.manager.remove(name)

    async def _watch(self) -> None:
        prefix = MODEL_PREFIX + "/"
        try:
            async for event in self.runtime.store.watch_prefix(prefix):
                try:
                    if event.type is WatchEventType.PUT and event.value is not None:
                        await self._on_put(event.key, event.value)
                    elif event.type is WatchEventType.DELETE:
                        await self._on_delete(event.key)
                except Exception:
                    logger.exception("model watch event failed: %s", event)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("model watcher terminated")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
