"""Frontend Prometheus metrics.

Three levels, mirroring the reference (`http/service/metrics.rs:28-110`):
per-request counters/durations, streaming quality (TTFT / inter-token
latency), and size histograms (input/output sequence length). Exposed in
Prometheus text format at GET /metrics.
"""

from __future__ import annotations

import time

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram, generate_latest

from dynamo_tpu.observability.incidents import IncidentCapture
from dynamo_tpu.observability.slo import SloAccountant

_DURATION_BUCKETS = (0.005, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
_QUEUE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0)
_TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1.0, 2.0, 5.0, 10.0)
_ITL_BUCKETS = (0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.1, 0.2, 0.5, 1.0)
_LEN_BUCKETS = (16, 64, 256, 1024, 3000, 8192, 32768, 131072)


class FrontendMetrics:
    def __init__(self, registry: CollectorRegistry | None = None) -> None:
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_frontend"
        self.requests = Counter(
            f"{ns}_requests_total", "Requests received", ["model", "endpoint", "status"], registry=self.registry
        )
        self.inflight = Gauge(f"{ns}_inflight_requests", "Requests in flight", ["model"], registry=self.registry)
        self.duration = Histogram(
            f"{ns}_request_duration_seconds", "Request duration", ["model"],
            buckets=_DURATION_BUCKETS, registry=self.registry,
        )
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds", "TTFT", ["model"], buckets=_TTFT_BUCKETS, registry=self.registry
        )
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds", "ITL", ["model"], buckets=_ITL_BUCKETS, registry=self.registry
        )
        self.input_len = Histogram(
            f"{ns}_input_sequence_tokens", "Prompt tokens", ["model"], buckets=_LEN_BUCKETS, registry=self.registry
        )
        self.output_len = Histogram(
            f"{ns}_output_sequence_tokens", "Generated tokens", ["model"], buckets=_LEN_BUCKETS, registry=self.registry
        )
        self.cached_tokens = Counter(
            f"{ns}_cached_prompt_tokens_total", "Prompt tokens served from prefix cache", ["model"],
            registry=self.registry,
        )
        # Accept -> engine-dispatch gap: frontend-side time (parse, model
        # lookup, preprocessing) before the request enters the pipeline.
        self.request_queue = Histogram(
            f"{ns}_request_queue_seconds", "Accept to engine-dispatch gap", ["model"],
            buckets=_QUEUE_BUCKETS, registry=self.registry,
        )
        # Engine-side admission wait (add_request to first scheduling) —
        # distinct from request_queue, which ends when the request *enters*
        # the pipeline. This is where EDF deferral and tenant throttling
        # show up; reported once per request via the first delta's
        # admission_wait_ms.
        self.admission_wait = Histogram(
            f"{ns}_admission_wait_seconds",
            "Engine admission wait (add_request to first scheduling)", ["model"],
            buckets=_QUEUE_BUCKETS, registry=self.registry,
        )
        # Router-side staleness of each worker's last load publish (synced
        # per scrape from the KvMetricsAggregator when one is wired).
        self.worker_staleness = Gauge(
            "dynamo_router_worker_staleness_seconds",
            "Seconds since the router last saw a worker's ForwardPassMetrics publish",
            ["worker"], registry=self.registry,
        )
        # Kernel-fallback visibility: compiled paged-attention programs that
        # dropped to the ~5x-slower XLA gather formulation, by shape
        # signature (ops/pallas_paged.FALLBACK_COUNTS; synced per scrape).
        self.kernel_fallbacks = Gauge(
            "dynamo_attention_kernel_fallback_programs",
            "Compiled paged-attention programs that fell back to the XLA gather formulation",
            ["signature"], registry=self.registry,
        )
        # SLO-conditioned accounting: the north star is goodput (tokens from
        # requests that attained the latency targets), not raw throughput.
        # Source of truth is the SloAccountant; counters/gauges are synced on
        # scrape so nothing is double-booked. A burn-rate alert rising edge
        # is itself an incident-capture trigger: the frontend snapshots its
        # own bundle (SLO state + spans + config) into the incident store.
        self.incidents = IncidentCapture(worker="frontend")
        self.slo = SloAccountant(
            on_fire=lambda kind, info: self.incidents.capture("slo_burn", info)
        )
        self.output_tokens = Gauge(
            "dynamo_output_tokens_total",
            "Output tokens generated across finished requests (SLO-blind)",
            registry=self.registry,
        )
        self.goodput_tokens = Gauge(
            "dynamo_goodput_tokens_total",
            "Output tokens from finished requests that attained the SLO "
            "(TTFT and per-request p99 ITL within slo.ttft_ms / slo.itl_p99_ms)",
            registry=self.registry,
        )
        self.slo_requests = Counter(
            "dynamo_slo_requests_total",
            "Finished requests classified against the SLO targets",
            ["model", "outcome"], registry=self.registry,
        )
        self.slo_attainment = Gauge(
            "dynamo_slo_attainment_ratio",
            "Fraction of finished requests that attained the SLO (cumulative)",
            registry=self.registry,
        )
        # Multi-window burn-rate alerting over goodput attainment
        # (observability/slo.py): burn = window miss fraction / error budget.
        self.slo_burn_rate = Gauge(
            "dynamo_slo_burn_rate",
            "SLO burn rate per rolling window (window miss fraction over the "
            "error budget 1 - alert.objective; 1.0 burns the budget exactly "
            "at the sustainable rate)",
            ["window"], registry=self.registry,
        )
        self.alert_active = Gauge(
            "dynamo_alert_active",
            "Burn-rate alerts currently firing (1 while active; hysteresis "
            "clears after alert.clear_after quiet requests)",
            ["kind"], registry=self.registry,
        )
        self.alert_fired = Gauge(
            "dynamo_alert_fired_total",
            "Burn-rate alert rising edges since frontend start",
            ["kind"], registry=self.registry,
        )
        # Federation visibility: worker telemetry scrapes that failed (the
        # federated /metrics otherwise degrades silently to the frontend
        # registry alone). Synced per scrape from the telemetry client.
        self.federation_failures = Gauge(
            "dynamo_federation_scrape_failures_total",
            "Failed worker telemetry fan-out calls per worker (metrics "
            "scrapes and debug queries that timed out or errored)",
            ["worker"], registry=self.registry,
        )
        # Client-plane health: watch-loop restarts/staleness and per-instance
        # circuit-breaker state, synced per scrape from every live runtime
        # client in this process (runtime/client.py snapshots).
        self.client_watch_restarts = Gauge(
            "dynamo_client_watch_restarts_total",
            "Instance-watch reconnects per endpoint (a restart means the discovery watch died and was resubscribed)",
            ["endpoint"], registry=self.registry,
        )
        self.client_watch_staleness = Gauge(
            "dynamo_client_watch_staleness_seconds",
            "Seconds the endpoint's instance watch has been down (0 while healthy)",
            ["endpoint"], registry=self.registry,
        )
        self.client_breaker_state = Gauge(
            "dynamo_client_breaker_state",
            "Per-instance circuit breaker state (0 closed / 1 half-open / 2 open)",
            ["endpoint", "instance"], registry=self.registry,
        )
        # HA control plane: role/epoch/lag of the store replica hosted in
        # this process (if any) plus the client-side failover view — synced
        # per scrape from runtime/replication.py and runtime/store_server.py.
        self.store_role = Gauge(
            "dynamo_store_role",
            "Store replica role hosted or observed by this process (1 for the active role label)",
            ["role"], registry=self.registry,
        )
        self.store_epoch = Gauge(
            "dynamo_store_epoch",
            "Leadership epoch of the store cluster as seen by this process",
            registry=self.registry,
        )
        self.store_replication_lag = Gauge(
            "dynamo_store_replication_lag_seconds",
            "Wall-clock age of the last replicated record applied by the local follower (0 on a leader)",
            registry=self.registry,
        )
        self.store_failovers = Gauge(
            "dynamo_store_failovers_total",
            "Store leadership changes this process has observed",
            registry=self.registry,
        )
        self.store_client_retries = Gauge(
            "dynamo_store_client_op_retries_total",
            "Idempotent store ops transparently replayed after a connection loss",
            registry=self.registry,
        )
        self.router_index_resyncs = Gauge(
            "dynamo_router_index_resyncs_total",
            "KV-index reconstructions (snapshot rebases + gap-forced resyncs) since frontend start",
            registry=self.registry,
        )
        # Streaming P^2 quantiles — no fixed-bucket distortion at the 500 ms
        # target the way a histogram boundary would introduce.
        self.ttft_quantile = Gauge(
            "dynamo_frontend_ttft_quantile_seconds",
            "Streaming TTFT quantile estimate (P^2, deployment-wide)",
            ["quantile"], registry=self.registry,
        )
        self.itl_quantile = Gauge(
            "dynamo_frontend_itl_quantile_seconds",
            "Streaming inter-token-latency quantile estimate (P^2, deployment-wide)",
            ["quantile"], registry=self.registry,
        )

    def render(self) -> bytes:
        from dynamo_tpu.ops.pallas_paged import fallback_snapshot
        from dynamo_tpu.router.events import router_resync_snapshot
        from dynamo_tpu.runtime.client import breaker_snapshot, watch_snapshot
        from dynamo_tpu.runtime.replication import replica_snapshot
        from dynamo_tpu.runtime.store_server import store_client_snapshot

        # Drop label sets from a previous scrape first: a signature that
        # left the snapshot (fallback cache reset) must not keep exporting
        # its last value forever.
        self.kernel_fallbacks.clear()
        for sig, n in fallback_snapshot().items():
            self.kernel_fallbacks.labels(sig).set(n)
        self.client_watch_restarts.clear()
        self.client_watch_staleness.clear()
        self.client_breaker_state.clear()
        for path, view in watch_snapshot().items():
            self.client_watch_restarts.labels(path).set(view["restarts"])
            self.client_watch_staleness.labels(path).set(view["staleness"])
        for (path, instance), state in breaker_snapshot().items():
            self.client_breaker_state.labels(path, instance).set(state)
        self.output_tokens.set(self.slo.output_tokens_total)
        self.goodput_tokens.set(self.slo.goodput_tokens_total)
        self.slo_attainment.set(self.slo.attainment())
        for window, burn in self.slo.burn_rates().items():
            self.slo_burn_rate.labels(window).set(burn)
        self.alert_active.clear()
        for kind in self.slo.alerts_active:
            self.alert_active.labels(kind).set(1)
        self.alert_fired.clear()
        for kind, n in self.slo.alerts_fired.items():
            self.alert_fired.labels(kind).set(n)
        for q, v in self.slo.ttft.snapshot().items():
            self.ttft_quantile.labels(f"p{int(q * 100)}").set(v)
        for q, v in self.slo.itl.snapshot().items():
            self.itl_quantile.labels(f"p{int(q * 100)}").set(v)
        # HA view: an in-process replica coordinator is authoritative; a pure
        # client process (the usual frontend) reports what its StoreClient
        # learned from who_leads.
        replica = replica_snapshot()
        client = store_client_snapshot()
        self.store_role.clear()
        if replica is not None:
            self.store_role.labels(replica["role"]).set(1)
            self.store_epoch.set(replica["epoch"])
            self.store_replication_lag.set(replica["lag_s"])
            self.store_failovers.set(replica["failovers"])
        else:
            self.store_role.labels(client["role"]).set(1)
            self.store_epoch.set(client["epoch"])
            self.store_replication_lag.set(0.0)
            self.store_failovers.set(client["failovers"])
        self.store_client_retries.set(client["retries"])
        self.router_index_resyncs.set(router_resync_snapshot()["resyncs"])
        return generate_latest(self.registry)

    def sync_federation(self, failures: dict[str, int]) -> None:
        """Refresh the per-worker scrape-failure gauge from the telemetry
        client's counters (clears first so departed workers drop out)."""
        self.federation_failures.clear()
        for worker, n in failures.items():
            self.federation_failures.labels(worker).set(n)

    def sync_staleness(self, staleness: dict[int, float]) -> None:
        """Refresh the per-worker staleness gauge from an aggregator view
        (clears first so departed workers drop their label sets)."""
        self.worker_staleness.clear()
        for wid, age in staleness.items():
            self.worker_staleness.labels(f"{wid:x}").set(age)

    def tracker(self, model: str, endpoint: str) -> "RequestTracker":
        return RequestTracker(self, model, endpoint)


class RequestTracker:
    """Per-request context manager: times the request + token stream gaps."""

    def __init__(self, metrics: FrontendMetrics, model: str, endpoint: str) -> None:
        self.m = metrics
        self.model = model
        self.endpoint = endpoint
        self._start = 0.0
        self._last_token: float | None = None
        self._dispatched = False
        self.status = "success"
        # Per-request latency profile for SLO classification at __exit__:
        # attainment needs this request's own TTFT and ITL-gap tail, not the
        # deployment aggregates.
        self._ttft: float | None = None
        self._gaps: list[float] = []
        self._tokens = 0
        self._admission_reported = False

    def __enter__(self) -> "RequestTracker":
        self._start = time.monotonic()
        self.m.inflight.labels(self.model).inc()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "error"
        self.m.inflight.labels(self.model).dec()
        self.m.requests.labels(self.model, self.endpoint, self.status).inc()
        self.m.duration.labels(self.model).observe(time.monotonic() - self._start)
        if self._ttft is not None:  # token-producing request: classify vs SLO
            verdict = self.m.slo.account(
                ttft_s=self._ttft,
                itl_gaps=self._gaps,
                output_tokens=self._tokens,
                ok=self.status == "success",
            )
            met = verdict.met and self.status == "success"
            self.m.slo_requests.labels(self.model, "met" if met else "missed").inc()

    def on_dispatch(self) -> None:
        """The request is leaving the frontend for the engine pipeline."""
        if not self._dispatched:
            self._dispatched = True
            self.m.request_queue.labels(self.model).observe(time.monotonic() - self._start)

    def on_admission_wait(self, seconds: float) -> None:
        """Engine admission wait from the first delta (once per request)."""
        if not self._admission_reported:
            self._admission_reported = True
            self.m.admission_wait.labels(self.model).observe(max(0.0, seconds))

    def on_token(self) -> None:
        now = time.monotonic()
        if self._last_token is None:
            self._ttft = now - self._start
            self.m.ttft.labels(self.model).observe(self._ttft)
            self.m.slo.observe_ttft(self._ttft)
        else:
            gap = now - self._last_token
            self.m.itl.labels(self.model).observe(gap)
            self.m.slo.observe_itl(gap)
            self._gaps.append(gap)
        self._last_token = now

    def on_usage(self, prompt_tokens: int | None, output_tokens: int, cached_tokens: int | None) -> None:
        if prompt_tokens:
            self.m.input_len.labels(self.model).observe(prompt_tokens)
        self.m.output_len.labels(self.model).observe(output_tokens)
        self._tokens = max(self._tokens, int(output_tokens or 0))
        if cached_tokens:
            self.m.cached_tokens.labels(self.model).inc(cached_tokens)
