"""Frontend Prometheus metrics.

Three levels, mirroring the reference (`http/service/metrics.rs:28-110`):
per-request counters/durations, streaming quality (TTFT / inter-token
latency), and size histograms (input/output sequence length). Exposed in
Prometheus text format at GET /metrics.
"""

from __future__ import annotations

import time

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram, generate_latest

_DURATION_BUCKETS = (0.005, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
_QUEUE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0)
_TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1.0, 2.0, 5.0, 10.0)
_ITL_BUCKETS = (0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.1, 0.2, 0.5, 1.0)
_LEN_BUCKETS = (16, 64, 256, 1024, 3000, 8192, 32768, 131072)


class FrontendMetrics:
    def __init__(self, registry: CollectorRegistry | None = None) -> None:
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_frontend"
        self.requests = Counter(
            f"{ns}_requests_total", "Requests received", ["model", "endpoint", "status"], registry=self.registry
        )
        self.inflight = Gauge(f"{ns}_inflight_requests", "Requests in flight", ["model"], registry=self.registry)
        self.duration = Histogram(
            f"{ns}_request_duration_seconds", "Request duration", ["model"],
            buckets=_DURATION_BUCKETS, registry=self.registry,
        )
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds", "TTFT", ["model"], buckets=_TTFT_BUCKETS, registry=self.registry
        )
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds", "ITL", ["model"], buckets=_ITL_BUCKETS, registry=self.registry
        )
        self.input_len = Histogram(
            f"{ns}_input_sequence_tokens", "Prompt tokens", ["model"], buckets=_LEN_BUCKETS, registry=self.registry
        )
        self.output_len = Histogram(
            f"{ns}_output_sequence_tokens", "Generated tokens", ["model"], buckets=_LEN_BUCKETS, registry=self.registry
        )
        self.cached_tokens = Counter(
            f"{ns}_cached_prompt_tokens_total", "Prompt tokens served from prefix cache", ["model"],
            registry=self.registry,
        )
        # Accept -> engine-dispatch gap: frontend-side time (parse, model
        # lookup, preprocessing) before the request enters the pipeline.
        self.request_queue = Histogram(
            f"{ns}_request_queue_seconds", "Accept to engine-dispatch gap", ["model"],
            buckets=_QUEUE_BUCKETS, registry=self.registry,
        )
        # Router-side staleness of each worker's last load publish (synced
        # per scrape from the KvMetricsAggregator when one is wired).
        self.worker_staleness = Gauge(
            "dynamo_router_worker_staleness_seconds",
            "Seconds since the router last saw a worker's ForwardPassMetrics publish",
            ["worker"], registry=self.registry,
        )
        # Kernel-fallback visibility: compiled paged-attention programs that
        # dropped to the ~5x-slower XLA gather formulation, by shape
        # signature (ops/pallas_paged.FALLBACK_COUNTS; synced per scrape).
        self.kernel_fallbacks = Gauge(
            "dynamo_attention_kernel_fallback_programs",
            "Compiled paged-attention programs that fell back to the XLA gather formulation",
            ["signature"], registry=self.registry,
        )

    def render(self) -> bytes:
        from dynamo_tpu.ops.pallas_paged import fallback_snapshot

        # Drop label sets from a previous scrape first: a signature that
        # left the snapshot (fallback cache reset) must not keep exporting
        # its last value forever.
        self.kernel_fallbacks.clear()
        for sig, n in fallback_snapshot().items():
            self.kernel_fallbacks.labels(sig).set(n)
        return generate_latest(self.registry)

    def sync_staleness(self, staleness: dict[int, float]) -> None:
        """Refresh the per-worker staleness gauge from an aggregator view
        (clears first so departed workers drop their label sets)."""
        self.worker_staleness.clear()
        for wid, age in staleness.items():
            self.worker_staleness.labels(f"{wid:x}").set(age)

    def tracker(self, model: str, endpoint: str) -> "RequestTracker":
        return RequestTracker(self, model, endpoint)


class RequestTracker:
    """Per-request context manager: times the request + token stream gaps."""

    def __init__(self, metrics: FrontendMetrics, model: str, endpoint: str) -> None:
        self.m = metrics
        self.model = model
        self.endpoint = endpoint
        self._start = 0.0
        self._last_token: float | None = None
        self._dispatched = False
        self.status = "success"

    def __enter__(self) -> "RequestTracker":
        self._start = time.monotonic()
        self.m.inflight.labels(self.model).inc()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "error"
        self.m.inflight.labels(self.model).dec()
        self.m.requests.labels(self.model, self.endpoint, self.status).inc()
        self.m.duration.labels(self.model).observe(time.monotonic() - self._start)

    def on_dispatch(self) -> None:
        """The request is leaving the frontend for the engine pipeline."""
        if not self._dispatched:
            self._dispatched = True
            self.m.request_queue.labels(self.model).observe(time.monotonic() - self._start)

    def on_token(self) -> None:
        now = time.monotonic()
        if self._last_token is None:
            self.m.ttft.labels(self.model).observe(now - self._start)
        else:
            self.m.itl.labels(self.model).observe(now - self._last_token)
        self._last_token = now

    def on_usage(self, prompt_tokens: int | None, output_tokens: int, cached_tokens: int | None) -> None:
        if prompt_tokens:
            self.m.input_len.labels(self.model).observe(prompt_tokens)
        self.m.output_len.labels(self.model).observe(output_tokens)
        if cached_tokens:
            self.m.cached_tokens.labels(self.model).inc(cached_tokens)
