"""OpenAI-compatible HTTP frontend.

- :mod:`dynamo_tpu.frontend.openai_format` — chat/completions response +
  SSE chunk construction and stream aggregation.
- :mod:`dynamo_tpu.frontend.model_manager` — per-model engine registry and
  the discovery watcher that builds serving pipelines as workers appear.
- :mod:`dynamo_tpu.frontend.metrics` — Prometheus request metrics
  (count/duration/TTFT/ITL/inflight, token histograms).
- :mod:`dynamo_tpu.frontend.http` — the aiohttp service:
  /v1/chat/completions, /v1/completions, /v1/models, /health, /live,
  /metrics, /clear_kv_blocks.

Parity: reference `lib/llm/src/http/service/*` (axum) + ModelManager/
ModelWatcher (`discovery/watcher.rs`), SURVEY.md §2 rows 17-18.
"""

from dynamo_tpu.frontend.http import HttpService

__all__ = ["HttpService"]
