"""OpenAI response construction: SSE chunks + non-streaming aggregation.

Parity: reference `protocols/openai/chat_completions/delta.rs` (delta
generator) and `protocols/openai/*/aggregator.rs` (stream -> full response),
plus the SSE codec (`protocols/codec.rs`).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import BackendOutput, FinishReason

_FINISH_MAP = {
    FinishReason.STOP: "stop",
    FinishReason.LENGTH: "length",
    FinishReason.CANCELLED: "stop",
    FinishReason.ERROR: "error",
}


def _finish_str(reason: FinishReason | None) -> str | None:
    return _FINISH_MAP.get(reason) if reason else None


def new_request_id(kind: str) -> str:
    return f"{kind}-{uuid.uuid4().hex}"


def _usage(prompt_tokens: int | None, completion_tokens: int, cached_tokens: int | None) -> dict[str, Any]:
    usage: dict[str, Any] = {
        "prompt_tokens": prompt_tokens or 0,
        "completion_tokens": completion_tokens,
        "total_tokens": (prompt_tokens or 0) + completion_tokens,
    }
    if cached_tokens:
        usage["prompt_tokens_details"] = {"cached_tokens": cached_tokens}
    return usage




def _legacy_top_logprobs(entries: list[dict]) -> list[dict[str, float]]:
    """BackendOutput.logprobs -> legacy completions ``top_logprobs``: one
    ``{token_text: logprob}`` dict per position. Distinct token ids can
    decode to the SAME text (partial-UTF-8 pieces all render as U+FFFD), and
    a plain dict comprehension silently drops all but one — keep the best
    logprob under the plain text and suffix the rest with their token id, so
    every one of the N requested alternatives survives."""
    out: list[dict[str, float]] = []
    for e in entries:
        d: dict[str, float] = {}
        for t in sorted(e.get("top", []), key=lambda t: t[1], reverse=True):
            key = t[2] if len(t) > 2 else str(t[0])
            while key in d:
                key = f"{key}#{t[0]}"
            d[key] = t[1]
        out.append(d)
    return out


def _chat_lp_content(entries: list[dict]) -> list[dict[str, Any]]:
    """BackendOutput.logprobs entries -> OpenAI chat `logprobs.content`."""
    out = []
    for e in entries:
        out.append({
            "token": e.get("token", ""),
            "logprob": e["logprob"],
            "bytes": e.get("bytes"),
            "top_logprobs": [
                {"token": t[2] if len(t) > 2 else "", "logprob": t[1],
                 "bytes": list(str(t[2]).encode()) if len(t) > 2 else None}
                for t in e.get("top", [])
            ],
        })
    return out


class ChatStream:
    """Builds chat.completion.chunk objects from BackendOutput deltas."""

    def __init__(self, model: str, *, request_id: str | None = None, send_usage: bool = False) -> None:
        self.id = request_id or new_request_id("chatcmpl")
        self.model = model
        self.created = int(time.time())
        self.send_usage = send_usage

    def _chunk(self, delta: dict[str, Any], finish: str | None = None, usage: dict | None = None) -> dict[str, Any]:
        out = {
            "id": self.id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [{"index": 0, "delta": delta, "finish_reason": finish}],
        }
        if usage is not None:
            out["usage"] = usage
        return out

    def first(self) -> dict[str, Any]:
        return self._chunk({"role": "assistant", "content": ""})

    def delta(self, out: BackendOutput) -> dict[str, Any]:
        usage = None
        if out.finish_reason is not None and self.send_usage:
            usage = _usage(out.prompt_tokens, out.cumulative_tokens, out.cached_tokens)
        chunk = self._chunk(
            {"content": out.text} if out.text else {},
            finish=_finish_str(out.finish_reason),
            usage=usage,
        )
        if out.logprobs:
            chunk["choices"][0]["logprobs"] = {"content": _chat_lp_content(out.logprobs)}
        return chunk

    def text_chunk(self, text: str) -> dict[str, Any]:
        return self._chunk({"content": text})

    def tool_calls_final(self, calls: list[dict[str, Any]], out: BackendOutput) -> dict[str, Any]:
        """Terminal chunk carrying the parsed tool calls (streaming shape:
        each call gets a list index) with finish_reason "tool_calls"."""
        usage = None
        if self.send_usage:
            usage = _usage(out.prompt_tokens, out.cumulative_tokens, out.cached_tokens)
        deltas = [
            {"index": i, "id": c["id"], "type": c["type"], "function": c["function"]}
            for i, c in enumerate(calls)
        ]
        return self._chunk({"tool_calls": deltas}, finish="tool_calls", usage=usage)


class CompletionStream:
    """Builds text_completion chunks from BackendOutput deltas."""

    def __init__(self, model: str, *, request_id: str | None = None, send_usage: bool = False) -> None:
        self.id = request_id or new_request_id("cmpl")
        self.model = model
        self.created = int(time.time())
        self.send_usage = send_usage

    def delta(self, out: BackendOutput) -> dict[str, Any]:
        chunk: dict[str, Any] = {
            "id": self.id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "choices": [
                {"index": 0, "text": out.text, "finish_reason": _finish_str(out.finish_reason),
                 "logprobs": None if not out.logprobs else {
                     "tokens": [e.get("token", "") for e in out.logprobs],
                     "token_logprobs": [e["logprob"] for e in out.logprobs],
                     "top_logprobs": _legacy_top_logprobs(out.logprobs),
                 }}
            ],
        }
        if out.finish_reason is not None and self.send_usage:
            chunk["usage"] = _usage(out.prompt_tokens, out.cumulative_tokens, out.cached_tokens)
        return chunk


async def aggregate_chat(
    model: str, stream: AsyncIterator[BackendOutput], *, parse_tools: bool = False
) -> dict[str, Any]:
    """Drain a backend stream into a full chat.completion response.

    ``parse_tools`` (set when the request declared ``tools``) lifts emitted
    tool-call blocks into ``message.tool_calls`` / ``finish_reason:
    "tool_calls"`` (see `frontend/tool_calls.py`)."""
    text_parts: list[str] = []
    finish: FinishReason | None = None
    prompt_tokens = cached = None
    completion_tokens = 0
    lp_entries: list[dict] = []
    async for out in stream:
        text_parts.append(out.text)
        completion_tokens = max(completion_tokens, out.cumulative_tokens)
        if out.logprobs:
            lp_entries.extend(out.logprobs)
        if out.finish_reason is not None:
            finish = out.finish_reason
            prompt_tokens, cached = out.prompt_tokens, out.cached_tokens
    text = "".join(text_parts)
    message: dict[str, Any] = {"role": "assistant", "content": text}
    finish_str = _finish_str(finish) or "stop"
    if parse_tools:
        from dynamo_tpu.frontend.tool_calls import parse_tool_calls

        content, calls = parse_tool_calls(text)
        if calls:
            message = {"role": "assistant", "content": content or None, "tool_calls": calls}
            finish_str = "tool_calls"
    return {
        "id": new_request_id("chatcmpl"),
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": message,
                "finish_reason": finish_str,
                **({"logprobs": {"content": _chat_lp_content(lp_entries)}} if lp_entries else {}),
            }
        ],
        "usage": _usage(prompt_tokens, completion_tokens, cached),
    }


async def aggregate_completion(model: str, stream: AsyncIterator[BackendOutput]) -> dict[str, Any]:
    text_parts: list[str] = []
    finish: FinishReason | None = None
    prompt_tokens = cached = None
    completion_tokens = 0
    lp_entries: list[dict] = []
    async for out in stream:
        text_parts.append(out.text)
        completion_tokens = max(completion_tokens, out.cumulative_tokens)
        if out.logprobs:
            lp_entries.extend(out.logprobs)
        if out.finish_reason is not None:
            finish = out.finish_reason
            prompt_tokens, cached = out.prompt_tokens, out.cached_tokens
    return {
        "id": new_request_id("cmpl"),
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": 0, "text": "".join(text_parts), "finish_reason": _finish_str(finish) or "stop",
             "logprobs": None if not lp_entries else {
                 "tokens": [e.get("token", "") for e in lp_entries],
                 "token_logprobs": [e["logprob"] for e in lp_entries],
                 "top_logprobs": _legacy_top_logprobs(lp_entries),
             }}
        ],
        "usage": _usage(prompt_tokens, completion_tokens, cached),
    }


def sse_encode(obj: dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
