"""Tool-call extraction from generated text.

OpenAI tool calling: the model emits a structured function invocation inside
its text; the frontend lifts it into ``message.tool_calls`` with
``finish_reason: "tool_calls"``. Two wire formats cover the shipped model
families:

- Hermes/Qwen style: ``<tool_call>{"name": ..., "arguments": {...}}</tool_call>``
  (possibly several blocks).
- Llama-3 JSON style: the entire message is one JSON object
  ``{"name": ..., "parameters": {...}}``.

Parity: reference `lib/llm/src/preprocessor/tools/*` (request-side tool
schema injection) and its response parsers; parsing is frontend-side here
because the backend stage is detokenize-only by design.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any

_TOOL_CALL_RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)


def _mk_call(name: str, arguments: Any) -> dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def parse_tool_calls(text: str) -> tuple[str, list[dict[str, Any]]]:
    """Split generated text into (content, tool_calls).

    Returns the text with tool-call blocks removed and the parsed calls in
    OpenAI response shape. Unparseable blocks stay in the content untouched
    (the caller falls back to a plain text message).
    """
    calls: list[dict[str, Any]] = []

    def lift(m: re.Match) -> str:
        try:
            obj = json.loads(m.group(1))
            name = obj["name"]
        except Exception:
            return m.group(0)  # malformed: leave in content
        calls.append(_mk_call(name, obj.get("arguments", obj.get("parameters", {}))))
        return ""

    content = _TOOL_CALL_RE.sub(lift, text)
    if not calls:
        # Llama-3 style: the whole message is one JSON function call.
        stripped = text.strip()
        if stripped.startswith("{") and stripped.endswith("}"):
            try:
                obj = json.loads(stripped)
                if isinstance(obj, dict) and "name" in obj and ("parameters" in obj or "arguments" in obj):
                    calls.append(_mk_call(obj["name"], obj.get("arguments", obj.get("parameters", {}))))
                    content = ""
            except Exception:
                pass
    return content.strip() if calls else text, calls


class ToolCallStreamJail:
    """Streaming guard: holds back text that may be tool-call markup.

    ``push(delta_text)`` returns the prefix that is provably plain content;
    anything that could open a ``<tool_call>`` block — or a message whose
    first character is ``{`` (the bare-JSON call style) — is buffered.
    ``finish()`` parses the held text and returns ``(trailing_text, calls)``.

    Mirrors the backend's StopStringJail pattern so streaming clients with
    ``tools`` declared receive ``tool_calls`` deltas instead of raw markup.
    """

    MARKER = "<tool_call>"

    def __init__(self) -> None:
        self._buf = ""
        self._holding_all = False  # saw a call opener: buffer to end of stream
        self._seen_content = False

    def push(self, text: str) -> str:
        self._buf += text
        if self._holding_all:
            return ""
        s = self._buf
        if not self._seen_content:
            stripped = s.lstrip()
            if not stripped:
                return ""
            self._seen_content = True
            if stripped.startswith("{"):
                self._holding_all = True  # possible bare-JSON call
                return ""
        i = s.find(self.MARKER)
        if i != -1:
            self._holding_all = True
            out, self._buf = s[:i], s[i:]
            return out
        # Release all but a tail that is a proper prefix of the marker.
        keep = 0
        for n in range(min(len(self.MARKER) - 1, len(s)), 0, -1):
            if self.MARKER.startswith(s[-n:]):
                keep = n
                break
        out, self._buf = s[: len(s) - keep], s[len(s) - keep :]
        return out

    def finish(self) -> tuple[str, list[dict[str, Any]]]:
        content, calls = parse_tool_calls(self._buf)
        self._buf = ""
        return content, calls
