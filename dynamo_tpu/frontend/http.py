"""The OpenAI-compatible aiohttp service.

Routes (parity: reference `http/service/openai.rs`, `health.rs`,
`metrics.rs`, `clear_kv_blocks.rs`):

- POST /v1/chat/completions — streaming (SSE) and aggregated
- POST /v1/completions
- GET  /v1/models
- GET  /health, /live
- GET  /metrics — Prometheus text (frontend registry + federated worker
  EngineMetrics registries, when a telemetry client is wired)
- GET  /debug/traces/{request_id} — the assembled distributed timeline for
  one request (local spans + fan-out to every worker's span ring)
- POST /clear_kv_blocks — admin: drop prefix caches on all workers

Distributed tracing starts here: an incoming W3C ``traceparent`` header is
ingested (or a fresh trace minted), a root ``http_request`` span wraps the
request, and its context rides the per-request ``Context`` through every
pipeline stage and process hop.

Client disconnects cancel generation: the per-request Context is killed when
the response write fails or the request is torn down, and that propagates
through the pipeline to the engine scheduler.
"""

from __future__ import annotations

import asyncio
import logging
import sys
from typing import Any, AsyncIterator, Awaitable, Callable

from aiohttp import web

from dynamo_tpu.frontend.metrics import FrontendMetrics
from dynamo_tpu.frontend.model_manager import ModelManager
from dynamo_tpu.frontend.openai_format import (
    SSE_DONE,
    ChatStream,
    CompletionStream,
    aggregate_chat,
    aggregate_completion,
    sse_encode,
)
from dynamo_tpu.protocols.common import BackendOutput, FinishReason
from dynamo_tpu.runtime.engine import Context

logger = logging.getLogger(__name__)


def _error(status: int, message: str, etype: str = "invalid_request_error") -> web.Response:
    return web.json_response({"error": {"message": message, "type": etype}}, status=status)


#: The structured SSE event a client sees when the engine dies mid-stream —
#: OpenAI error shape, no traceback, followed by [DONE] and a clean close.
_ENGINE_ERROR_EVENT = {
    "error": {
        "message": "the engine failed while generating this response",
        "type": "engine_error",
        "code": "mid_stream_failure",
    }
}


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        *,
        metrics: FrontendMetrics | None = None,
        clear_kv_hook: Callable[[], Awaitable[int]] | None = None,
        telemetry: Any = None,
    ) -> None:
        self.manager = manager
        self.metrics = metrics or FrontendMetrics()
        self.clear_kv_hook = clear_kv_hook
        # WorkerTelemetryClient (observability/service.py): fans /metrics and
        # /debug/traces queries out to every live worker. None on frontends
        # with no runtime wired (unit tests) — both routes degrade to
        # frontend-local data.
        self.telemetry = telemetry
        self._runner: web.AppRunner | None = None
        self.app = web.Application()
        self.app.add_routes(
            [
                web.post("/v1/chat/completions", self.chat_completions),
                web.post("/v1/completions", self.completions),
                web.post("/v1/embeddings", self.embeddings),
                web.get("/v1/models", self.list_models),
                web.get("/health", self.health),
                web.get("/live", self.live),
                web.get("/metrics", self.prometheus),
                web.get("/debug/traces/{request_id}", self.debug_traces),
                web.get("/debug/explain/{request_id}", self.debug_explain),
                web.get("/debug/flight/{worker}", self.debug_flight),
                web.get("/debug/cost", self.debug_cost),
                web.get("/debug/profile/{worker}", self.debug_profile_status),
                web.post("/debug/profile/{worker}", self.debug_profile_capture),
                web.get("/debug/incidents", self.debug_incidents),
                web.get("/debug/incidents/{incident_id}", self.debug_incident),
                web.get("/debug/federation", self.debug_federation),
                web.get("/debug/store", self.debug_store),
                web.post("/clear_kv_blocks", self.clear_kv_blocks),
                web.post("/engine/profile", self.engine_profile),
            ]
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "0.0.0.0", port: int = 8080) -> int:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual = self._runner.addresses[0][1] if self._runner.addresses else port
        logger.info("HTTP frontend listening on %s:%d", host, actual)
        return actual

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- OpenAI endpoints --------------------------------------------------

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_openai(request, kind="chat")

    async def completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_openai(request, kind="completions")

    async def embeddings(self, request: web.Request) -> web.Response:
        """OpenAI /v1/embeddings: input = str | [str] | [int] | [[int]].

        Parity: `lib/llm/src/http/service/openai.rs:580`. Each input runs
        through the same preprocessor -> router -> worker pipeline as chat
        (annotated ``embed``); the worker answers with one vector.
        """
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str):
            return _error(400, "missing 'model'")
        entry = self.manager.get(model)
        if entry is None:
            return _error(404, f"model '{model}' not found", "model_not_found")
        raw = body.get("input")
        if isinstance(raw, str):
            inputs: list = [raw]
        elif isinstance(raw, list) and raw and all(isinstance(t, int) for t in raw):
            inputs = [raw]  # single pre-tokenized input
        elif isinstance(raw, list) and raw:
            inputs = raw
        else:
            return _error(400, "missing or empty 'input'")

        async def run_batch() -> tuple[list[list[float]], int]:
            # One pipeline request carries the whole input batch: the worker
            # encodes all rows in a single device dispatch (runner.embed).
            req_body = {"model": model, "prompt": inputs[0], "embed": True,
                        "embed_batch": inputs[1:]}
            vecs: list[list[float]] = []
            tokens = 0
            async for out in entry.pipeline.generate(req_body, Context()):
                out = out if isinstance(out, BackendOutput) else BackendOutput.from_dict(out)
                if out.embedding is not None:
                    vecs.append(out.embedding)
                    tokens += out.prompt_tokens or 0
                if out.finish_reason is not None:
                    break
            if len(vecs) != len(inputs):
                raise RuntimeError(f"worker returned {len(vecs)}/{len(inputs)} embeddings")
            return vecs, tokens

        with self.metrics.tracker(model, "embeddings") as tracker:
            try:
                vecs, total = await run_batch()
            except ValueError as exc:
                tracker.status = "invalid"
                return _error(400, str(exc))
            except Exception:
                logger.exception("embeddings failed (model=%s)", model)
                return _error(500, "internal error", "internal_error")
        return web.json_response(
            {
                "object": "list",
                "model": model,
                "data": [
                    {"object": "embedding", "index": i, "embedding": vec}
                    for i, vec in enumerate(vecs)
                ],
                "usage": {"prompt_tokens": total, "total_tokens": total},
            }
        )

    async def _serve_openai(self, request: web.Request, *, kind: str) -> web.StreamResponse:
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str):
            return _error(400, "missing 'model'")
        if kind == "chat" and not isinstance(body.get("messages"), list):
            return _error(400, "missing 'messages'")
        if kind == "completions" and "prompt" not in body:
            return _error(400, "missing 'prompt'")
        entry = self.manager.get(model)
        if (
            entry is None
            or (kind == "chat" and not entry.card.supports_chat)
            or (kind == "completions" and not entry.card.supports_completions)
        ):
            return _error(404, f"model '{model}' not found", "model_not_found")
        stream_mode = bool(body.get("stream", False))
        # OpenAI default: usage only when explicitly requested via stream_options.
        send_usage = bool((body.get("stream_options") or {}).get("include_usage", False))
        # Multi-tenant admission (dynamo_tpu/sched): tenant identity rides a
        # header (an API gateway stamps it; clients can't be trusted to);
        # priority is a plain body field. The preprocessor carries both into
        # PreprocessedRequest.
        tenant = request.headers.get("x-dynamo-tenant")
        if tenant:
            body["tenant_id"] = tenant
        else:
            # No gateway header: drop any client-supplied identity so a
            # client can't impersonate another tenant's quota (or hop to an
            # unconfigured tenant to dodge its own throttling).
            body.pop("tenant_id", None)
        ctx = Context(request_id=body.get("request_id"))
        # Trace ingress: continue the caller's W3C trace or mint a fresh one.
        # The root span's context rides ctx.trace through every pipeline
        # stage and process hop (GET /debug/traces/{ctx.id} reassembles it).
        from dynamo_tpu.tracing import Span, TraceContext

        incoming = TraceContext.from_traceparent(request.headers.get("traceparent"))
        root = Span("http_request", trace=incoming, request_id=ctx.id, model=model, endpoint=kind)
        ctx.trace = root.context.to_dict()
        root.__enter__()

        try:
            with self.metrics.tracker(model, kind) as tracker:
                try:
                    backend_stream = self._backend_stream(entry.pipeline, body, ctx, tracker)
                    if stream_mode:
                        return await self._stream_response(
                            request, model, kind, ctx, backend_stream, send_usage,
                            parse_tools=kind == "chat" and bool(body.get("tools")),
                            tracker=tracker,
                        )
                    if kind == "chat":
                        payload = await aggregate_chat(
                            model, backend_stream, parse_tools=bool(body.get("tools"))
                        )
                    else:
                        payload = await aggregate_completion(model, backend_stream)
                    choices = payload.get("choices") or []
                    if choices and choices[0].get("finish_reason") == "error":
                        # Engine died under the aggregation: headers aren't
                        # out yet, so a real HTTP error is still possible.
                        tracker.status = "error"
                        return _error(
                            502, "the engine failed while generating this response", "engine_error"
                        )
                    return web.json_response(
                        payload, headers={"x-dynamo-trace-id": root.trace_id}
                    )
                except asyncio.CancelledError:
                    ctx.kill()
                    raise
                except ValueError as exc:  # request-shape errors from the preprocessor
                    tracker.status = "invalid"
                    ctx.kill()
                    return _error(400, str(exc))
                except Exception:
                    logger.exception("request failed (model=%s)", model)
                    ctx.kill()
                    return _error(500, "internal error", "internal_error")
        finally:
            root.__exit__(*sys.exc_info())

    async def _backend_stream(self, pipeline, body, ctx: Context, tracker) -> AsyncIterator[BackendOutput]:
        tracker.on_dispatch()
        async for item in pipeline.generate(body, ctx):
            out = item if isinstance(item, BackendOutput) else BackendOutput.from_dict(item)
            tracker.on_token()
            if out.admission_wait_ms is not None:
                tracker.on_admission_wait(out.admission_wait_ms / 1e3)
            if out.finish_reason is not None:
                tracker.on_usage(out.prompt_tokens, out.cumulative_tokens, out.cached_tokens)
            yield out

    async def _stream_response(
        self, request: web.Request, model: str, kind: str, ctx: Context,
        backend_stream: AsyncIterator[BackendOutput], send_usage: bool,
        *, parse_tools: bool = False, tracker=None,
    ) -> web.StreamResponse:
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
        }
        # Surface the trace id to the client on the stream too: with it (or
        # the request id) /debug/traces and /debug/explain are reachable
        # without grepping worker logs.
        trace_id = (ctx.trace or {}).get("trace_id") if isinstance(ctx.trace, dict) else None
        if trace_id:
            headers["x-dynamo-trace-id"] = str(trace_id)
        resp = web.StreamResponse(headers=headers)
        await resp.prepare(request)
        fmt = ChatStream(model, send_usage=send_usage) if kind == "chat" else CompletionStream(model, send_usage=send_usage)
        jail = None
        if parse_tools:
            from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail

            jail = ToolCallStreamJail()
        try:
            if kind == "chat":
                await resp.write(sse_encode(fmt.first()))
            async for out in backend_stream:
                if out.finish_reason is FinishReason.ERROR and not out.token_ids:
                    # Mid-stream engine death: emit a structured OpenAI-style
                    # error event (never a traceback) and end the stream.
                    if tracker is not None:
                        tracker.status = "error"
                    await resp.write(sse_encode(_ENGINE_ERROR_EVENT))
                    break
                if jail is None:
                    await resp.write(sse_encode(fmt.delta(out)))
                    continue
                # Tools declared: hold back potential tool-call markup; on
                # the final delta decide between text and tool_calls finish.
                safe = jail.push(out.text) if out.text else ""
                if out.finish_reason is None:
                    if safe:
                        await resp.write(sse_encode(fmt.text_chunk(safe)))
                    continue
                trailing, calls = jail.finish()
                if calls:
                    if safe:
                        await resp.write(sse_encode(fmt.text_chunk(safe)))
                    await resp.write(sse_encode(fmt.tool_calls_final(calls, out)))
                else:
                    out.text = safe + trailing
                    await resp.write(sse_encode(fmt.delta(out)))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            logger.info("client disconnected; cancelling %s", ctx.id)
            ctx.kill()
            raise
        except Exception:
            # Headers are already on the wire: a JSON 500 is impossible. End
            # the SSE stream with an error event instead of a silent cut.
            logger.exception("stream failed mid-flight (model=%s)", model)
            ctx.kill()
            if tracker is not None:
                tracker.status = "error"
            try:
                await resp.write(sse_encode(_ENGINE_ERROR_EVENT))
                await resp.write(SSE_DONE)
            except (ConnectionResetError, OSError):
                pass
        finally:
            aclose = getattr(backend_stream, "aclose", None)
            if aclose:
                await aclose()
        await resp.write_eof()
        return resp

    # -- service endpoints -------------------------------------------------

    async def list_models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": c.name, "object": "model", "created": 0, "owned_by": "dynamo-tpu"}
                    for c in self.manager.cards()
                ],
            }
        )

    async def health(self, request: web.Request) -> web.Response:
        models = self.manager.names()
        status = "healthy" if models else "no_models"
        return web.json_response({"status": status, "models": models})

    async def live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def prometheus(self, request: web.Request) -> web.Response:
        self._sync_router_staleness()
        if self.telemetry is not None:
            from dynamo_tpu.observability.metrics import federate_text

            worker_parts: list[bytes] = []
            try:
                worker_parts = await self.telemetry.collect_metrics_texts()
            except Exception:
                logger.exception("worker metrics federation failed; serving frontend registry only")
            # Sync the scrape-failure counters *before* rendering the
            # frontend registry so a worker lost this scrape shows up in
            # this scrape's dynamo_federation_scrape_failures_total.
            self.metrics.sync_federation(self.telemetry.scrape_failures)
            parts = [self.metrics.render(), *worker_parts]
            return web.Response(body=federate_text(parts), content_type="text/plain")
        return web.Response(body=self.metrics.render(), content_type="text/plain")

    def _sync_router_staleness(self) -> None:
        """Fold every model's KvMetricsAggregator view into the staleness
        gauge (aggregators live in the model entries' aux lists)."""
        staleness: dict[int, float] = {}
        for name in self.manager.names():
            entry = self.manager.get(name)
            if entry is None:
                continue
            for a in entry.aux:
                fn = getattr(a, "staleness_seconds", None)
                if fn is not None:
                    staleness.update(fn())
        self.metrics.sync_staleness(staleness)

    async def debug_traces(self, request: web.Request) -> web.Response:
        """The assembled distributed timeline for one request id.

        Union of the frontend-local span ring and every worker's (via the
        telemetry fan-out), deduped by span_id; a second fan-out by trace_id
        catches spans a hop recorded under a different request id.
        """
        from dynamo_tpu.observability.service import assemble_timeline

        rid = request.match_info["request_id"]
        unique = await self._request_spans(rid)
        if not unique:
            return web.json_response(
                {"request_id": rid, "trace_ids": [], "span_count": 0, "spans": []}, status=404
            )
        return web.json_response(assemble_timeline(rid, unique))

    async def _request_spans(self, rid: str) -> list[dict]:
        """Deduped span-doc union for one request (local + worker fan-out +
        a trace-id follow-up for spans recorded under other request ids)."""
        from dynamo_tpu.tracing import SPANS

        spans = SPANS.query(request_id=rid)
        if self.telemetry is not None:
            try:
                spans += await self.telemetry.collect_spans(request_id=rid)
                for tid in sorted({s.get("trace_id") for s in spans if s.get("trace_id")}):
                    spans += SPANS.query(trace_id=tid)
                    spans += await self.telemetry.collect_spans(trace_id=tid)
            except Exception:
                logger.exception("trace fan-out failed; serving local spans only")
        seen: set[str] = set()
        unique = []
        for s in spans:
            sid = s.get("span_id")
            if sid and sid in seen:
                continue
            if sid:
                seen.add(sid)
            unique.append(s)
        return unique

    async def debug_explain(self, request: web.Request) -> web.Response:
        """One request's critical-path latency budget.

        Joins the request's span timeline (same union as ``/debug/traces``)
        with the serving worker's flight STEP/COMPILE records (``debug_explain``
        fan-out, windowed to the request's span bounds) into an ordered
        segment breakdown whose sum is checked against the measured E2E
        latency — the residual reported as ``unattributed``
        (``observability/attribution.py``).
        """
        from dynamo_tpu.config import load_attrib_settings
        from dynamo_tpu.observability.attribution import build_explain

        rid = request.match_info["request_id"]
        spans = await self._request_spans(rid)
        if not spans:
            return web.json_response(
                {"request_id": rid, "error": "no spans for this request id"}, status=404
            )
        step_docs: list[dict] = []
        if self.telemetry is not None:
            t0 = min((s.get("start_ts") or 0.0) for s in spans)
            t1 = max(
                (s.get("start_ts") or 0.0) + (s.get("duration_ms") or 0.0) / 1e3
                for s in spans
            )
            try:
                step_docs = await self.telemetry.collect_explain(t0=t0 - 1.0, t1=t1 + 1.0)
            except Exception:
                logger.exception("explain fan-out failed; serving span-only budget")
        doc = build_explain(
            rid, spans, step_docs,
            tolerance_frac=load_attrib_settings().tolerance_frac,
        )
        if doc is None:
            return web.json_response(
                {"request_id": rid, "error": "no anchor span (http_request/engine_request)"},
                status=404,
            )
        return web.json_response(doc)

    async def debug_flight(self, request: web.Request) -> web.Response:
        """One worker's engine flight ring (ordered per-step records).

        ``{worker}`` is the engine worker id (``all`` fans out to every
        worker); ``?last=N`` bounds the tail, ``?kind=step|compile|crash``
        filters by record kind.
        """
        if self.telemetry is None:
            return web.json_response(
                {"error": "no worker telemetry wired on this frontend"}, status=404
            )
        worker = request.match_info["worker"]
        last = request.query.get("last")
        try:
            rings = await self.telemetry.collect_flight(
                worker=worker,
                last=int(last) if last else None,
                kind=request.query.get("kind"),
            )
        except Exception:
            logger.exception("flight fan-out failed")
            return web.json_response({"error": "flight fan-out failed"}, status=502)
        if not rings:
            return web.json_response(
                {"error": f"no flight records for worker {worker!r}"}, status=404
            )
        return web.json_response(
            {
                "worker": worker,
                "workers": {
                    wid: {"count": len(recs), "records": recs} for wid, recs in rings.items()
                },
            }
        )

    async def debug_cost(self, request: web.Request) -> web.Response:
        """Fleet-wide device-cost snapshot: per-worker chip peaks, the
        per-compiled-program cost table (XLA flops / bytes-accessed / peak
        memory joined with measured dispatch wall) and the per-step-kind
        roofline ledger. A worker with ``DYN_COST_PLANE=0`` reports
        ``enabled: false`` rather than vanishing from the listing."""
        if self.telemetry is None:
            return web.json_response(
                {"error": "no worker telemetry wired on this frontend"}, status=404
            )
        try:
            workers = await self.telemetry.collect_cost()
        except Exception:
            logger.exception("cost fan-out failed")
            return web.json_response({"error": "cost fan-out failed"}, status=502)
        return web.json_response({"count": len(workers), "workers": workers})

    async def debug_profile_status(self, request: web.Request) -> web.Response:
        """Profile-capture availability: is ``jax.profiler`` usable on the
        worker, is a trace currently running, and where artifacts land.
        ``{worker}`` = engine worker id, or ``all``."""
        if self.telemetry is None:
            return web.json_response(
                {"error": "no worker telemetry wired on this frontend"}, status=404
            )
        worker = request.match_info["worker"]
        try:
            workers = await self.telemetry.profile_status(worker=worker)
        except Exception:
            logger.exception("profile status fan-out failed")
            return web.json_response({"error": "profile status fan-out failed"}, status=502)
        if not workers:
            return web.json_response(
                {"error": f"no profile endpoint for worker {worker!r}"}, status=404
            )
        return web.json_response({"worker": worker, "workers": workers})

    async def debug_profile_capture(self, request: web.Request) -> web.Response:
        """Arm a bounded device trace on one worker:
        ``POST /debug/profile/{worker}?duration_ms=2000``.

        Blocks for the trace window and returns the artifact directory +
        file summary; ``409`` when another capture is already running on
        that worker (single-flight) and ``501`` when ``jax.profiler`` is
        unavailable there — a refusal, not an error, so automation can tell
        "try later" from "never works here"."""
        if self.telemetry is None:
            return web.json_response(
                {"error": "no worker telemetry wired on this frontend"}, status=404
            )
        worker = request.match_info["worker"]
        try:
            duration_ms = float(request.query.get("duration_ms", 2000.0))
        except ValueError:
            return web.json_response({"error": "duration_ms must be a number"}, status=400)
        try:
            doc = await self.telemetry.capture_profile(worker, duration_ms)
        except Exception:
            logger.exception("profile capture fan-out failed")
            return web.json_response({"error": "profile capture failed"}, status=502)
        if doc is None:
            return web.json_response(
                {"error": f"no profile endpoint for worker {worker!r}"}, status=404
            )
        if not doc.get("ok"):
            status = {"busy": 409, "profiler_unavailable": 501}.get(
                doc.get("reason", ""), 502
            )
            return web.json_response(doc, status=status)
        return web.json_response(doc)

    async def debug_incidents(self, request: web.Request) -> web.Response:
        """Fleet-wide incident bundle listing (frontend-local + every worker).

        Workers on one host may share the incident directory (run_local,
        fleetsim), so summaries are deduplicated by id; each summary's
        ``worker`` field names the process that captured it.
        """
        workers: dict[str, list[dict]] = {}
        if self.telemetry is not None:
            try:
                workers = await self.telemetry.collect_incidents()
            except Exception:
                logger.exception("incident fan-out failed")
                return web.json_response({"error": "incident fan-out failed"}, status=502)
        seen: dict[str, dict] = {}
        for items in workers.values():
            for item in items:
                seen.setdefault(item["id"], item)
        for item in self.metrics.incidents.store.list():
            seen.setdefault(item["id"], item)
        incidents = sorted(seen.values(), key=lambda i: i.get("ts") or 0.0)
        return web.json_response({"count": len(incidents), "incidents": incidents})

    async def debug_incident(self, request: web.Request) -> web.Response:
        """One full incident bundle by id, from whichever process holds it."""
        incident_id = request.match_info["incident_id"]
        bundle = self.metrics.incidents.store.get(incident_id)
        if bundle is None and self.telemetry is not None:
            try:
                bundle = await self.telemetry.fetch_incident(incident_id)
            except Exception:
                logger.exception("incident fetch fan-out failed")
                return web.json_response({"error": "incident fetch failed"}, status=502)
        if bundle is None:
            return web.json_response({"error": f"no incident {incident_id!r}"}, status=404)
        return web.json_response(bundle)

    async def debug_federation(self, request: web.Request) -> web.Response:
        """Telemetry fan-out health: per-worker failure counts + last failure."""
        if self.telemetry is None:
            return web.json_response({"failures": {}, "last_failure": None})
        return web.json_response(
            {
                "failures": dict(self.telemetry.scrape_failures),
                "last_failure": self.telemetry.last_failure,
            }
        )

    async def debug_store(self, request: web.Request) -> web.Response:
        """HA control-plane view from this process: the hosted store replica
        (role/epoch/seq/lag, if one lives in-process), the client-side
        failover ledger, and the router's index-resync counter. Process-local
        snapshots only — no store RPC, so it answers even mid-failover."""
        from dynamo_tpu.router.events import router_resync_snapshot
        from dynamo_tpu.runtime.replication import replica_snapshot
        from dynamo_tpu.runtime.store_server import store_client_snapshot

        return web.json_response(
            {
                "replica": replica_snapshot(),
                "client": store_client_snapshot(),
                "router": router_resync_snapshot(),
            }
        )

    async def engine_profile(self, request: web.Request) -> web.Response:
        """On-demand device trace: POST {"seconds": 3, "dir": "/tmp/trace"}.

        Captures an XPlane trace of this process's JAX work (meaningful when
        the engine runs in-process, `launch.run_local`); view with
        TensorBoard/xprof. Parity: A1 tracing hook (reference exposes engine
        profilers through its debug surface)."""
        from dynamo_tpu.tracing import profile_for, trace_running

        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be a JSON object"}, status=400)
        try:
            seconds = min(max(float(body.get("seconds", 3.0)), 0.1), 60.0)
        except (TypeError, ValueError):
            return web.json_response({"error": "seconds must be a number"}, status=400)
        log_dir = str(body.get("dir", "/tmp/dynamo-trace"))
        if trace_running():
            return web.json_response({"error": "trace already running"}, status=409)
        path = await profile_for(seconds, log_dir)
        return web.json_response({"trace_dir": path, "seconds": seconds})

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        if self.clear_kv_hook is None:
            return web.json_response({"cleared": 0, "detail": "no workers wired"}, status=200)
        cleared = await self.clear_kv_hook()
        return web.json_response({"cleared": cleared})
