"""The ``dynamo`` CLI: inspect and serve service graphs.

- ``python -m dynamo_tpu.sdk graph graphs.agg:Frontend`` — print topology.
- ``python -m dynamo_tpu.sdk serve graphs.agg:Frontend -f config.yaml`` —
  one process per service replica, coordinated via a store server.
- ``python -m dynamo_tpu.sdk config -f config.yaml`` — show the merged
  per-service config after the file+env cascade.

Parity: reference `deploy/sdk` `dynamo serve` CLI (`cli/serving.py:49-288`).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal
import sys


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="python -m dynamo_tpu.sdk")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_graph = sub.add_parser("graph", help="print a graph's topology")
    p_graph.add_argument("ref")

    p_serve = sub.add_parser("serve", help="serve a graph, one process per replica")
    p_serve.add_argument("ref")
    p_serve.add_argument("-f", "--config", default=None)
    p_serve.add_argument("--store-port", type=int, default=7411)
    p_serve.add_argument("--host", default="127.0.0.1")

    p_cfg = sub.add_parser("config", help="print the merged service config")
    p_cfg.add_argument("-f", "--config", default=None)

    p_build = sub.add_parser("build", help="package a graph into a deployable archive")
    p_build.add_argument("ref")
    p_build.add_argument("-f", "--config", default=None)
    p_build.add_argument("-o", "--output", default=None, help="output .tar.gz (default <graph>.tar.gz)")

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from dynamo_tpu.sdk.graph import load_graph
    from dynamo_tpu.sdk.serving import ServeFleet, load_service_config

    if args.cmd == "graph":
        print(load_graph(args.ref).describe())
    elif args.cmd == "config":
        print(json.dumps(load_service_config(args.config), indent=2))
    elif args.cmd == "build":
        from dynamo_tpu.sdk.build import build_archive

        out = build_archive(args.ref, config_path=args.config, output=args.output)
        print(f"BUILT {out}")
    elif args.cmd == "serve":
        graph = load_graph(args.ref)
        config = load_service_config(args.config)

        async def run() -> None:
            fleet = await ServeFleet(
                args.ref, config_path=args.config, store_port=args.store_port, host=args.host
            ).start(graph, config)
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
            print(f"FLEET UP services={[s.name for s in graph.services]}", flush=True)
            try:
                await stop.wait()
            finally:
                await fleet.close()

        asyncio.run(run())
    else:  # pragma: no cover
        parser.print_help()
        sys.exit(2)


if __name__ == "__main__":
    main()
