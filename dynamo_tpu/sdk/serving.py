"""Serve a service graph: bind services to the runtime, wire dependencies.

Two modes share all the binding code:

- ``serve_graph`` — every service in one process over one runtime
  (``DistributedRuntime.detached()`` by default). Dev loop + tests.
- ``serve_fleet`` — one OS process per service replica (subprocesses running
  ``python -m dynamo_tpu.sdk.serve_entry``), coordinated through a TCP store
  server; replica crash → respawn. Deployment shape of the reference's
  ``dynamo serve`` (circus watchers, `cli/serving.py:49-288`), with process
  supervision instead of circus and the shared store instead of NATS/etcd.

Per-service config cascades YAML/TOML file -> ``DYN_SVC_<SERVICE>_<FIELD>``
env -> constructor; ``replicas`` and ``resources`` keys override the
decorator (reference `lib/config.py` cascade).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import logging
import os
import pathlib
import sys
import time
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.sdk import ServiceClient, ServiceSpec
from dynamo_tpu.sdk.graph import Graph

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Config cascade
# ---------------------------------------------------------------------------


def load_service_config(path: str | pathlib.Path | None, *, env: dict[str, str] | None = None) -> dict[str, dict[str, Any]]:
    """service name -> merged config section (file then env overrides)."""
    env = os.environ if env is None else env
    sections: dict[str, dict[str, Any]] = {}
    if path is not None:
        p = pathlib.Path(path)
        text = p.read_text()
        if p.suffix in (".yaml", ".yml"):
            import yaml

            data = yaml.safe_load(text) or {}
        elif p.suffix == ".toml":
            import tomllib

            data = tomllib.loads(text)
        else:
            data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(f"service config {p} must be a mapping of service name -> section")
        sections = {str(k): dict(v or {}) for k, v in data.items()}
    # DYN_SVC_WORKER_REPLICAS=2 -> sections["Worker"]["replicas"] = 2. The
    # service-name token is matched case-insensitively against existing
    # sections at every underscore split (so DYN_SVC_KV_ROUTER_REPLICAS can
    # target a KvRouter section); otherwise the first token becomes a new
    # UPPERCASE section, which _section_for matches via spec.name.upper().
    for key, raw in env.items():
        if not key.startswith("DYN_SVC_"):
            continue
        rest = key[len("DYN_SVC_") :]
        parts = rest.split("_")
        if len(parts) < 2:
            continue
        try:
            value: Any = json.loads(raw)
        except (json.JSONDecodeError, ValueError):
            value = raw
        by_upper = {name.upper().replace("_", ""): name for name in sections}
        bucket = None
        field = ""
        for split in range(len(parts) - 1, 0, -1):
            candidate = "".join(parts[:split])
            if candidate in by_upper:
                bucket = sections[by_upper[candidate]]
                field = "_".join(parts[split:]).lower()
                break
        if bucket is None:
            bucket = sections.setdefault(parts[0], {})
            field = "_".join(parts[1:]).lower()
        if field:
            bucket[field] = value
    return sections


def _section_for(config: dict[str, dict[str, Any]], spec: ServiceSpec) -> dict[str, Any]:
    for key in (spec.name, spec.name.upper(), spec.component):
        if key in config:
            return dict(config[key])
    return {}


# ---------------------------------------------------------------------------
# Binding a service object to the runtime
# ---------------------------------------------------------------------------


class _MethodEngine(AsyncEngine[Any, Any]):
    """Adapts a bound service method into the AsyncEngine contract.

    Async generators stream; plain coroutines become one-item streams. The
    method may accept (request) or (request, context).
    """

    def __init__(self, fn: Any) -> None:
        self.fn = fn
        params = [
            p for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
        self._wants_context = len(params) >= 2

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        args = (request, context) if self._wants_context else (request,)
        if inspect.isasyncgenfunction(self.fn):
            async for item in self.fn(*args):
                if context.is_stopped or context.is_killed:
                    break
                yield item
        else:
            yield await self.fn(*args)


async def bind_dependencies(runtime: DistributedRuntime, spec: ServiceSpec, obj: Any) -> list[ServiceClient]:
    """Install a started ServiceClient for every ``depends()`` attribute."""
    bound: list[ServiceClient] = []
    for attr, dep in spec.dependencies.items():
        target = dep.spec
        clients = {}
        for ep in target.endpoints:
            endpoint = (
                runtime.namespace(target.namespace).component(target.component).endpoint(ep.name)
            )
            clients[ep.name] = await endpoint.client(router_mode=dep.router_mode).start()
        sc = ServiceClient(clients)
        obj.__dict__[attr] = sc
        bound.append(sc)
    return bound


def _construct(spec: ServiceSpec, section: dict[str, Any]) -> Any:
    """Instantiate the service class; pass the config section if accepted."""
    kwargs = {k: v for k, v in section.items() if k not in ("replicas", "resources", "http_port")}
    if spec.cls.__init__ is object.__init__:
        params: dict[str, Any] = {}
        takes_kw = False
    else:
        params = inspect.signature(spec.cls.__init__).parameters
        takes_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())
    accepted = {
        k: v for k, v in kwargs.items() if takes_kw or k in params
    }
    dropped = sorted(set(kwargs) - set(accepted))
    if dropped:
        logger.warning("service %s: config keys %s not accepted by __init__", spec.name, dropped)
    obj = spec.cls(**accepted)
    obj.__dict__.setdefault("config", dict(section))
    return obj


class ServiceHandle:
    def __init__(self, spec: ServiceSpec, obj: Any, runtime: DistributedRuntime) -> None:
        self.spec = spec
        self.obj = obj
        self.runtime = runtime
        self.instances: list[Any] = []
        self.clients: list[ServiceClient] = []
        self.http_site: Any = None
        self.http_port: int | None = None

    async def close(self) -> None:
        if self.http_site is not None:
            await self.http_site.cleanup()
        for c in self.clients:
            await c.close()
        shutdown = getattr(self.obj, "async_shutdown", None)
        if shutdown is not None:
            await shutdown()


async def serve_service(
    runtime: DistributedRuntime,
    spec: ServiceSpec,
    section: dict[str, Any] | None = None,
    *,
    http_port: int | None = None,
    http_host: str = "127.0.0.1",
) -> ServiceHandle:
    """Construct + bind + publish one service on ``runtime``.

    A configured ``http_port`` is offset by this process's replica index
    (``DYN_SDK_REPLICA``), so ``replicas: 2`` with ``http_port: 8000`` binds
    :8000 and :8001 instead of crash-looping on EADDRINUSE.
    """
    section = dict(section or {})
    obj = _construct(spec, section)
    handle = ServiceHandle(spec, obj, runtime)
    handle.clients = await bind_dependencies(runtime, spec, obj)
    init = getattr(obj, "async_init", None)
    if init is not None:
        await init()
    lease = await runtime.primary_lease()
    for ep in spec.endpoints:
        endpoint = runtime.namespace(spec.namespace).component(spec.component).endpoint(ep.name)
        engine = _MethodEngine(getattr(obj, ep.method))
        handle.instances.append(await endpoint.serve(engine, lease=lease))
    if spec.apis:
        port = http_port if http_port is not None else int(section.get("http_port", 0))
        if port > 0:
            port += int(os.environ.get("DYN_SDK_REPLICA", "0") or 0)
        if port >= 0:
            handle.http_site, handle.http_port = await _serve_apis(spec, obj, port, host=http_host)
    return handle


async def _serve_apis(spec: ServiceSpec, obj: Any, port: int, *, host: str = "127.0.0.1"):
    """Mount ``@api`` methods on an aiohttp app (dict -> JSON, async gen -> SSE)."""
    from aiohttp import web

    app = web.Application()

    def make_handler(api_spec):
        method = getattr(obj, api_spec.method)

        async def handler(request: web.Request) -> web.StreamResponse:
            if request.method in ("POST", "PUT", "PATCH"):
                try:
                    body = await request.json()
                except json.JSONDecodeError:
                    return web.json_response({"error": "invalid JSON body"}, status=400)
            else:
                body = dict(request.query)
            result = method(body)
            if inspect.isasyncgen(result):
                resp = web.StreamResponse(
                    headers={"Content-Type": "text/event-stream", "Cache-Control": "no-cache"}
                )
                # Failures before the first item become a clean 500; once
                # streaming has started the only honest signal is an error
                # event + connection close (headers are already gone).
                try:
                    first = await anext(result, None)
                except Exception as exc:
                    logger.exception("api %s failed", api_spec.path)
                    return web.json_response({"error": str(exc)}, status=500)
                await resp.prepare(request)
                try:
                    if first is not None:
                        data = first if isinstance(first, str) else json.dumps(first)
                        await resp.write(f"data: {data}\n\n".encode())
                    async for item in result:
                        data = item if isinstance(item, str) else json.dumps(item)
                        await resp.write(f"data: {data}\n\n".encode())
                    await resp.write(b"data: [DONE]\n\n")
                except (ConnectionResetError, ConnectionError):
                    logger.debug("api %s: client disconnected mid-stream", api_spec.path)
                    return resp
                except Exception as exc:
                    logger.exception("api %s failed mid-stream", api_spec.path)
                    try:
                        await resp.write(f"data: {json.dumps({'error': str(exc)})}\n\n".encode())
                    except (ConnectionResetError, ConnectionError):
                        return resp
                await resp.write_eof()
                return resp
            try:
                value = await result
            except Exception as exc:  # service bug -> 500, not a dead connection
                logger.exception("api %s failed", api_spec.path)
                return web.json_response({"error": str(exc)}, status=500)
            if isinstance(value, web.StreamResponse):
                return value
            return web.json_response(value)

        return handler

    for api_spec in spec.apis:
        app.router.add_route(api_spec.http_method, api_spec.path, make_handler(api_spec))
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    actual = runner.addresses[0][1] if runner.addresses else port
    logger.info("service %s api on http://%s:%s", spec.name, host, actual)
    return runner, actual


# ---------------------------------------------------------------------------
# In-process graph serving (dev / tests)
# ---------------------------------------------------------------------------


class GraphHandles:
    def __init__(self, runtime: DistributedRuntime, handles: list[ServiceHandle], own_runtime: bool) -> None:
        self.runtime = runtime
        self.handles = handles
        self._own_runtime = own_runtime

    def get(self, name: str) -> ServiceHandle:
        for h in self.handles:
            if h.spec.name == name:
                return h
        raise KeyError(name)

    async def close(self) -> None:
        for h in reversed(self.handles):  # dependents first
            await h.close()
        if self._own_runtime:
            await self.runtime.close()


async def serve_graph(
    graph: Graph,
    *,
    runtime: DistributedRuntime | None = None,
    config: dict[str, dict[str, Any]] | None = None,
) -> GraphHandles:
    own = runtime is None
    runtime = runtime or DistributedRuntime.detached()
    config = config or {}
    handles: list[ServiceHandle] = []
    try:
        for spec in graph.services:  # leaves first
            handles.append(await serve_service(runtime, spec, _section_for(config, spec)))
    except BaseException:
        for h in reversed(handles):
            await h.close()
        if own:
            await runtime.close()
        raise
    return GraphHandles(runtime, handles, own)


# ---------------------------------------------------------------------------
# Multi-process fleet serving (deployment)
# ---------------------------------------------------------------------------


class ServeFleet:
    """One subprocess per service replica + the coordinating store server."""

    def __init__(self, ref: str, *, config_path: str | None, store_port: int, host: str = "127.0.0.1") -> None:
        self.ref = ref
        self.config_path = config_path
        self.store_port = store_port
        self.host = host
        self.procs: list[tuple[str, Any]] = []
        self.store_server: Any = None
        self._respawn_task: asyncio.Task | None = None
        self._closing = False

    async def start(self, graph: Graph, config: dict[str, dict[str, Any]]) -> "ServeFleet":
        from dynamo_tpu.runtime.store_server import StoreServer

        self.store_server = await StoreServer(host=self.host, port=self.store_port).start()
        self.store_port = self.store_server.port  # resolve an ephemeral request (port=0)
        for spec in graph.services:
            replicas = int(_section_for(config, spec).get("replicas", spec.replicas))
            for i in range(replicas):
                self.procs.append([spec.name, i, self._spawn(spec.name, i), time.monotonic(), 1.0])
        self._respawn_task = asyncio.create_task(self._supervise())
        return self

    def _spawn(self, service: str, replica: int):
        import subprocess

        cmd = [
            sys.executable, "-m", "dynamo_tpu.sdk.serve_entry",
            self.ref, "--service", service,
            "--store", f"tcp://{self.host}:{self.store_port}",
            "--host", self.host,
        ]
        if self.config_path:
            cmd += ["-f", self.config_path]
        env = dict(os.environ)
        env["DYN_SDK_REPLICA"] = str(replica)  # replica N of *this service*
        logger.info("spawning %s[%d]: %s", service, replica, " ".join(cmd))
        return subprocess.Popen(cmd, env=env)

    async def _supervise(self) -> None:
        """Respawn dead replicas (the circus-watcher role).

        Per-replica exponential backoff: a replica that dies right after
        spawning (bad config, port conflict) is retried at 1s, 2s, ... 30s
        instead of fork-bombing at 1 Hz; a long-lived replica that crashes
        resets to the fast path.
        """
        while not self._closing:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            for entry in self.procs:
                name, replica, proc, spawned_at, backoff = entry
                if proc.poll() is None or self._closing:
                    continue
                lived = now - spawned_at
                if lived >= 10.0:
                    backoff = 1.0  # it served for a while: crash, not a config bug
                if now - spawned_at < backoff:
                    continue  # still in this replica's backoff window
                logger.warning(
                    "service %s[%d] exited rc=%s after %.1fs; respawning (backoff %.0fs)",
                    name, replica, proc.returncode, lived, backoff,
                )
                entry[2] = self._spawn(name, replica)
                entry[3] = time.monotonic()
                entry[4] = min(backoff * 2.0, 30.0)

    async def close(self) -> None:
        self._closing = True
        if self._respawn_task is not None:
            self._respawn_task.cancel()
        for entry in self.procs:
            if entry[2].poll() is None:
                entry[2].terminate()
        loop = asyncio.get_running_loop()

        def wait_all() -> None:
            for entry in self.procs:
                try:
                    entry[2].wait(timeout=10)
                except Exception:
                    entry[2].kill()

        await loop.run_in_executor(None, wait_all)
        if self.store_server is not None:
            await self.store_server.close()
