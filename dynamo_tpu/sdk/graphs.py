"""Ready-made LLM service graphs for the SDK (`dynamo serve` targets).

``Frontend`` -> ``Processor`` -> ``Worker`` is the aggregated topology of the
reference's `examples/llm/graphs/agg.py`: HTTP ingress, tokenize/detokenize,
first-party JAX engine. Serve it with::

    python -m dynamo_tpu.sdk serve dynamo_tpu.sdk.graphs:Frontend -f cfg.yaml

where cfg.yaml can set per-service keys, e.g.::

    Worker: {model: test-tiny, num_pages: 64}
    Frontend: {http_port: 8000}

Every service also works in-process via ``sdk.serving.serve_graph`` (the
tests drive the full chain that way on the in-memory runtime).
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from dynamo_tpu.sdk import api, depends, endpoint, service


@service(namespace="inference", resources={"tpu": 1})
class Worker:
    """First-party JAX engine behind a ``generate`` endpoint.

    Config: ``model`` (preset name, checkpoint dir, or .gguf), ``mock``
    (timing-model engine instead of the JAX engine), plus engine knobs
    (``num_pages``, ``max_batch_size``).
    """

    def __init__(self, model: str = "test-tiny", mock: bool = False, **engine_kw: Any) -> None:
        self.model = model
        self.mock = mock
        self.engine_kw = engine_kw
        self.service: Any = None

    async def async_init(self) -> None:
        from dynamo_tpu.launch import build_engine_service, make_worker_spec

        spec = make_worker_spec(self.model, **self.engine_kw)
        if self.mock:
            from dynamo_tpu.mocker import build_mock_service

            self.service = await build_mock_service(spec.engine_config)
        else:
            self.service = await build_engine_service(spec)
        self.card = spec.card

    @endpoint()
    async def generate(self, request: Any, context: Any) -> AsyncIterator[Any]:
        from dynamo_tpu.protocols.common import PreprocessedRequest

        if isinstance(request, dict):
            request = PreprocessedRequest.from_dict(request)
        async for out in self.service.generate(request, context):
            yield out

    async def async_shutdown(self) -> None:
        if self.service is not None:
            await self.service.close()


@service(namespace="inference")
class Processor:
    """Tokenize prompts in, detokenize token streams out."""

    def __init__(self, model: str = "test-tiny", tokenizer: str | None = None) -> None:
        import os

        from dynamo_tpu.tokenizer import load_tokenizer

        # Mirror the Worker's `model` key: a checkpoint dir / .gguf brings its
        # own tokenizer; presets fall back to the hermetic byte tokenizer.
        if tokenizer is None:
            tokenizer = model if os.path.exists(model) else "byte"
        self.tokenizer = load_tokenizer(tokenizer)

    worker = depends(Worker)

    @endpoint()
    async def generate(self, request: dict, context: Any) -> AsyncIterator[dict]:
        from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
        from dynamo_tpu.tokenizer import IncrementalDetokenizer

        prompt = request.get("prompt", "")
        pre = PreprocessedRequest(
            token_ids=self.tokenizer.encode(prompt, add_bos=True),
            sampling=SamplingOptions(temperature=float(request.get("temperature", 0.0))),
            stop=StopConditions(max_tokens=int(request.get("max_tokens", 16))),
        )
        detok = IncrementalDetokenizer(self.tokenizer)
        async for out in self.worker.generate(pre.to_dict(), context):
            token_ids = out.get("token_ids", []) if isinstance(out, dict) else []
            text = detok.push(token_ids) if token_ids else ""
            item = {"text": text}
            if isinstance(out, dict) and out.get("finish_reason"):
                item["finish_reason"] = out["finish_reason"]
            yield item


@service(namespace="inference")
class Frontend:
    """HTTP ingress: ``POST /generate`` -> SSE stream of text deltas."""

    processor = depends(Processor)

    @api(path="/generate")
    async def generate(self, body: dict) -> AsyncIterator[dict]:
        async for item in self.processor.generate(body):
            yield item
