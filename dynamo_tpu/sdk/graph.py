"""Graph discovery: ``module:Service`` ref -> topologically ordered services.

``load_graph("examples.agg:Frontend")`` imports the module, takes the named
service class, and walks its ``depends()`` edges transitively. The resulting
order is leaves-first so the serving layer brings dependencies up before
their dependents (a frontend never starts with a dead backend edge).

Parity: reference `deploy/sdk/.../cli/serving.py` graph resolution (the
``graphs/agg.py`` + ``dynamo serve graphs.agg:Frontend`` flow).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Iterable

from dynamo_tpu.sdk import ServiceSpec, spec_of


@dataclasses.dataclass
class Graph:
    entry: ServiceSpec
    services: list[ServiceSpec]  # leaves-first; entry is last

    def __iter__(self) -> Iterable[ServiceSpec]:
        return iter(self.services)

    def get(self, name: str) -> ServiceSpec:
        for s in self.services:
            if s.name == name:
                return s
        raise KeyError(f"graph has no service {name!r} (has: {[s.name for s in self.services]})")

    def edges(self) -> list[tuple[str, str]]:
        """(dependent, dependency) service-name pairs."""
        out = []
        for s in self.services:
            for dep in s.dependencies.values():
                out.append((s.name, dep.spec.name))
        return out

    def describe(self) -> str:
        lines = []
        for s in self.services:
            deps = ", ".join(d.spec.name for d in s.dependencies.values()) or "-"
            eps = ", ".join(e.name for e in s.endpoints) or "-"
            apis = ", ".join(f"{a.http_method} {a.path}" for a in s.apis) or "-"
            lines.append(
                f"{s.name} (ns={s.namespace}, replicas={s.replicas}, "
                f"resources={s.resources or '-'}) endpoints=[{eps}] apis=[{apis}] deps=[{deps}]"
            )
        return "\n".join(lines)


def build_graph(entry_cls: type) -> Graph:
    """Walk ``depends()`` edges from ``entry_cls``; cycle-safe; leaves first."""
    entry = spec_of(entry_cls)
    order: list[ServiceSpec] = []
    seen: set[type] = set()
    visiting: set[type] = set()

    def visit(cls: type, chain: tuple[str, ...]) -> None:
        if cls in seen:
            return
        if cls in visiting:
            raise ValueError(f"dependency cycle: {' -> '.join(chain + (cls.__name__,))}")
        visiting.add(cls)
        spec = spec_of(cls)
        for dep in spec.dependencies.values():
            visit(dep.target, chain + (cls.__name__,))
        visiting.discard(cls)
        seen.add(cls)
        order.append(spec)

    visit(entry_cls, ())
    return Graph(entry=entry, services=order)


def load_graph(ref: str) -> Graph:
    """Resolve a ``module.path:ServiceName`` reference to a Graph."""
    module_name, _, attr = ref.partition(":")
    if not attr:
        raise ValueError(f"graph ref must be 'module:Service', got {ref!r}")
    module = importlib.import_module(module_name)
    try:
        entry_cls = getattr(module, attr)
    except AttributeError:
        raise AttributeError(f"module {module_name!r} has no service {attr!r}") from None
    return build_graph(entry_cls)
