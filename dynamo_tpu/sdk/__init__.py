"""Service-graph SDK: ``@service`` / ``@endpoint`` / ``@api`` / ``depends()``.

Declares inference graphs as plain Python classes whose dependency edges are
class attributes. The decorators only attach metadata — a decorated class
stays an ordinary class, instantiable and unit-testable without any runtime.
``sdk.graph.load_graph`` walks the edges into a topologically-ordered Graph,
and ``sdk.serving`` binds each service onto the DistributedRuntime (one
process per service, or all-in-process for tests/dev).

Example::

    @service(namespace="inference", resources={"tpu": 1})
    class Worker:
        @endpoint()
        async def generate(self, request, context):
            yield {"text": "..."}

    @service(namespace="inference")
    class Frontend:
        worker = depends(Worker)

        @api(path="/generate")
        async def generate(self, body):
            return [r async for r in self.worker.generate(body)]

Parity: reference `deploy/sdk/src/dynamo/sdk/__init__.py` decorators
(`core/decorators/endpoint.py:99-112`, `lib/decorators.py:68-95`) and its
`depends()` service-graph DSL. TPU-first difference: services bind to the
first-party runtime's component model (`runtime/component.py`) rather than a
circus/NATS deployment, and resource requests are expressed in TPU chips.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, AsyncIterator, Callable

__all__ = [
    "api",
    "depends",
    "endpoint",
    "service",
    "ApiSpec",
    "Dependency",
    "EndpointSpec",
    "ServiceClient",
    "ServiceSpec",
]

_SERVICE_ATTR = "__dynamo_service__"
_ENDPOINT_ATTR = "__dynamo_endpoint__"
_API_ATTR = "__dynamo_api__"


@dataclasses.dataclass(frozen=True)
class EndpointSpec:
    name: str
    method: str  # attribute name on the class


@dataclasses.dataclass(frozen=True)
class ApiSpec:
    path: str
    http_method: str
    method: str  # attribute name on the class


@dataclasses.dataclass
class ServiceSpec:
    name: str
    namespace: str
    component: str
    cls: type
    resources: dict[str, Any]
    replicas: int
    endpoints: list[EndpointSpec]
    apis: list[ApiSpec]
    dependencies: dict[str, "Dependency"]

    @property
    def ref(self) -> str:
        return f"{self.namespace}/{self.component}"


def service(
    cls: type | None = None,
    *,
    name: str | None = None,
    namespace: str = "dynamo",
    resources: dict[str, Any] | None = None,
    replicas: int = 1,
) -> Any:
    """Class decorator: register endpoints/apis/dependencies as a service."""

    def wrap(target: type) -> type:
        endpoints: list[EndpointSpec] = []
        apis: list[ApiSpec] = []
        for attr, member in inspect.getmembers(target, callable):
            ep = getattr(member, _ENDPOINT_ATTR, None)
            if ep is not None:
                endpoints.append(EndpointSpec(name=ep or attr, method=attr))
            ap = getattr(member, _API_ATTR, None)
            if ap is not None:
                apis.append(ApiSpec(path=ap[0], http_method=ap[1], method=attr))
        deps = {
            attr: value
            for attr, value in vars(target).items()
            if isinstance(value, Dependency)
        }
        spec = ServiceSpec(
            name=name or target.__name__,
            namespace=namespace,
            component=(name or target.__name__).lower(),
            cls=target,
            resources=dict(resources or {}),
            replicas=replicas,
            endpoints=sorted(endpoints, key=lambda e: e.name),
            apis=sorted(apis, key=lambda a: a.path),
            dependencies=deps,
        )
        setattr(target, _SERVICE_ATTR, spec)
        return target

    return wrap(cls) if cls is not None else wrap


def endpoint(fn: Callable | None = None, *, name: str | None = None) -> Any:
    """Mark a method as a runtime endpoint (async generator or coroutine)."""

    def wrap(target: Callable) -> Callable:
        setattr(target, _ENDPOINT_ATTR, name or "")
        return target

    return wrap(fn) if fn is not None else wrap


def api(fn: Callable | None = None, *, path: str | None = None, method: str = "POST") -> Any:
    """Mark a method as an HTTP route (served when the service runs)."""

    def wrap(target: Callable) -> Callable:
        setattr(target, _API_ATTR, (path or f"/{target.__name__}", method.upper()))
        return target

    return wrap(fn) if fn is not None else wrap


def spec_of(cls: type) -> ServiceSpec:
    spec = getattr(cls, _SERVICE_ATTR, None)
    if spec is None:
        raise TypeError(f"{cls.__name__} is not a @service-decorated class")
    return spec


class Dependency:
    """A ``depends(OtherService)`` edge.

    As a descriptor it resolves to the :class:`ServiceClient` installed by the
    serving layer (``instance.__dict__[attr]``); accessing it on an unbound
    instance raises, which keeps "forgot to serve the dependency" an explicit
    error instead of a hang.
    """

    def __init__(self, target: type, *, router_mode: str = "round_robin") -> None:
        self.target = target
        self.router_mode = router_mode
        self._attr: str | None = None

    @property
    def spec(self) -> ServiceSpec:
        return spec_of(self.target)

    def __set_name__(self, owner: type, attr: str) -> None:
        self._attr = attr

    def __get__(self, obj: Any, objtype: type | None = None) -> Any:
        if obj is None:
            return self
        try:
            return obj.__dict__[self._attr]
        except KeyError:
            raise RuntimeError(
                f"dependency {objtype.__name__}.{self._attr} is not bound — "
                f"serve the graph (sdk.serving) or inject a client for tests"
            ) from None


def depends(target: type, *, router_mode: str = "round_robin") -> Dependency:
    return Dependency(target, router_mode=router_mode)


class ServiceClient:
    """What a bound ``depends()`` resolves to: one call per target endpoint.

    ``client.generate(req)`` opens a response stream on a live replica of the
    target service (routing + retries from ``runtime/client.py``).
    """

    def __init__(self, clients: dict[str, Any]) -> None:
        self._clients = clients

    def __getattr__(self, name: str) -> Callable[..., AsyncIterator[Any]]:
        try:
            client = self._clients[name]
        except KeyError:
            raise AttributeError(
                f"target service has no endpoint {name!r} (has: {sorted(self._clients)})"
            ) from None

        def call(request: Any, context: Any | None = None, **kw: Any) -> AsyncIterator[Any]:
            return client.generate(request, context, **kw)

        return call

    def endpoint_client(self, name: str) -> Any:
        """The underlying runtime Client (instance table, direct routing)."""
        return self._clients[name]

    async def close(self) -> None:
        for client in self._clients.values():
            await client.close()
