"""Per-process entrypoint for fleet serving: run ONE service of a graph.

``python -m dynamo_tpu.sdk.serve_entry graphs.agg:Frontend --service Worker
--store tcp://127.0.0.1:7001 [-f config.yaml]``

Connects to the deployment's store server, binds the named service's
endpoints onto a TCP transport, and serves until signalled. The reference's
``serve_dynamo.py`` plays this role under circus (`cli/serving.py`).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store_server import StoreClient
from dynamo_tpu.runtime.tcp import TcpTransport
from dynamo_tpu.sdk.graph import load_graph
from dynamo_tpu.sdk.serving import _section_for, load_service_config, serve_service

logger = logging.getLogger(__name__)


async def amain(args: argparse.Namespace) -> None:
    graph = load_graph(args.graph)
    spec = graph.get(args.service)
    config = load_service_config(args.config)
    store = StoreClient.from_url(args.store)
    runtime = DistributedRuntime(store, TcpTransport(host=args.host))
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    handle = await serve_service(runtime, spec, _section_for(config, spec), http_host=args.host)
    print(f"SERVING {spec.name} instances={len(handle.instances)}", flush=True)
    try:
        await stop.wait()
    finally:
        await handle.close()
        await runtime.close()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="python -m dynamo_tpu.sdk.serve_entry")
    parser.add_argument("graph", help="module:Service graph reference")
    parser.add_argument("--service", required=True, help="which service of the graph to run")
    parser.add_argument("--store", required=True, help="tcp://host:port of the store server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("-f", "--config", default=None, help="YAML/TOML/JSON service config")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
