"""``dynamo build``: package a service graph into a deployable archive.

The archive is a tar.gz containing the graph's *user* source modules (the
modules defining its services, with parent ``__init__.py`` files so
``src/`` is a regular importable tree), the service config, and
``manifest.json`` (graph ref, service inventory, resources, build
metadata). A deploy host with dynamo-tpu installed extracts the archive,
puts ``src/`` on ``sys.path``, and serves the manifest's graph ref —
framework-internal modules (``dynamo_tpu.*``) are intentionally not
packaged; they come with the installed framework.

Parity: reference ``dynamo build`` packaging (`deploy/sdk` — builds a
deployable service artifact consumed by the operator's image pipeline).
"""

from __future__ import annotations

import inspect
import io
import json
import pathlib
import sys
import tarfile
import time
from typing import Any

from dynamo_tpu.sdk.graph import Graph, load_graph


def _manifest(ref: str, graph: Graph, config: dict[str, Any]) -> dict[str, Any]:
    return {
        "schema": 1,
        "graph": ref,
        "entry": graph.entry.name,
        "built_at": time.time(),
        "services": [
            {
                "name": s.name,
                "namespace": s.namespace,
                "component": s.component,
                "replicas": s.replicas,
                "resources": s.resources,
                "endpoints": [e.name for e in s.endpoints],
                "apis": [f"{a.http_method} {a.path}" for a in s.apis],
                "module": s.cls.__module__,
            }
            for s in graph.services
        ],
        "config": config,
    }


def build_archive(
    ref: str,
    *,
    config_path: str | None = None,
    output: str | None = None,
) -> pathlib.Path:
    """module:Service ref -> <name>.tar.gz with sources + manifest."""
    from dynamo_tpu.sdk.serving import load_service_config

    graph = load_graph(ref)
    config = load_service_config(config_path)
    module_names = {s.cls.__module__ for s in graph.services}
    out = pathlib.Path(output or f"{graph.entry.name.lower()}.tar.gz")
    manifest = _manifest(ref, graph, config)

    with tarfile.open(out, "w:gz") as tar:
        def add_bytes(name: str, data: bytes) -> None:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))

        add_bytes("manifest.json", json.dumps(manifest, indent=2).encode())
        if config_path:
            add_bytes(f"config{pathlib.Path(config_path).suffix}", pathlib.Path(config_path).read_bytes())
        packaged: set[str] = set()
        for module_name in sorted(module_names):
            # Framework-internal graphs ship with the installed dynamo-tpu —
            # packaging them would require shadowing the whole framework
            # package at import time. Only user graph modules go in.
            if module_name == "dynamo_tpu" or module_name.startswith("dynamo_tpu."):
                continue
            module = sys.modules[module_name]
            src_file = inspect.getsourcefile(module)
            if src_file is None:
                continue
            # store under src/<dotted path as path>; packages (services
            # defined in a pkg __init__) land as pkg/__init__.py, and parent
            # packages get their __init__.py so src/ is a regular tree
            if hasattr(module, "__path__"):
                rel = module_name.replace(".", "/") + "/__init__.py"
            else:
                rel = module_name.replace(".", "/") + ".py"
            add_bytes(f"src/{rel}", pathlib.Path(src_file).read_bytes())
            packaged.add(module_name)
            parts = module_name.split(".")[:-1]
            for i in range(1, len(parts) + 1):
                pkg = ".".join(parts[:i])
                if pkg in packaged:
                    continue
                pkg_mod = sys.modules.get(pkg)
                init_file = inspect.getsourcefile(pkg_mod) if pkg_mod else None
                data = pathlib.Path(init_file).read_bytes() if init_file else b""
                add_bytes("src/" + pkg.replace(".", "/") + "/__init__.py", data)
                packaged.add(pkg)
    return out


def load_archive(path: str | pathlib.Path, extract_to: str | pathlib.Path) -> dict[str, Any]:
    """Extract an archive and return its manifest; ``extract_to/src`` is
    importable (add it to sys.path to serve the packaged graph)."""
    dest = pathlib.Path(extract_to)
    dest.mkdir(parents=True, exist_ok=True)
    with tarfile.open(path, "r:gz") as tar:
        tar.extractall(dest, filter="data")
    manifest = json.loads((dest / "manifest.json").read_text())
    if int(manifest.get("schema", 0)) != 1:
        raise ValueError(f"unsupported archive schema {manifest.get('schema')!r}")
    return manifest
