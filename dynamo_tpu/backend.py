"""Backend (postprocessor) stage: tokens -> text, stop-string detection.

Sits on the response path between the engine and the preprocessor. For each
request it keeps an incremental detokenizer and a stop-string *jail*: text
that could still turn out to be the prefix of a stop string is held back and
only released once disambiguated — so clients never see a partial stop
sequence flash by, and never miss text when no stop fires.

On a stop-string hit the stream ends with ``FinishReason.STOP``, output
truncated at the match start (hidden stop, OpenAI semantics), and the
downstream engine stream is closed, which propagates cancellation to the
scheduler (transport teardown == kill).

Parity: reference `lib/llm/src/backend.rs:63-433` (Decoder/DecodeStream, stop
triggers, jail/unjail).
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import BackendOutput, EngineOutput, FinishReason, PreprocessedRequest
from dynamo_tpu.runtime.engine import AsyncEngine, Context, Operator
from dynamo_tpu.tokenizer import BaseTokenizer, IncrementalDetokenizer


class StopStringJail:
    """Streams text while withholding any suffix that may begin a stop string."""

    def __init__(self, stop_strings: list[str]) -> None:
        self._stops = [s for s in stop_strings if s]
        self._max_hold = max((len(s) - 1 for s in self._stops), default=0)
        self._pending = ""
        self.triggered: str | None = None

    def push(self, text: str) -> str:
        """Feed new text; return releasable text. Sets ``triggered`` on a hit."""
        if not self._stops:
            return text
        if self.triggered is not None:
            return ""
        self._pending += text
        # Full match anywhere in pending?
        earliest = -1
        for s in self._stops:
            idx = self._pending.find(s)
            if idx != -1 and (earliest == -1 or idx < earliest):
                earliest = idx
                self.triggered = s
        if self.triggered is not None:
            out = self._pending[:earliest]
            self._pending = ""
            return out
        # Hold back the longest tail that is a prefix of some stop string.
        hold = 0
        for k in range(min(self._max_hold, len(self._pending)), 0, -1):
            tail = self._pending[-k:]
            if any(s.startswith(tail) for s in self._stops):
                hold = k
                break
        out = self._pending[: len(self._pending) - hold]
        self._pending = self._pending[len(self._pending) - hold :]
        return out

    def flush(self) -> str:
        """Release anything still jailed (stream ended without a stop hit)."""
        out, self._pending = self._pending, ""
        return out


class Backend(Operator):
    """Operator: forwards PreprocessedRequest unchanged; detokenizes the
    response stream and enforces stop strings."""

    def __init__(self, downstream: AsyncEngine[Any, Any], tokenizer: BaseTokenizer) -> None:
        super().__init__(downstream)
        self.tokenizer = tokenizer

    async def transform_request(self, request: Any, context: Context) -> Any:
        return request

    def transform_stream(
        self, stream: AsyncIterator[Any], request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        if isinstance(request, dict):
            request = PreprocessedRequest.from_dict(request)
        return self._decode_stream(stream, request, context)

    async def _decode_stream(
        self, stream: AsyncIterator[Any], request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[BackendOutput]:
        detok = IncrementalDetokenizer(self.tokenizer)
        jail = StopStringJail(request.stop.stop_strings)
        async for item in stream:
            out = EngineOutput.from_dict(item) if isinstance(item, dict) else item
            if out.embedding is not None:  # embeddings: nothing to detokenize
                yield BackendOutput(
                    finish_reason=out.finish_reason,
                    prompt_tokens=out.prompt_tokens,
                    cached_tokens=out.cached_tokens,
                    embedding=out.embedding,
                )
                if out.finish_reason is not None:  # one output per batch input
                    return
                continue
            lp = None
            if out.logprobs:
                # Per-token text for the OpenAI logprobs schema. A lone token
                # may be a partial UTF-8 piece; "bytes" carries the exact
                # bytes (the schema's escape hatch for that).
                lp = []
                for e in out.logprobs:
                    piece = self.tokenizer.decode([e["id"]], skip_special_tokens=False)
                    lp.append({
                        **e, "token": piece, "bytes": list(piece.encode()),
                        "top": [
                            [tid, tlp, self.tokenizer.decode([tid], skip_special_tokens=False)]
                            for tid, tlp in e.get("top", [])
                        ],
                    })
            text = detok.push(out.token_ids) if out.token_ids else ""
            released = jail.push(text)
            if jail.triggered is not None:
                # Hidden stop: truncate, finish, and cancel the engine stream.
                yield BackendOutput(
                    text=released,
                    token_ids=out.token_ids,
                    finish_reason=FinishReason.STOP,
                    cumulative_tokens=out.cumulative_tokens,
                    prompt_tokens=out.prompt_tokens,
                    cached_tokens=out.cached_tokens,
                    logprobs=lp,
                    admission_wait_ms=out.admission_wait_ms,
                )
                return  # Operator.generate closes the stream -> engine cancels
            final = out.finish_reason is not None
            if final:
                released += jail.flush()
            if released or out.token_ids or final:
                yield BackendOutput(
                    text=released,
                    token_ids=out.token_ids,
                    finish_reason=out.finish_reason,
                    cumulative_tokens=out.cumulative_tokens,
                    prompt_tokens=out.prompt_tokens,
                    cached_tokens=out.cached_tokens,
                    logprobs=lp,
                    admission_wait_ms=out.admission_wait_ms,
                )
            if final:
                return
