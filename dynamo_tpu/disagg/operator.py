"""Disagg operator: the decode-worker stage that orchestrates remote prefill.

Sits in front of the decode engine service on the ``generate`` endpoint.
For each request: decide (DisaggRouter), enqueue a prefill task carrying our
transfer address, await KV injection, then hand the request to the ordinary
engine path — whose prefix match now hits the injected blocks. On transfer
timeout the request simply proceeds with local prefill (graceful
degradation; no wedged requests).

Parity: the decision + callback choreography of
`examples/llm/components/worker.py:190-229` without the block-id callback —
injection into the prefix cache replaces RemotePrefillParams entirely.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, AsyncIterator

import asyncio

from dynamo_tpu.disagg.queue import DistributedQueue
from dynamo_tpu.disagg.router import DisaggRouter
from dynamo_tpu.disagg.transfer import KvTransferService
from dynamo_tpu.engine.service import JaxEngineService
from dynamo_tpu.protocols.common import PreprocessedRequest
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)


class DisaggDecodeService(AsyncEngine[Any, dict]):
    def __init__(
        self,
        engine: JaxEngineService,
        transfer: KvTransferService,
        queue: DistributedQueue,
        router: DisaggRouter,
        transfer_address: str,
        *,
        transfer_timeout: float = 30.0,
    ) -> None:
        self.engine = engine
        self.transfer = transfer
        self.queue = queue
        self.router = router
        self.transfer_address = transfer_address
        self.transfer_timeout = transfer_timeout
        self.remote_prefills = 0
        self.local_prefills = 0

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(request) if isinstance(request, dict) else request
        if req.annotations.get("embed") or req.mm_inputs:
            # Embeddings build no KV; multimodal prompts carry image
            # embeddings the prefill queue task does not — both stay local.
            async for item in self.engine.generate(req, context):
                yield item
            return
        prefill_len = len(req.token_ids)
        # Length screen first: the common short-prompt path must not pay the
        # queue-depth store scans.
        go_remote = self.router.wants_remote(prefill_len)
        if go_remote:
            go_remote = self.router.prefill_remote(prefill_len, await self.queue.depth())
        if go_remote:
            from dynamo_tpu.tracing import Span, trace_of

            rid = req.request_id or uuid.uuid4().hex
            done = self.transfer.expect(rid)
            # The task carries the trace across the queue hop: spans on the
            # remote prefill worker parent under this wait span, and the
            # enqueue stamp lets the worker record the queue-wait gap.
            span = Span("remote_prefill", trace=trace_of(context), request_id=rid, tokens=prefill_len)
            with span:
                await self.queue.put(
                    {
                        "request_id": rid,
                        "token_ids": list(req.token_ids),
                        "transfer_address": self.transfer_address,
                        "trace": span.context.to_dict(),
                        "t_enqueue": time.time(),
                    }
                )
                try:
                    await asyncio.wait_for(done.wait(), timeout=self.transfer_timeout)
                    self.remote_prefills += 1
                except asyncio.TimeoutError:
                    logger.warning("remote prefill timed out for %s; prefilling locally", rid)
                    span.fields["timeout"] = True
                    self.local_prefills += 1
                finally:
                    self.transfer.forget(rid)
        else:
            self.local_prefills += 1
        async for item in self.engine.generate(req, context):
            yield item
