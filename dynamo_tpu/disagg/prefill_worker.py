"""Prefill worker: claims queue tasks, prefills, ships KV to decode workers.

The reference's `examples/llm/components/prefill_worker.py` role. The local
engine runs an ordinary 1-token generation (prefill + first decode step);
its committed pages are then read out and streamed to the requesting decode
worker's transfer endpoint. The sampled token is discarded — the decode side
recomputes the sub-page tail locally and samples there, so the transferred
artifact is pure KV.

The worker claims up to ``max_concurrency`` queue tasks at once, but that
bound applies to the *compute* phase only: the moment a task's local prefill
generation completes, its compute slot is released and the KV ship continues
under a separate ``ship_concurrency`` bound (``DYN_PREFILL_SHIP_CONCURRENCY``,
default ``2 * max_concurrency``). Ship-of-request-A therefore overlaps
prefill-of-request-B even when ``max_concurrency`` is 1 — the wire rides
under the next prompt's compute instead of serializing behind it. The engine
additionally chunks each prompt under the mixed-step scheduler
(engine/core.py), so overlapping tasks interleave their prefill chunks.
"""

from __future__ import annotations

import asyncio
import logging
import os

from dynamo_tpu.disagg.queue import DistributedQueue
from dynamo_tpu.disagg.transfer import (
    collect_prefill_blocks,
    send_blocks,
    send_blocks_chunked,
    send_pull_offer,
)
from dynamo_tpu.engine.service import JaxEngineService
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.tokens import compute_block_hashes

logger = logging.getLogger(__name__)

PREFILL_QUEUE = "prefill"


class PrefillWorker:
    def __init__(
        self,
        runtime: DistributedRuntime,
        service: JaxEngineService,
        *,
        queue_name: str = PREFILL_QUEUE,
        max_concurrency: int = 2,
        ship_concurrency: int | None = None,
    ) -> None:
        self.runtime = runtime
        self.service = service
        self.queue = DistributedQueue(runtime, queue_name)
        self._task: asyncio.Task | None = None
        # Compute-phase bound: held from claim until the local prefill
        # generation finishes (NOT until the ship completes — see _run_one).
        self._sem = asyncio.Semaphore(max(1, max_concurrency))
        if ship_concurrency is None:
            try:
                ship_concurrency = int(
                    os.environ.get("DYN_PREFILL_SHIP_CONCURRENCY", "")
                    or 2 * max(1, max_concurrency)
                )
            except ValueError:
                ship_concurrency = 2 * max(1, max_concurrency)
        # Ship-phase bound: caps in-flight KV transfers (each striped ship
        # holds host buffers for ~streams chunks) without tying up a compute
        # slot while bytes are on the wire.
        self._ship_sem = asyncio.Semaphore(max(1, ship_concurrency))
        self._inflight: set[asyncio.Task] = set()
        self.completed = 0

    async def start(self) -> "PrefillWorker":
        if self._task is None:
            self._task = asyncio.create_task(self._loop(), name="prefill-worker")
        return self

    async def _loop(self) -> None:
        while True:
            try:
                await self._sem.acquire()
                try:
                    claimed = await self.queue.claim(timeout=None)
                except BaseException:
                    self._sem.release()
                    raise
                if claimed is None:
                    self._sem.release()
                    continue
                t = asyncio.create_task(self._run_one(claimed), name="prefill-task")
                self._inflight.add(t)
                t.add_done_callback(self._inflight.discard)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prefill claim failed")
                await asyncio.sleep(0.2)

    async def _run_one(self, claimed: tuple) -> None:
        key, task = claimed
        # The compute slot frees as soon as the prefill generation is done
        # (callback invoked inside _prefill_and_ship) so the NEXT task's
        # prefill runs under THIS task's ship; the finally is the backstop
        # for failures before that point. Idempotent by construction.
        released = False

        def release_compute() -> None:
            nonlocal released
            if not released:
                released = True
                self._sem.release()

        try:
            await self._handle(task, release_compute)
            await self.queue.delete(key)
            self.completed += 1
        except asyncio.CancelledError:
            raise
        except Exception:
            # Release the claim so a *peer* reclaims the task immediately —
            # leaving it for our lease to expire would stall it a full TTL.
            logger.exception("prefill task failed; releasing claim for a peer to retry")
            try:
                await self.queue.release(key)
            except Exception:
                logger.exception("claim release failed; lease expiry will reclaim %s", key)
            await asyncio.sleep(0.2)
        finally:
            release_compute()

    async def _handle(self, task: dict, release_compute=lambda: None) -> None:
        import time

        from dynamo_tpu.tracing import Span, TraceContext, record_span

        token_ids = task["token_ids"]
        request_id = task["request_id"]
        # The decode side's remote_prefill span context rides the task dict;
        # everything this worker records links under it (one trace_id across
        # both processes). Untraced tasks get local root spans.
        trace = TraceContext.from_dict(task.get("trace"))
        t_enq = task.get("t_enqueue")
        if t_enq is not None:
            # Wall-clock gap (cross-process; clocks assumed NTP-close): how
            # long the task sat in the distributed queue before our claim.
            record_span(
                "prefill_queue_wait", max(0.0, (time.time() - float(t_enq)) * 1e3),
                trace=trace, request_id=request_id,
            )
        exec_span = Span("prefill_exec", trace=trace, request_id=request_id, tokens=len(token_ids))
        with exec_span:
            if FAULTS.armed:
                FAULTS.fire("prefill.exec")
            await self._prefill_and_ship(task, exec_span.context, release_compute)

    async def _prefill_and_ship(self, task: dict, trace, release_compute=lambda: None) -> None:
        token_ids = task["token_ids"]
        request_id = task["request_id"]
        page_size = self.service.core.config.page_size
        salt = self.service.core.config.salt
        # Ordinary 1-token generation: prefill fills + commits the prompt's
        # full pages into this worker's prefix cache.
        req = PreprocessedRequest(
            token_ids=token_ids,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=1, ignore_eos=True),
            request_id=request_id,
        )
        async for _ in self.service.generate(req, Context(request_id=request_id, trace=trace.to_dict())):
            pass
        # Compute done: free the slot so the next claimed task prefills while
        # this one's KV goes out under the ship bound.
        release_compute()
        hashes = compute_block_hashes(token_ids, page_size, salt=salt)
        async with self._ship_sem:
            await self._ship(task, trace, hashes)

    async def _ship(self, task: dict, trace, hashes: list[int]) -> None:
        token_ids = task["token_ids"]
        request_id = task["request_id"]

        # Co-located decode worker with matching cache geometry: move the
        # pages over the device path (gather -> device_put -> scatter; ICI
        # when chips differ). The TCP stream below is the cross-host (DCN)
        # fallback, also taken if the device path fails.
        from dynamo_tpu.disagg.device_transfer import REGISTRY, cache_compatible

        peer = REGISTRY.lookup(task["transfer_address"])
        if peer is not None and cache_compatible(self.service.core.runner, peer.core.runner):
            try:
                injected = await peer.inject_from(self.service.core, hashes, request_id)
            except Exception:
                logger.exception(
                    "prefill %s: device-path transfer failed, falling back to TCP", request_id
                )
            else:
                logger.info(
                    "prefill %s: %d tokens -> %d blocks via device path (%s)",
                    request_id, len(token_ids), injected, peer.stats(),
                )
                return

        # Cross-process device path: offer the chain for a transfer-engine
        # pull (jax.experimental.transfer — ICI/DCN, no host bounce). The
        # receiver's response tells us whether it could pull; any failure
        # falls through to the packed-bytes TCP stream below.
        try:
            result = await send_pull_offer(
                self.runtime.transport, task["transfer_address"], request_id,
                self.service.core, hashes,
            )
        except Exception:
            logger.exception("prefill %s: pull offer failed, falling back to TCP", request_id)
            result = None
        if result is not None:
            logger.info(
                "prefill %s: %d tokens -> %s blocks via cross-process device pull (%s)",
                request_id, len(token_ids), result.get("injected"), result.get("stats"),
            )
            return

        # Chunked TCP stream (wire v3 striped when the transport has a duplex
        # data plane, v2 single-stream otherwise): gather, pack and wire
        # pipelined per chunk, runner lock released between chunks. The
        # monolithic v1 collect-then-send below is the last-resort fallback.
        try:
            result = await send_blocks_chunked(
                self.runtime.transport, task["transfer_address"], request_id,
                self.service.core, hashes, trace=trace,
            )
        except Exception:
            logger.exception(
                "prefill %s: chunked stream failed, falling back to monolithic TCP", request_id
            )
        else:
            if result.get("total", 0) == 0:
                logger.warning("prefill %s produced no transferable blocks", request_id)
            logger.info(
                "prefill %s: %d tokens -> %s blocks streamed via wire %s x%s (%s injected, phases %s)",
                request_id, len(token_ids), result.get("total"),
                result.get("protocol", "v2"), result.get("streams", 1),
                result.get("injected"), result.get("phases"),
            )
            return

        loop = asyncio.get_running_loop()
        blocks = await loop.run_in_executor(None, collect_prefill_blocks, self.service.core, hashes)
        if not blocks:
            logger.warning("prefill %s produced no transferable blocks", request_id)
        result = await send_blocks(
            self.runtime.transport, task["transfer_address"], request_id, blocks,
            trace=trace, core=self.service.core,
        )
        logger.info(
            "prefill %s: %d tokens -> %d blocks shipped (%s injected)",
            request_id, len(token_ids), len(blocks), result.get("injected"),
        )

    async def drain(self, timeout: float = 30.0) -> bool:
        """Stop claiming new tasks and wait for in-flight prefills to finish
        (under ``timeout``). Returns True if everything completed."""
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._inflight:
            _done, pending = await asyncio.wait(list(self._inflight), timeout=timeout)
            return not pending
        return True

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in list(self._inflight):
            t.cancel()
        self._inflight.clear()
        await self.queue.close()
