"""Device-path KV transfer: cache pages move device->device, never via host.

The TCP transfer service (``disagg/transfer.py``) is the DCN fallback: pages
bounce device -> host -> msgpack -> host -> device. When the prefill and
decode engines live in the same process group (one host's chips, or one
slice), the pages can instead move as device arrays: one batched gather on
the source cache, a ``jax.device_put`` onto the destination's devices (XLA
routes it over ICI when source and destination differ; it never touches
Python), and one batched in-place scatter into the destination cache.

Every transfer records bytes and wall time; ``stats()`` exposes cumulative
GB/s — KV-transfer bandwidth is a tracked north-star metric (BASELINE.md).

Parity: the reference's NIXL RDMA put into remote block ids
(`lib/llm/src/block_manager/block/transfer/nixl.rs:86`) — here the RDMA role
is played by ICI DMA under ``device_put``, and the registry plays the
rendezvous role of NIXL metadata exchange (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import dataclasses
import time

import jax

from dynamo_tpu.engine.runner import ModelRunner, next_pow2

import numpy as np
import jax.numpy as jnp


def cache_compatible(a: ModelRunner, b: ModelRunner) -> bool:
    """Whether two runners' caches share page geometry (layers, page size,
    KV width) and dtype — the precondition for a raw device-path page copy.
    Runners without a device cache (the mocker) are never compatible; they
    take the host/TCP path."""
    ka, kb = getattr(a, "k_cache", None), getattr(b, "k_cache", None)
    if ka is None or kb is None:
        return False
    return (ka.shape[0], ka.shape[2], ka.shape[3], ka.dtype) == (
        kb.shape[0], kb.shape[2], kb.shape[3], kb.dtype
    )


@dataclasses.dataclass
class TransferStats:
    transfers: int = 0
    pages: int = 0
    bytes: int = 0
    seconds: float = 0.0

    @property
    def gbytes_per_sec(self) -> float:
        return (self.bytes / 1e9) / self.seconds if self.seconds > 0 else 0.0


class DeviceKvTransfer:
    """Moves KV pages between two runners' caches on the device path."""

    #: Pages per locked chunk. Each chunk holds both runners' io_locks for
    #: one gather->put->scatter; the locks RELEASE between chunks so a large
    #: prefix migration cannot stall either engine's decode loop for the
    #: whole transfer (VERDICT r3 weak #3; the reference bounds concurrent
    #: transfers off the hot path the same way, offload.rs:48-50). Safe
    #: because callers hold refcounts on both page sets for the duration —
    #: interleaved engine steps can't reuse them. Chunks also pin the
    #: gather/scatter to ONE compiled shape instead of pow2(n) variants.
    CHUNK_PAGES = 64

    def __init__(self) -> None:
        self.stats = TransferStats()

    def transfer(
        self,
        src: ModelRunner,
        src_pages: list[int],
        dst: ModelRunner,
        dst_pages: list[int],
        *,
        chunk_pages: int | None = None,
    ) -> TransferStats:
        """Copy ``src_pages`` of src's cache into ``dst_pages`` of dst's,
        in bounded-lock-hold chunks. Cache geometry (layers, page size,
        width) must match; the destination pages must already be allocated
        by dst's allocator and both page sets refcount-held by the caller.
        """
        assert len(src_pages) == len(dst_pages)
        chunk = chunk_pages or self.CHUNK_PAGES
        for off in range(0, len(src_pages), chunk):
            if off:
                # CPython lock handoff is unfair: without a real yield the
                # re-acquire below beats any decode step blocked on the
                # io_locks, and "releases between chunks" never actually
                # lets anyone in. Sleep outside the timed chunk, so stats
                # still measure pure copy.
                time.sleep(0.001)
            self._transfer_chunk(
                src, src_pages[off:off + chunk], dst, dst_pages[off:off + chunk]
            )
        return self.stats

    def _transfer_chunk(
        self,
        src: ModelRunner,
        src_pages: list[int],
        dst: ModelRunner,
        dst_pages: list[int],
    ) -> TransferStats:
        """One locked chunk: one gather -> one device_put -> one scatter."""
        if not src_pages:
            return self.stats
        n = len(src_pages)
        padded_n = next_pow2(n)
        src_ids = np.zeros(padded_n, np.int32)
        src_ids[:n] = src_pages
        # Padded slots scatter into the reserved null page 0, so the whole
        # padded buffer stays on device (no slice-and-restack host bounce).
        dst_ids = np.zeros(padded_n, np.int32)
        dst_ids[:n] = dst_pages
        # Both runners' caches are touched (src gathered, dst donated into),
        # each racing its own engine's in-flight steps — hold both io_locks,
        # in a stable order so opposed concurrent transfers can't deadlock.
        lock_a, lock_b = (
            (src.io_lock, dst.io_lock) if id(src) <= id(dst) else (dst.io_lock, src.io_lock)
        )
        with lock_a, lock_b:
            # Resharding device_put: each shard of the gathered pages lands
            # on the device that owns the matching shard of dst's cache (the
            # cache spec never shards the page axis, so it applies to
            # [L, N, ps, W] too). Single-device runners degenerate to a
            # plain placement.
            dst_sharding = dst.k_cache.sharding

            if padded_n not in src._devxfer_warm or padded_n not in dst._devxfer_warm:
                # Untimed warm-up into the null page: compiles the gather/
                # scatter kernels for this shape so the timed run below
                # measures the copy, not XLA compilation (bandwidth is a
                # tracked metric).
                kg, vg = src._gather_pages_fn(src.k_cache, src.v_cache, jnp.asarray(src_ids))
                dst.write_pages([0] * padded_n, jax.device_put(kg, dst_sharding), jax.device_put(vg, dst_sharding))
                jax.block_until_ready(dst.k_cache)
                src._devxfer_warm.add(padded_n)
                dst._devxfer_warm.add(padded_n)

            t0 = time.perf_counter()
            k_gath, v_gath = src._gather_pages_fn(src.k_cache, src.v_cache, jnp.asarray(src_ids))
            # Device->device: XLA moves the buffers over ICI (or aliases them
            # when src and dst share devices); the host never sees the bytes.
            k_dst = jax.device_put(k_gath, dst_sharding)
            v_dst = jax.device_put(v_gath, dst_sharding)
            dst.write_pages(list(dst_ids), k_dst, v_dst)
            jax.block_until_ready(dst.k_cache)
            dt = time.perf_counter() - t0

        # bytes per page = L * ps * W * itemsize, for K and V.
        page_bytes = src.k_cache.shape[0] * src.k_cache.shape[2] * src.k_cache.shape[3] * src.k_cache.itemsize
        moved = 2 * n * page_bytes
        self.stats.transfers += 1
        self.stats.pages += n
        self.stats.bytes += moved
        self.stats.seconds += dt
        return self.stats


class DeviceTransferRegistry:
    """In-process rendezvous: decode workers publish their transfer service
    under their (globally unique) transfer address, so a co-located prefill
    worker can take the device path instead of TCP.

    The registry is the process-local analogue of NIXL's metadata exchange:
    presence in the registry *is* reachability over the device path.
    """

    def __init__(self) -> None:
        self._services: dict[str, object] = {}  # transfer address -> KvTransferService

    def register(self, transfer_address: str, service) -> "RegistryHandle":
        self._services[transfer_address] = service
        return RegistryHandle(self, transfer_address)

    def unregister(self, transfer_address: str) -> None:
        self._services.pop(transfer_address, None)

    def lookup(self, transfer_address: str):
        return self._services.get(transfer_address)


class RegistryHandle:
    """Aux-closeable registration (unregisters with the owning service)."""

    def __init__(self, registry: DeviceTransferRegistry, address: str) -> None:
        self._registry = registry
        self._address = address

    async def close(self) -> None:
        self._registry.unregister(self._address)


# One registry per process (run_local topologies share it automatically).
REGISTRY = DeviceTransferRegistry()
