"""Distributed work queue over the discovery store (JetStream equivalent).

Tasks are records under ``queue/{name}/task/{seq:020d}``; claims are
lease-bound records under ``queue/{name}/claim/{seq}``. A worker claims the
oldest unclaimed task with an atomic ``put_if_absent``; if the worker dies,
its claim's lease expires, the claim key vanishes, and the task becomes
claimable again — at-least-once delivery with crash-safe reclaim, the same
guarantee the reference gets from JetStream acks (`utils/prefill_queue.py`,
`transports/nats.rs:345`).

Watch-driven: consumers block on the task-prefix watch rather than polling.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.discovery import WatchEventType

logger = logging.getLogger(__name__)


class DistributedQueue:
    def __init__(self, runtime: DistributedRuntime, name: str) -> None:
        import uuid

        self.runtime = runtime
        self.name = name
        self._seq = 0
        # Producer-unique suffix: several queue instances may share one
        # process/lease; keys must never collide (an overwrite loses a task).
        self._uid = uuid.uuid4().hex[:8]
        self._wake = asyncio.Event()
        self._watch_task: asyncio.Task | None = None
        #: Tasks this consumer claimed that some consumer had already
        #: delivered before (peer crash -> claim-lease expiry, or an explicit
        #: :meth:`release`). The redelivery count behind at-least-once.
        self.requeues = 0

    @property
    def task_prefix(self) -> str:
        return f"queue/{self.name}/task/"

    def _claim_key(self, task_key: str) -> str:
        return f"queue/{self.name}/claim/{task_key.rsplit('/', 1)[-1]}"

    def _delivered_key(self, task_key: str) -> str:
        return f"queue/{self.name}/delivered/{task_key.rsplit('/', 1)[-1]}"

    # -- producer ----------------------------------------------------------

    async def put(self, item: dict[str, Any], *, lease_bound: bool = False) -> str:
        """Enqueue a task; returns its key. ``lease_bound`` ties the task's
        lifetime to this process (use when the result is useless without us)."""
        lease_id = None
        if lease_bound:
            lease_id = (await self.runtime.primary_lease()).id
        self._seq += 1
        key = f"{self.task_prefix}{self._seq:012d}-{self._uid}"
        await self.runtime.store.put(key, json.dumps(item).encode(), lease_id=lease_id)
        return key

    async def delete(self, task_key: str) -> None:
        """Ack: remove a completed task (and its claim record)."""
        await self.runtime.store.delete(task_key)
        await self.runtime.store.delete(self._claim_key(task_key))
        await self.runtime.store.delete(self._delivered_key(task_key))

    async def release(self, task_key: str) -> None:
        """Give a claimed task back without acking: the claim record is
        dropped so a peer can reclaim *immediately*, instead of waiting out
        this process's lease TTL. Use on execution failure."""
        await self.runtime.store.delete(self._claim_key(task_key))
        self._wake.set()

    # -- consumer ----------------------------------------------------------

    async def _ensure_watch(self) -> None:
        if self._watch_task is None:
            self._watch_task = asyncio.create_task(self._watch())

    async def _watch(self) -> None:
        try:
            async for event in self.runtime.store.watch_prefix(self.task_prefix):
                if event.type is WatchEventType.PUT:
                    self._wake.set()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("queue watch failed: %s", self.name)

    async def claim(self, *, timeout: float | None = None) -> tuple[str, dict[str, Any]] | None:
        """Claim the oldest available task; blocks until one is available.

        Returns (task_key, item), or None on timeout. The claim is bound to
        this process's lease: call :meth:`delete` when done, or crash and let
        the claim expire for another worker to pick it up.
        """
        await self._ensure_watch()
        deadline = asyncio.get_event_loop().time() + timeout if timeout is not None else None
        lease = await self.runtime.primary_lease()
        while True:
            tasks = await self.runtime.store.get_prefix(self.task_prefix)
            for key in sorted(tasks):
                if await self.runtime.store.put_if_absent(self._claim_key(key), b"1", lease_id=lease.id):
                    # Task may have been deleted between scan and claim.
                    value = await self.runtime.store.get(key)
                    if value is None:
                        await self.runtime.store.delete(self._claim_key(key))
                        await self.runtime.store.delete(self._delivered_key(key))
                        continue
                    # Unleased delivery marker: if it already exists, another
                    # consumer delivered this task before us — a redelivery
                    # (its claim expired or it released the task).
                    if not await self.runtime.store.put_if_absent(self._delivered_key(key), b"1"):
                        self.requeues += 1
                        logger.warning("task %s redelivered (previous consumer failed)", key)
                    return key, json.loads(value)
            self._wake.clear()
            remaining = None if deadline is None else deadline - asyncio.get_event_loop().time()
            if remaining is not None and remaining <= 0:
                return None
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=min(remaining, 1.0) if remaining else 1.0)
            except asyncio.TimeoutError:
                pass  # rescan: claims may have expired

    async def depth(self) -> int:
        tasks = await self.runtime.store.get_prefix(self.task_prefix)
        claims = await self.runtime.store.get_prefix(f"queue/{self.name}/claim/")
        return max(0, len(tasks) - len(claims))

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
