"""Cross-process device-path KV pull transport.

The reference's NIXL writes KV blocks straight into a remote worker's GPU
memory (`lib/llm/src/block_manager/block/transfer/nixl.rs:86`). The TPU
equivalent is JAX's cross-slice transfer engine
(``jax.experimental.transfer``): the source stages device arrays under a
uuid on its ``TransferServer``; the destination connects to the source's
transfer address and *pulls* them — bytes move device-to-device over
ICI/DCN through the PJRT transfer engine, never through Python or the
host heap.

Protocol shape (sender-initiated, receiver-pulled):

1. The prefill worker gathers the chain's pages into stacked device arrays
   and ``offer()``s them under a fresh uuid.
2. It sends a *descriptor* (address, uuid, shapes, dtypes, hash chain) to
   the decode worker's ``kv_transfer`` endpoint — a tiny control message on
   the ordinary transport.
3. The decode worker allocates destination pages, ``pull()``s the arrays
   with its own cache sharding (the transfer engine delivers each shard to
   the device that owns it), scatters them into the paged cache, commits.
4. The response releases the sender's staged arrays.

Not every PJRT plugin implements the transfer-engine API (the CPU backend
and tunneled dev chips don't): :func:`device_pull_supported` probes once,
and senders fall back to the packed-bytes TCP path (``disagg/transfer.py``)
when either end lacks support — same fallback the reference takes when
NIXL is unavailable.
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Any, Sequence

logger = logging.getLogger(__name__)

_uuid_counter = itertools.count(1)
_lock = threading.Lock()


class JaxPullTransport:
    """``jax.experimental.transfer`` wrapper: one server + cached peer
    connections per process."""

    def __init__(self) -> None:
        self._server = None
        self._connections: dict[str, Any] = {}
        # Offered arrays are kept alive until acknowledged: the transfer
        # engine holds device buffers, but the Python references pin them
        # against donation/GC races on our side.
        self._offers: dict[int, Any] = {}

    def _ensure_server(self):
        if self._server is None:
            import jax
            from jax.experimental import transfer

            self._server = transfer.start_transfer_server(
                jax.local_devices()[0].client
            )
        return self._server

    def address(self) -> str:
        """This process's transfer address (host-reachable form)."""
        import socket

        addr = self._ensure_server().address()
        if addr.startswith("[::]"):
            addr = socket.gethostbyname(socket.gethostname()) + addr[4:]
        return addr

    def new_uuid(self) -> int:
        return next(_uuid_counter)

    def offer(self, uuid: int, arrays: Sequence[Any]) -> None:
        """Source side: stage device arrays for a remote pull."""
        server = self._ensure_server()
        with _lock:
            self._offers[uuid] = list(arrays)
        server.await_pull(uuid, list(arrays))

    #: How long a loopback drain may run before we stop waiting for it.
    DRAIN_TIMEOUT = 10.0

    def finish_offer(self, uuid: int, consumed: bool = True) -> None:
        """Release an offer. ``consumed=False`` means the receiver never
        pulled it — TransferServer has no cancel/deregister API (jax 0.9),
        and an un-pulled offer pins the staged device buffers forever, so we
        drain it ourselves with a loopback self-pull (the same mechanism the
        capability probe uses) to make the server release them.

        ``consumed`` is inferred from the receiver's phase-2 reply, which can
        be lost *after* a successful pull — in that case the drain would
        re-pull a consumed one-shot offer and block forever. The drain
        therefore runs on a daemon thread bounded by :attr:`DRAIN_TIMEOUT`:
        on timeout we give up and log the (possible) buffer leak instead of
        hanging the caller's executor thread (ADVICE r4)."""
        with _lock:
            arrays = self._offers.pop(uuid, None)
        if consumed or arrays is None:
            return

        def _drain() -> None:
            try:
                import jax

                specs = [
                    jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
                    for a in arrays
                ]
                for drained in self.pull(self.address(), uuid, specs):
                    drained.block_until_ready()
            except Exception as e:
                logger.warning("draining un-pulled offer %d failed: %s", uuid, e)

        t = threading.Thread(target=_drain, name=f"drain-offer-{uuid}", daemon=True)
        t.start()
        t.join(self.DRAIN_TIMEOUT)
        if t.is_alive():
            logger.warning(
                "drain of offer %d still blocked after %.0fs (receiver likely "
                "consumed it and the reply was lost); abandoning the drain — "
                "staged buffers may stay pinned until process exit", uuid,
                self.DRAIN_TIMEOUT,
            )

    def pull(self, address: str, uuid: int, specs: Sequence[Any]) -> list:
        """Destination side: fetch staged arrays device-path (blocking —
        call via run_in_executor). ``specs``: ShapeDtypeStructs carrying the
        *destination* sharding."""
        server = self._ensure_server()
        with _lock:
            conn = self._connections.get(address)
        if conn is None:
            conn = server.connect(address)
            with _lock:
                self._connections[address] = conn
        return conn.pull(uuid, list(specs))


_supported: bool | None = None
_transport: JaxPullTransport | None = None


def device_pull_supported() -> bool:
    """Whether this process's PJRT backend implements the transfer engine
    (probed once with a loopback self-pull of a tiny array)."""
    global _supported
    if _supported is None:
        try:
            import jax
            import jax.numpy as jnp

            t = get_transport()
            probe = jnp.zeros((8,), jnp.float32)
            uuid = t.new_uuid()
            t.offer(uuid, [probe])
            sds = jax.ShapeDtypeStruct(
                probe.shape, probe.dtype,
                sharding=jax.sharding.SingleDeviceSharding(jax.local_devices()[0]),
            )
            [back] = t.pull(t.address(), uuid, [sds])
            back.block_until_ready()
            t.finish_offer(uuid)
            _supported = True
        except Exception as e:  # UNIMPLEMENTED on cpu/tunneled backends
            logger.info("device pull transport unavailable (%s); TCP fallback", e)
            _supported = False
    return _supported


def get_transport() -> JaxPullTransport:
    """Process-wide transport (tests may substitute a stub via
    ``set_transport``)."""
    global _transport
    if _transport is None:
        _transport = JaxPullTransport()
    return _transport


def set_transport(transport, supported: bool | None = None) -> None:
    """Test seam: install a stub transport and force the capability probe."""
    global _transport, _supported
    _transport = transport
    _supported = supported
