"""KV block transfer: the prefill->decode migration path.

Decode workers serve a ``kv_transfer`` endpoint (`KvTransferService`).
A transfer request is a stream of block payloads — each a hash-chained,
complete page of KV for all layers — which the service writes into freshly
allocated pages and *commits to the local prefix cache*. From that moment
the blocks are indistinguishable from locally-computed cache: admission
matches them, KV events announce them, eviction can offload them to tiers.

Wire format per block (msgpack-native, no base64):
  {"hash": int, "parent": int|None, "tokens": [int], "k": bytes, "v": bytes,
   "shape": [L, ps, kv, hd], "dtype": str}

Two framings carry those blocks (docs/KV_TRANSFER_WIRE_V2.md):

- v1 (monolithic): one ``{"request_id", "blocks": [...]}`` message with the
  whole chain — collect-then-send, retained as the last-resort fallback.
- v2 (streaming): a sequence of ``{"request_id", "seq", "blocks", "last"}``
  chunk messages. The sender (:func:`send_blocks_chunked`) pipelines them:
  chunk N+1's device gather + D2H copy is dispatched (``read_pages_async``)
  before chunk N is packed and sent, so gather, pack and wire overlap and
  the runner lock releases between chunks. The receiver scatters each chunk
  with one batched ``write_pages``, commits it incrementally (every prefix
  of the hash chain is a valid cache state) while holding refcounts so a
  later chunk's allocations can't evict the chain, and rolls back staging
  on mid-stream failure or sender death.
- v3 (striped): the same chunks split round-robin across a pool of
  ``DYN_KV_WIRE_STREAMS`` persistent duplex connections, each chunk a raw
  blob frame (msgpack header + raw k/v bytes, no per-block msgpack copies).
  The receiver reassembles out-of-order arrivals under a host-staging byte
  budget (``DYN_KV_WIRE_INFLIGHT``) and commits strictly in seq order, so
  v2's incremental commit/rollback and per-chunk crc-retry semantics carry
  over exactly. Falls back to v2 when the transport or the receiver has no
  duplex data plane.

Completion notifications resolve per-request futures so the disagg operator
holding the original request knows when injection is done.

Parity: replaces the reference's NIXL RDMA block writes
(`block_manager/block/transfer/nixl.rs`, vLLM patch in SURVEY.md §3C) with a
receiver-driven stream over the runtime's data plane — the DCN path. Workers
sharing a host/slice can short-circuit with device-to-device copies; that
fast path rides the same interface.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import threading
import time
import zlib
from typing import Any, AsyncIterator

import numpy as np

from dynamo_tpu.engine.allocator import OutOfPagesError
from dynamo_tpu.engine.core import EngineCore
from dynamo_tpu.observability.metrics import observe_kv_phase
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.faults import FAULTS, corrupt_bytes
from dynamo_tpu.runtime.transport import DuplexUnsupportedError, Transport
from dynamo_tpu.tracing import TraceContext, record_span

logger = logging.getLogger(__name__)

KV_TRANSFER_ENDPOINT = "kv_transfer"

#: Pages per streamed chunk — the same bounded-lock-hold sizing as
#: ``device_transfer.DeviceKvTransfer.CHUNK_PAGES``: each chunk's gather
#: holds the sender's io_lock for one dispatch only, and each chunk is one
#: compiled pow2 shape, so a long chain costs a handful of programs and the
#: engines' decode loops interleave with an in-flight transfer.
#: Overridable end-to-end with ``DYN_KV_CHUNK_PAGES``.
CHUNK_PAGES = 64


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_chunk_pages() -> int:
    """Pages per streamed chunk; ``DYN_KV_CHUNK_PAGES`` overrides."""
    return max(1, _env_int("DYN_KV_CHUNK_PAGES", CHUNK_PAGES))


def default_wire_streams() -> int:
    """Striped data-plane connections per transfer (wire v3).

    ``DYN_KV_WIRE_STREAMS`` overrides; 0 pins the legacy single-stream v2
    protocol (per-chunk request/response round trips)."""
    return max(0, _env_int("DYN_KV_WIRE_STREAMS", 4))


def staging_budget_bytes() -> int:
    """Receiver-side host bytes allowed in out-of-order reassembly staging
    across ALL in-flight sessions; ``DYN_KV_WIRE_INFLIGHT`` overrides.
    In-order chunks are always admitted, so the budget bounds memory without
    ever blocking stream progress."""
    return max(1, _env_int("DYN_KV_WIRE_INFLIGHT", 256 * 1024 * 1024))


class _PhaseClock:
    """Busy-interval union across parallel streams.

    ``total`` accumulates wall time during which *at least one* stream was
    inside the phase — per-stream-attributed wall time, never a sum over
    concurrent streams. This keeps the overlap-is-real invariant (phase sums
    exceeding end-to-end time measure genuine overlap) meaningful for the
    striped sender, where four stripes on the wire at once must count as one
    second per second. Thread-safe: pack runs on executor threads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._busy = 0
        self._t0 = 0.0
        self.total = 0.0

    def enter(self) -> None:
        with self._lock:
            if self._busy == 0:
                self._t0 = time.perf_counter()
            self._busy += 1

    def exit(self) -> None:
        with self._lock:
            self._busy -= 1
            if self._busy == 0:
                self.total += time.perf_counter() - self._t0


@dataclasses.dataclass
class _StreamSession:
    """Receiver-side state of one in-flight chunk stream (wire v2 or v3).

    ``pinned`` holds refcounts on every block of the chain ingested so far
    (cache hits AND incrementally-committed chunks): a later chunk's
    allocations must not be able to evict the chain prefix mid-stream. The
    refcounts drop when the stream ends — on the ``last`` chunk, an abort,
    an error, or the abandoned-stream sweep.

    Wire v3 adds out-of-order reassembly: stripes deliver chunks in any
    order, ``staging`` parks arrivals ahead of ``next_seq`` (bounded by the
    service-wide staging budget), and a per-session pump task commits them
    strictly in seq order — so the v2 invariant that every committed prefix
    is a valid cache state is untouched. Acks are deferred until commit;
    ``acks``/``wake`` hand them back to the stripe handler that parked.
    """

    next_seq: int = 0
    pinned: list[int] = dataclasses.field(default_factory=list)
    injected: int = 0
    total_blocks: int = 0
    #: Pool exhaustion truncated the chain: later chunks are acknowledged
    #: but not ingested (their parents are missing — committing them would
    #: publish unreachable blocks).
    truncated: bool = False
    t_last: float = dataclasses.field(default_factory=time.monotonic)
    # -- wire v3 (striped) state ------------------------------------------
    sid: str = ""  # sender-chosen stream id: stripes of one transfer attach
    stripes: int = 1
    total_chunks: int | None = None  # None = v2 session (total from "last")
    conns: int = 0  # open stripe connections feeding this session
    dead: bool = False
    bytes: int = 0
    staging: dict[int, tuple[list[dict], int]] = dataclasses.field(default_factory=dict)
    staged_bytes: int = 0
    acks: dict[int, dict] = dataclasses.field(default_factory=dict)
    wake: asyncio.Event = dataclasses.field(default_factory=asyncio.Event)
    pump: asyncio.Task | None = None
    #: Sender's trace context (from the stream_open request): v3 meta blocks
    #: don't carry per-block trace dicts, so receiver-side spans link here.
    trace: dict | None = None

    def pulse(self) -> None:
        """Wake everything parked on this session (generation-event idiom:
        waiters grab ``wake`` before re-checking their predicate)."""
        ev = self.wake
        self.wake = asyncio.Event()
        ev.set()


def pack_block(block_hash: int, parent_hash: int | None, tokens: list[int], k: np.ndarray, v: np.ndarray) -> dict:
    kb = np.ascontiguousarray(k).tobytes()
    vb = np.ascontiguousarray(v).tobytes()
    return {
        "hash": block_hash,
        "parent": parent_hash,
        "tokens": list(tokens),
        "k": kb,
        "v": vb,
        "shape": list(k.shape),
        "dtype": str(k.dtype),
        # End-to-end payload integrity: verified receiver-side before the
        # scatter (msgpack/TCP don't checksum application payloads for us).
        "crc": zlib.crc32(vb, zlib.crc32(kb)),
    }


def block_crc_ok(blk: dict) -> bool:
    """Verify a packed block's crc32. Blocks without one (older senders)
    pass — the check is opt-in by wire format, not a protocol break."""
    crc = blk.get("crc")
    if crc is None:
        return True
    return zlib.crc32(blk["v"], zlib.crc32(blk["k"])) == crc


def unpack_payload(msg: dict) -> tuple[np.ndarray, np.ndarray]:
    shape = tuple(msg["shape"])
    dtype = np.dtype(msg["dtype"])
    k = np.frombuffer(msg["k"], dtype=dtype).reshape(shape)
    v = np.frombuffer(msg["v"], dtype=dtype).reshape(shape)
    return k, v


def pack_chunk_blob(
    hashes: list[int], parents: list[int | None], payloads, clock: _PhaseClock | None = None
) -> tuple[list[dict], list[memoryview], int]:
    """Wire v3 framing: per-block *metadata* only (msgpack head) plus the raw
    k/v buffers as zero-copy memoryviews for the blob body — no ``tobytes``
    and no per-block msgpack of payload bytes (that was v2's pack_s)."""
    if clock is not None:
        clock.enter()
    try:
        meta: list[dict] = []
        bufs: list[memoryview] = []
        nbytes = 0
        for i, (k, v) in enumerate(payloads):
            k = np.ascontiguousarray(k)
            v = np.ascontiguousarray(v)
            shape, dtype = list(k.shape), str(k.dtype)
            # Byte-view before memoryview: extension dtypes (bfloat16 et al)
            # have no buffer-protocol format char, but their bytes do.
            kb = memoryview(k.view(np.uint8).reshape(-1))
            vb = memoryview(v.view(np.uint8).reshape(-1))
            meta.append({
                "hash": hashes[i],
                "parent": parents[i],
                "tokens": [],
                "shape": shape,
                "dtype": dtype,
                "k_len": kb.nbytes,
                "v_len": vb.nbytes,
                "crc": zlib.crc32(vb, zlib.crc32(kb)),
            })
            bufs.extend((kb, vb))
            nbytes += kb.nbytes + vb.nbytes
        return meta, bufs, nbytes
    finally:
        if clock is not None:
            clock.exit()


def blob_to_blocks(meta: list[dict], blob) -> list[dict]:
    """Slice a chunk's blob body back into v2-shaped block dicts (memoryview
    k/v, so crc verify / unpack / scatter reuse the v2 receiver unchanged)."""
    mv = memoryview(blob)
    off = 0
    out: list[dict] = []
    for m in meta:
        blk = dict(m)
        blk["k"] = mv[off:off + m["k_len"]]
        off += m["k_len"]
        blk["v"] = mv[off:off + m["v_len"]]
        off += m["v_len"]
        out.append(blk)
    if off != len(mv):
        raise ValueError(f"blob length mismatch: meta declares {off}, body has {len(mv)}")
    return out


class KvTransferService(AsyncEngine[Any, dict]):
    """Served by decode workers: ingests KV blocks into the local cache.

    Two ingestion paths share this service: the TCP stream below (DCN
    fallback, host-bounced) and :meth:`inject_from` (device path — pages
    move src-device -> dst-device through ``disagg/device_transfer.py``
    without touching the host). Both record bytes/seconds; ``stats()``
    reports cumulative bandwidth, a tracked metric (BASELINE.md).
    """

    #: Staged pull state older than this is assumed abandoned (sender died
    #: between phases) and rolled back on the next service interaction.
    PENDING_PULL_MAX_AGE = 120.0

    def __init__(self, core: EngineCore) -> None:
        self.core = core
        self._completions: dict[str, asyncio.Event] = {}
        # request_id -> (pinned, staged, parents, t_monotonic): pages staged
        # by a pull_query, awaiting the matching pull (two-phase protocol).
        self._pending_pulls: dict[str, tuple[list[int], list, list, float]] = {}
        # request_id -> in-flight chunk stream (wire protocol v2 or v3).
        self._streams: dict[str, _StreamSession] = {}
        self._sweeper: asyncio.Task | None = None
        self.blocks_received = 0
        self.bytes_received = 0
        self.transfer_seconds = 0.0
        self.scatter_seconds = 0.0
        self.device_path_blocks = 0
        self.crc_failures = 0
        self.rollbacks = 0
        # Which path served each completed transfer (ISSUE 8 tentpole #4):
        # device_colocated / device_pull / host_striped / host_chunked /
        # host_monolithic -> {"transfers", "bytes"}.
        self.path_stats: dict[str, dict[str, int]] = {}
        # Wire v3: service-wide out-of-order staging budget + accounting.
        self._staging_budget = staging_budget_bytes()
        self._staged_bytes = 0
        self._wire_conns = 0  # open striped data-plane connections
        self._wake = asyncio.Event()  # pulsed when staging bytes are freed

    def _record_path(self, path: str, nbytes: int) -> None:
        d = self.path_stats.setdefault(path, {"transfers": 0, "bytes": 0})
        d["transfers"] += 1
        d["bytes"] += nbytes

    def _pulse_budget(self) -> None:
        ev = self._wake
        self._wake = asyncio.Event()
        ev.set()

    def start_sweeper(self, interval: float | None = None) -> "KvTransferService":
        """Run :meth:`_sweep_pending_pulls` on a timer, so staging abandoned
        by a dead sender is reclaimed even when no further transfer traffic
        arrives (the in-band sweep in :meth:`generate` only fires on
        interaction — ADVICE r4). Returns self so callers can register it
        for ``close()``."""
        interval = interval or self.PENDING_PULL_MAX_AGE / 4

        async def _loop() -> None:
            while True:
                await asyncio.sleep(interval)
                try:
                    self._sweep_pending_pulls()
                except Exception:
                    # A sweep failure must not kill the task (or surface as a
                    # stale exception out of close()) — the next tick retries.
                    logger.exception("pending-pull sweep failed")

        if self._sweeper is None:
            self._sweeper = asyncio.create_task(_loop(), name="kv-transfer-sweeper")
        return self

    async def close(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            try:
                await self._sweeper
            except asyncio.CancelledError:
                pass
            self._sweeper = None

    def stats(self) -> dict:
        gbps = (self.bytes_received / 1e9) / self.transfer_seconds if self.transfer_seconds else 0.0
        return {
            "blocks": self.blocks_received,
            "device_path_blocks": self.device_path_blocks,
            "bytes": self.bytes_received,
            "seconds": round(self.transfer_seconds, 6),
            "scatter_s": round(self.scatter_seconds, 6),
            "streams_in_flight": len(self._streams),
            "gbytes_per_sec": round(gbps, 6),
            "crc_failures": self.crc_failures,
            "rollbacks": self.rollbacks,
            "wire_conns": self._wire_conns,
            "staged_bytes": self._staged_bytes,
            "paths": {p: dict(d) for p, d in self.path_stats.items()},
        }

    # -- staging (shared by the TCP and device ingestion paths) ------------

    def _stage_chain(self, items) -> tuple[list[int], list[tuple[int, int, Any]]]:
        """Pin already-present blocks; allocate a destination page per miss.

        ``items``: (block_hash, payload) in chain order; stops at pool
        exhaustion. Returns ``(pinned_hits, staged)`` with staged =
        ``[(dst_pid, block_hash, payload), ...]``. Hits are *pinned*
        (refcount++) so the allocations here can't evict them mid-chain —
        the caller must release them. Staged pages are uncommitted
        (refcount 1): finish with :meth:`_commit_staged` or roll back with
        :meth:`_release_staged`.
        """
        alloc = self.core.allocator
        pinned: list[int] = []
        staged: list[tuple[int, int, Any]] = []
        for h, payload in items:
            hit = alloc.acquire_cached(h)  # already have it (races are benign)
            if hit is not None:
                pinned.append(hit)
                continue
            try:
                [pid] = alloc.allocate(1)
            except OutOfPagesError:
                logger.warning("kv injection out of pages after %d blocks", len(staged))
                break
            staged.append((pid, h, payload))
        return pinned, staged

    def _commit_staged(self, entries) -> None:
        """``entries``: (dst_pid, hash, parent_hash, tokens) — publish each
        written page to the prefix cache and drop the staging refcount."""
        alloc = self.core.allocator
        for pid, h, parent, tokens in entries:
            alloc.commit(pid, h, parent, tokens)
            alloc.release([pid])  # refcount 0: lives as prefix cache
            self.blocks_received += 1

    def _release_staged(self, staged) -> None:
        # Uncommitted pages: release returns them to the free list instead
        # of stranding them at refcount 1 forever.
        self.core.allocator.release([pid for pid, _h, _p in staged])

    async def inject_from(self, src_core: EngineCore, block_hashes: list[int], request_id: str = "") -> int:
        """Device-path injection: pull the hash chain's pages straight from a
        co-located engine's cache over the device interconnect.

        Returns the number of chain blocks now present at the destination
        (already-cached hits + freshly transferred). On a transfer failure
        the staged destination pages are released and the error propagates —
        the caller falls back to the TCP path.
        """
        from dynamo_tpu.disagg.device_transfer import DeviceKvTransfer

        src_alloc = src_core.allocator
        src_pages = src_alloc.match_prefix(block_hashes)  # acquires refcounts
        pinned: list[int] = []
        staged: list[tuple[int, int, Any]] = []  # payload = source page id
        try:
            pinned, staged = self._stage_chain(
                (block_hashes[i], src_pid) for i, src_pid in enumerate(src_pages)
            )
            if staged:
                xfer = DeviceKvTransfer()
                loop = asyncio.get_running_loop()
                try:
                    await loop.run_in_executor(
                        None, xfer.transfer,
                        src_core.runner, [src_pid for _pid, _h, src_pid in staged],
                        self.core.runner, [pid for pid, _h, _s in staged],
                    )
                except Exception:
                    self._release_staged(staged)
                    staged = []
                    raise
                self._commit_staged(
                    (pid, h, src_alloc.page_parent_hash(src_pid), ())
                    for pid, h, src_pid in staged
                )
                self.transfer_seconds += xfer.stats.seconds
                self.bytes_received += xfer.stats.bytes
                self.device_path_blocks += len(staged)
                self._record_path("device_colocated", xfer.stats.bytes)
        finally:
            self.core.allocator.release(pinned)
            src_alloc.release(src_pages)
        ev = self._completions.get(request_id)
        if ev is not None:
            ev.set()
        return len(pinned) + len(staged)

    def _abort_pull(self, request_id: str) -> None:
        """Roll back pages staged by a pull_query whose pull never arrived."""
        pending = self._pending_pulls.pop(request_id, None)
        if pending is None:
            return
        pinned, staged, _parents, _t0 = pending
        self._release_staged(staged)
        self.core.allocator.release(pinned)

    def _sweep_pending_pulls(self) -> None:
        now = time.monotonic()
        for rid in [
            rid for rid, (_p, _s, _pa, t0) in self._pending_pulls.items()
            if now - t0 > self.PENDING_PULL_MAX_AGE
        ]:
            logger.warning("abandoned pull staging for %s rolled back", rid)
            self._abort_pull(rid)
        for rid in [
            rid for rid, sess in self._streams.items()
            if now - sess.t_last > self.PENDING_PULL_MAX_AGE
        ]:
            logger.warning("abandoned chunk stream for %s rolled back", rid)
            self._abort_stream(rid)

    # -- wire protocol v2: streaming chunk ingestion -----------------------

    def _abort_stream(self, request_id: str) -> None:
        """Drop a chunk stream's session and its chain refcounts.

        Blocks committed by earlier chunks STAY in the prefix cache — an
        incremental commit only ever publishes a valid, chain-consistent
        prefix — but releasing the pins makes them ordinary evictable cache
        again, so a dead sender reclaims to a clean allocator state.
        """
        sess = self._streams.pop(request_id, None)
        if sess is None:
            return
        self.rollbacks += 1
        self.core.allocator.release(sess.pinned)
        # Wire v3: drop out-of-order staging, return its budget share, and
        # wake every stripe handler parked on a deferred ack or the pump.
        sess.dead = True
        if sess.staged_bytes:
            self._staged_bytes -= sess.staged_bytes
            sess.staging.clear()
            sess.staged_bytes = 0
        self._pulse_budget()
        sess.pulse()

    async def _ingest_chunk(self, request_id: str, request: dict) -> dict:
        """One v2 chunk: stage, scatter (one batched ``write_pages``), and
        commit incrementally, keeping the whole chain pinned until ``last``.

        Any failure rolls the stream back (:meth:`_abort_stream`): the
        uncommitted staged pages return to the free pool and the response's
        ``stream_error`` tells the sender to fall back to the monolithic
        path. Out-of-order or unknown ``seq`` is a protocol error and also
        aborts — a reconnecting sender restarts at seq 0, which replaces
        any stale session for the same request id.
        """
        if FAULTS.armed:
            FAULTS.fire("kv.chunk.recv")
        seq = int(request.get("seq", 0))
        last = bool(request.get("last"))
        blocks = request.get("blocks", [])
        sess = self._streams.get(request_id)
        if seq == 0:
            if sess is not None:
                if sess.next_seq == 0 and not sess.pinned:
                    # crc-retry of the very first chunk: the session never
                    # ingested anything, so replacing it is not a rollback.
                    self._streams.pop(request_id, None)
                else:
                    self._abort_stream(request_id)
            sess = _StreamSession()
            self._streams[request_id] = sess
        if sess is None or seq != sess.next_seq:
            self._abort_stream(request_id)
            return {
                "request_id": request_id, "seq": seq,
                "stream_error": f"unexpected seq {seq}"
                + (f" (want {sess.next_seq})" if sess else " (no session)"),
            }
        bad = sum(1 for blk in blocks if not block_crc_ok(blk))
        if bad:
            # Corruption is retryable, not fatal: the session is untouched
            # (next_seq unchanged) so the sender can re-send this exact seq.
            self.crc_failures += bad
            sess.t_last = time.monotonic()
            logger.warning(
                "kv chunk crc mismatch (req=%s seq=%d, %d/%d blocks); asking sender to retry",
                request_id, seq, bad, len(blocks),
            )
            return {"request_id": request_id, "seq": seq, "crc_error": True, "bad_blocks": bad}
        t0 = time.perf_counter()
        staged: list[tuple[int, int, Any]] = []
        try:
            sess.total_blocks += len(blocks)
            if not sess.truncated and blocks:
                pinned, staged = self._stage_chain((blk["hash"], blk) for blk in blocks)
                sess.pinned.extend(pinned)
                if len(pinned) + len(staged) < len(blocks):
                    sess.truncated = True  # pool exhausted: drop the tail
                if staged:
                    payloads = [unpack_payload(blk) for _pid, _h, blk in staged]
                    t_sc = time.perf_counter()
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.core.runner.write_pages,
                        [pid for pid, _h, _b in staged],
                        [k for k, _ in payloads], [v for _, v in payloads],
                    )
                    dt_sc = time.perf_counter() - t_sc
                    self.scatter_seconds += dt_sc
                    observe_kv_phase("scatter", dt_sc, core=self.core)
                    # Receiver-side phase span, linked into the sender's
                    # trace when the chunk carries one.
                    record_span(
                        "kv_scatter", dt_sc * 1e3,
                        trace=TraceContext.from_dict(request.get("trace")),
                        request_id=request_id, seq=seq, blocks=len(staged),
                    )
                    alloc = self.core.allocator
                    for pid, h, blk in staged:
                        # Incremental commit: publish, but KEEP the staging
                        # refcount as the session's pin (released at stream
                        # end) so later chunks can't evict the chain prefix.
                        alloc.commit(pid, h, blk.get("parent"), tuple(blk.get("tokens", ())))
                        sess.pinned.append(pid)
                        self.blocks_received += 1
                    chunk_bytes = sum(k.nbytes + v.nbytes for k, v in payloads)
                    self.bytes_received += chunk_bytes
                    sess.bytes += chunk_bytes
                sess.injected += len(pinned) + len(staged)
            self.transfer_seconds += time.perf_counter() - t0
        except Exception:
            self._release_staged(staged)
            self._abort_stream(request_id)
            logger.exception(
                "kv chunk ingestion failed (req=%s seq=%d); stream rolled back",
                request_id, seq,
            )
            return {"request_id": request_id, "seq": seq, "stream_error": "ingestion failed"}
        sess.next_seq = seq + 1
        sess.t_last = time.monotonic()
        summary = {"request_id": request_id, "seq": seq, "injected": sess.injected, "last": last}
        if last:
            self._streams.pop(request_id, None)
            self.core.allocator.release(sess.pinned)
            self._record_path("host_chunked", sess.bytes)
            summary["total"] = sess.total_blocks
            summary["stats"] = self.stats()
            ev = self._completions.get(request_id)
            if ev is not None:
                ev.set()
        return summary

    # -- wire protocol v3: striped duplex ingestion ------------------------

    def _attach_striped(self, request_id: str, request: dict) -> _StreamSession | None:
        """Attach a stripe connection to its session, creating it on first
        arrival. Stripes of one transfer share a sender-chosen ``sid``; a
        different sid means a retry/new attempt and replaces any stale
        session (rolling it back iff it had ingested anything, mirroring the
        v2 seq-0 rule)."""
        sid = str(request.get("sid", ""))
        total = int(request.get("total_chunks", 0))
        if not sid or total <= 0:
            return None
        sess = self._streams.get(request_id)
        if sess is not None and sess.sid == sid and not sess.dead:
            return sess
        if sess is not None:
            if sess.next_seq == 0 and not sess.pinned:
                self._streams.pop(request_id, None)
                sess.dead = True
                sess.pulse()
            else:
                self._abort_stream(request_id)
        sess = _StreamSession(
            sid=sid, stripes=int(request.get("stripes", 1)), total_chunks=total,
            trace=request.get("trace"),
        )
        self._streams[request_id] = sess
        sess.pump = asyncio.create_task(
            self._striped_pump(request_id, sess), name=f"kv-stripe-pump-{request_id}"
        )
        return sess

    async def _striped_pump(self, request_id: str, sess: _StreamSession) -> None:
        """Per-session reassembly pump: commits staged chunks strictly in seq
        order, so the incremental-commit invariant (every committed prefix is
        a valid cache state) is exactly v2's. Each commit publishes its ack
        into ``sess.acks`` and pulses the stripe handler that parked on it."""
        total = sess.total_chunks or 0
        try:
            while not sess.dead and sess.next_seq < total:
                # Grab the generation event BEFORE checking state: a pulse
                # between check and wait replaces the event, and waiting on
                # the replacement would miss it.
                ev = sess.wake
                entry = sess.staging.pop(sess.next_seq, None)
                if entry is None:
                    await ev.wait()
                    continue
                blocks, nbytes = entry
                sess.staged_bytes -= nbytes
                self._staged_bytes -= nbytes
                self._pulse_budget()
                seq = sess.next_seq
                ack = await self._commit_striped_chunk(request_id, sess, seq, blocks, nbytes)
                sess.acks[seq] = ack
                sess.pulse()
                # The commit advanced the cursor: stripes parked on the
                # budget whose seq is now <= next_seq must re-check (their
                # admission no longer needs budget headroom).
                self._pulse_budget()
                if ack.get("stream_error"):
                    return
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("kv stripe pump failed (req=%s); stream rolled back", request_id)
            if self._streams.get(request_id) is sess:
                self._abort_stream(request_id)

    async def _commit_striped_chunk(
        self, request_id: str, sess: _StreamSession, seq: int, blocks: list[dict], nbytes: int
    ) -> dict:
        """Scatter + incrementally commit one in-seq-order chunk — the v2
        ``_ingest_chunk`` body on v3-framed blocks. Returns the chunk's ack;
        the final chunk's ack carries the stream summary."""
        total = sess.total_chunks or 0
        t0 = time.perf_counter()
        staged: list[tuple[int, int, Any]] = []
        try:
            sess.total_blocks += len(blocks)
            if not sess.truncated and blocks:
                pinned, staged = self._stage_chain((blk["hash"], blk) for blk in blocks)
                sess.pinned.extend(pinned)
                if len(pinned) + len(staged) < len(blocks):
                    sess.truncated = True  # pool exhausted: drop the tail
                if staged:
                    payloads = [unpack_payload(blk) for _pid, _h, blk in staged]
                    t_sc = time.perf_counter()
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.core.runner.write_pages,
                        [pid for pid, _h, _b in staged],
                        [k for k, _ in payloads], [v for _, v in payloads],
                    )
                    dt_sc = time.perf_counter() - t_sc
                    self.scatter_seconds += dt_sc
                    observe_kv_phase("scatter", dt_sc, core=self.core)
                    record_span(
                        "kv_scatter", dt_sc * 1e3,
                        trace=TraceContext.from_dict(sess.trace),
                        request_id=request_id, seq=seq, blocks=len(staged),
                    )
                    alloc = self.core.allocator
                    for pid, h, blk in staged:
                        alloc.commit(pid, h, blk.get("parent"), tuple(blk.get("tokens", ())))
                        sess.pinned.append(pid)
                        self.blocks_received += 1
                    chunk_bytes = sum(k.nbytes + v.nbytes for k, v in payloads)
                    self.bytes_received += chunk_bytes
                    sess.bytes += chunk_bytes
                sess.injected += len(pinned) + len(staged)
            self.transfer_seconds += time.perf_counter() - t0
        except Exception:
            self._release_staged(staged)
            if self._streams.get(request_id) is sess:
                self._abort_stream(request_id)
            logger.exception(
                "kv striped chunk ingestion failed (req=%s seq=%d); stream rolled back",
                request_id, seq,
            )
            return {"request_id": request_id, "seq": seq, "stream_error": "ingestion failed"}
        sess.next_seq = seq + 1
        sess.t_last = time.monotonic()
        ack = {"request_id": request_id, "seq": seq, "injected": sess.injected,
               "last": seq == total - 1}
        if seq == total - 1:
            self._streams.pop(request_id, None)
            self.core.allocator.release(sess.pinned)
            self._record_path("host_striped", sess.bytes)
            ack["total"] = sess.total_blocks
            ack["stats"] = self.stats()
            ev = self._completions.get(request_id)
            if ev is not None:
                ev.set()
        return ack

    async def _ingest_striped_chunk(self, request_id: str, sess: _StreamSession, msg: dict) -> dict:
        """One stripe arrival: crc-verify, admit (staging out-of-order chunks
        under the service-wide byte budget; in-seq chunks are always admitted
        so the stream can't deadlock on its own backpressure), then park
        until the pump commits this seq and hands back the ack.

        crc failure responds immediately without touching the session — the
        sender retries the same seq on the same stripe, exactly v2's
        retry-before-rollback contract, now per stripe."""
        if FAULTS.armed:
            FAULTS.fire("kv.chunk.recv")  # fires per stripe per chunk
        seq = int(msg.get("seq", -1))
        total = sess.total_chunks or 0
        blocks = blob_to_blocks(msg.get("blocks", []), msg.get("blob", b""))
        bad = sum(1 for blk in blocks if not block_crc_ok(blk))
        if bad:
            self.crc_failures += bad
            sess.t_last = time.monotonic()
            logger.warning(
                "kv chunk crc mismatch (req=%s seq=%d, %d/%d blocks); asking sender to retry",
                request_id, seq, bad, len(blocks),
            )
            return {"request_id": request_id, "seq": seq, "crc_error": True, "bad_blocks": bad}
        nbytes = sum(len(blk["k"]) + len(blk["v"]) for blk in blocks)
        # Budget backpressure applies only to chunks AHEAD of the commit
        # cursor; the cursor chunk always proceeds, which also drains staging.
        while True:
            ev = self._wake
            if (sess.dead or seq <= sess.next_seq
                    or self._staged_bytes + nbytes <= self._staging_budget):
                break
            await ev.wait()
        if sess.dead or self._streams.get(request_id) is not sess:
            return {"request_id": request_id, "seq": seq, "stream_error": "no session"}
        if seq < sess.next_seq or seq >= total or seq in sess.staging or seq in sess.acks:
            self._abort_stream(request_id)
            return {
                "request_id": request_id, "seq": seq,
                "stream_error": f"unexpected seq {seq} (want {sess.next_seq})",
            }
        sess.staging[seq] = (blocks, nbytes)
        sess.staged_bytes += nbytes
        self._staged_bytes += nbytes
        sess.t_last = time.monotonic()
        sess.pulse()  # wake the pump
        while True:
            ev = sess.wake
            if sess.dead or seq in sess.acks:
                break
            await ev.wait()
        ack = sess.acks.pop(seq, None)
        if ack is None:
            return {"request_id": request_id, "seq": seq, "stream_error": "stream aborted"}
        return ack

    async def duplex(self, request: Any, inbound: AsyncIterator[dict], context: Context) -> AsyncIterator[dict]:
        """Wire v3 data plane: one duplex connection per stripe.

        The opening request is ``{"request_id", "stream_open": true, "sid",
        "stripe", "stripes", "total_chunks"}``; every inbound message is one
        chunk — msgpack head ``{"seq", "blocks": [meta...], "last"}`` plus
        the raw k/v blob — and gets exactly one ack, deferred until the
        chunk commits. When the last stripe connection drops while the
        session is incomplete, the sender died: roll back immediately
        instead of waiting for the abandoned-stream sweep."""
        request_id = str(request.get("request_id", ""))
        sess = self._attach_striped(request_id, request) if request.get("stream_open") else None
        if sess is None:
            yield {"request_id": request_id,
                   "stream_error": "expected stream_open with sid/total_chunks"}
            return
        sess.conns += 1
        self._wire_conns += 1
        try:
            async for msg in inbound:
                try:
                    resp = await self._ingest_striped_chunk(request_id, sess, msg)
                except Exception:
                    logger.exception(
                        "kv striped ingest failed (req=%s); stream rolled back", request_id
                    )
                    if self._streams.get(request_id) is sess:
                        self._abort_stream(request_id)
                    resp = {"request_id": request_id, "seq": msg.get("seq"),
                            "stream_error": "ingestion failed"}
                yield resp
                if resp.get("stream_error"):
                    return
        finally:
            sess.conns -= 1
            self._wire_conns -= 1
            if sess.conns == 0 and self._streams.get(request_id) is sess:
                logger.warning(
                    "kv stripe connections for %s all closed mid-stream; rolling back",
                    request_id,
                )
                self._abort_stream(request_id)

    async def _handle_pull_query(self, request_id: str, query: dict) -> dict:
        """Phase 1 of the two-phase device-path pull: report which chain
        blocks are missing locally, staging destination pages for them.

        The sender gathers and offers ONLY the missed pages afterwards — a
        fully-cached chain completes right here with zero gather work and
        zero transfer-server staging on either side (the un-pulled-offer
        device-memory leak class, ADVICE r3)."""
        import time

        from dynamo_tpu.disagg.pull_transport import device_pull_supported

        if not device_pull_supported():
            return {"request_id": request_id, "injected": 0, "pull_unsupported": True}
        self._abort_pull(request_id)  # a re-query replaces stale staging
        hashes = list(query["hashes"])
        parents = list(query["parents"])
        pinned, staged = self._stage_chain((h, i) for i, h in enumerate(hashes))
        if not staged:
            # Warm cache: the whole chain is already here.
            self.core.allocator.release(pinned)
            ev = self._completions.get(request_id)
            if ev is not None:
                ev.set()
            return {
                "request_id": request_id,
                "injected": len(pinned),
                "total": len(hashes),
                "miss": [],
                "pull": True,
                "stats": self.stats(),
            }
        self._pending_pulls[request_id] = (pinned, staged, parents, time.monotonic())
        return {
            "request_id": request_id,
            "miss": [i for _pid, _h, i in staged],
            "hits": len(pinned),
            "pull": True,
        }

    async def _ingest_pull(self, request_id: str, pull: dict) -> dict:
        """Phase 2: pull the sender's staged miss-page stack through the
        transfer engine (``disagg/pull_transport.py``) and scatter it into
        the pages staged by :meth:`_handle_pull_query`.

        Returns the summary dict; ``pull_failed`` tells the sender to fall
        back to the packed-bytes TCP path (its offer stays un-pulled, so it
        must drain it — ``finish_offer(consumed=False)``)."""
        import time

        import jax
        import numpy as np

        from dynamo_tpu.disagg.pull_transport import get_transport

        pending = self._pending_pulls.pop(request_id, None)
        if pending is None:
            logger.warning("pull for %s without a pending pull_query", request_id)
            return {"request_id": request_id, "injected": 0, "pull_failed": True}
        pinned, staged, parents, _t0 = pending
        t0 = time.perf_counter()
        wire_pulled = False  # whether the transfer-engine pull itself completed
        try:
            runner = self.core.runner
            sharding = runner.k_cache.sharding
            k_sds = jax.ShapeDtypeStruct(
                tuple(pull["k_shape"]), np.dtype(pull["k_dtype"]), sharding=sharding
            )
            v_sds = jax.ShapeDtypeStruct(
                tuple(pull["v_shape"]), np.dtype(pull["v_dtype"]), sharding=sharding
            )
            transport = get_transport()
            try:
                k, v = await asyncio.get_running_loop().run_in_executor(
                    None, transport.pull, pull["address"], pull["uuid"], [k_sds, v_sds]
                )
                wire_pulled = True
                # The stack holds exactly the missed pages (staged order),
                # padded to a power of two; slice off the pad device-side.
                n = len(staged)
                await asyncio.get_running_loop().run_in_executor(
                    None, self.core.runner.write_pages,
                    [pid for pid, _h, _i in staged], k[:, :n], v[:, :n],
                )
            except Exception:
                self._release_staged(staged)
                logger.exception("device pull ingestion failed; sender will fall back")
                # "pulled" tells the sender whether its offer was consumed:
                # a consumed one-shot offer must NOT be drained again (a
                # second pull of the same uuid can block forever).
                return {
                    "request_id": request_id, "injected": 0,
                    "pull_failed": True, "pulled": wire_pulled,
                }
            self._commit_staged(
                (pid, h, parents[i], ()) for pid, h, i in staged
            )
            pulled_bytes = (
                int(np.prod(pull["k_shape"])) * np.dtype(pull["k_dtype"]).itemsize
                + int(np.prod(pull["v_shape"])) * np.dtype(pull["v_dtype"]).itemsize
            )
            self.bytes_received += pulled_bytes
            self.transfer_seconds += time.perf_counter() - t0
            self.device_path_blocks += len(staged)
            self._record_path("device_pull", pulled_bytes)
        finally:
            self.core.allocator.release(pinned)
        ev = self._completions.get(request_id)
        if ev is not None:
            ev.set()
        return {
            "request_id": request_id,
            "injected": len(pinned) + len(staged),
            "total": pull.get("total", len(pinned) + len(staged)),
            "pull": True,
            "stats": self.stats(),
        }

    def expect(self, request_id: str) -> asyncio.Event:
        """Register interest in a transfer's completion (disagg operator)."""
        ev = self._completions.setdefault(request_id, asyncio.Event())
        return ev

    def forget(self, request_id: str) -> None:
        self._completions.pop(request_id, None)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        """Request forms:

        - ``{"request_id", "seq", "blocks", "last"}`` — wire protocol v2:
          one chunk of a pipelined stream (:meth:`_ingest_chunk`);
        - ``{"request_id", "stream_abort": true}`` — sender abandoned a v2
          stream mid-flight; roll back its session;
        - ``{"request_id", "blocks": [packed blocks...]}`` — v1 monolithic
          packed-bytes message (last-resort fallback);
        - ``{"request_id", "pull_query": {hashes, parents}}`` — phase 1 of
          the device-path pull (:meth:`_handle_pull_query`);
        - ``{"request_id", "pull": descriptor}`` — phase 2
          (:meth:`_ingest_pull`);
        - ``{"request_id", "pull_abort": true}`` — sender abandoned a
          staged pull (falls back to packed bytes); roll back staging.

        Responds with one summary item per message. On the v1 path the whole
        chain is staged (allocate + unpack) then written as one batched
        scatter and committed; a failure anywhere releases the staged pages,
        so the cache keeps only previously-present blocks — still a valid,
        chain-consistent prefix.
        """
        request_id = request.get("request_id", "")
        # Reclaim staging abandoned by dead senders on EVERY interaction,
        # not just pull queries — otherwise packed-bytes-only traffic never
        # frees it.
        self._sweep_pending_pulls()
        if "seq" in request:
            yield await self._ingest_chunk(request_id, request)
            return
        if request.get("stream_abort"):
            self._abort_stream(request_id)
            yield {"request_id": request_id, "aborted": True}
            return
        if request.get("pull_query") is not None:
            yield await self._handle_pull_query(request_id, request["pull_query"])
            return
        if request.get("pull") is not None:
            yield await self._ingest_pull(request_id, request["pull"])
            return
        if request.get("pull_abort"):
            self._abort_pull(request_id)
            yield {"request_id": request_id, "aborted": True}
            return
        # Packed-bytes path: supersedes any staged pull or stream for this
        # request.
        self._abort_pull(request_id)
        self._abort_stream(request_id)
        blocks = request.get("blocks", [])
        first_bad = next((i for i, blk in enumerate(blocks) if not block_crc_ok(blk)), None)
        if first_bad is not None:
            # v1 has no per-chunk retry protocol: truncate at the first
            # corrupt block (every prefix of the hash chain is a valid cache
            # state; committing past a gap would publish unreachable blocks).
            self.crc_failures += 1
            logger.warning(
                "v1 kv payload crc mismatch at block %d/%d (req=%s); chain truncated",
                first_bad, len(blocks), request_id,
            )
            blocks = blocks[:first_bad]
        injected = 0
        t0 = time.perf_counter()
        pinned: list[int] = []
        staged: list[tuple[int, int, Any]] = []  # payload = packed block dict
        try:
            pinned, staged = self._stage_chain((blk["hash"], blk) for blk in blocks)
            injected += len(pinned)
            if staged:
                payloads = [unpack_payload(blk) for _pid, _h, blk in staged]
                # One stacked transfer + one scatter for the whole chain,
                # instead of a dispatch round-trip per page.
                t_sc = time.perf_counter()
                await asyncio.get_running_loop().run_in_executor(
                    None, self.core.runner.write_pages,
                    [pid for pid, _h, _b in staged],
                    [k for k, _ in payloads], [v for _, v in payloads],
                )
                dt_sc = time.perf_counter() - t_sc
                self.scatter_seconds += dt_sc
                observe_kv_phase("scatter", dt_sc, core=self.core)
                record_span(
                    "kv_scatter", dt_sc * 1e3,
                    trace=TraceContext.from_dict(request.get("trace")),
                    request_id=request_id, blocks=len(staged), protocol="v1",
                )
                self._commit_staged(
                    (pid, h, blk.get("parent"), tuple(blk.get("tokens", ())))
                    for pid, h, blk in staged
                )
                injected += len(staged)
                v1_bytes = sum(k.nbytes + v.nbytes for k, v in payloads)
                self.bytes_received += v1_bytes
                self.transfer_seconds += time.perf_counter() - t0
                self._record_path("host_monolithic", v1_bytes)
        except Exception:
            self._release_staged(staged)
            logger.exception("kv injection failed; dropped %d staged blocks", len(staged))
        finally:
            self.core.allocator.release(pinned)
        ev = self._completions.get(request_id)
        if ev is not None:
            ev.set()
        yield {"request_id": request_id, "injected": injected, "total": len(blocks), "stats": self.stats()}


async def send_blocks(
    transport: Transport,
    address: str,
    request_id: str,
    blocks: list[dict],
    *,
    context: Context | None = None,
    trace: TraceContext | None = None,
    core: EngineCore | None = None,
) -> dict:
    """Sender-side: ship packed blocks to a decode worker's transfer endpoint.

    ``core`` (when the caller has one) routes the wire-phase observation to
    that engine's metrics registry instead of the process-global fallback.
    """
    context = context or Context()
    msg: dict = {"request_id": request_id, "blocks": blocks}
    if trace is not None:
        msg["trace"] = trace.to_dict()
    t0 = time.perf_counter()
    result: dict = {}
    async for item in transport.generate(address, msg, context):
        result = item
    dt = time.perf_counter() - t0
    observe_kv_phase("wire", dt, core=core)
    record_span("kv_wire", dt * 1e3, trace=trace, request_id=request_id, blocks=len(blocks), protocol="v1")
    return result


async def send_blocks_chunked(
    transport: Transport,
    address: str,
    request_id: str,
    core: EngineCore,
    block_hashes: list[int],
    *,
    chunk_pages: int | None = None,
    streams: int | None = None,
    context: Context | None = None,
    trace: TraceContext | None = None,
) -> dict:
    """Pipelined chunked transfer of a committed hash chain (wire v2/v3).

    With ``streams >= 1`` (default: ``DYN_KV_WIRE_STREAMS``) and a transport
    that has a duplex data plane, the chunks are striped round-robin across
    that many persistent connections as raw blob frames
    (:func:`_send_blocks_striped`); when the transport or receiver lacks
    duplex support — or ``streams == 0`` pins the legacy protocol — the
    single-stream v2 loop below runs instead.

    The chain's pages are shipped in ``chunk_pages`` chunks (default:
    ``DYN_KV_CHUNK_PAGES``) with the three phases double-buffered: chunk
    N+1's batched gather + device->host DMA is dispatched
    (``read_pages_async``, lock held for the dispatch only) BEFORE chunk N
    is packed and sent, so the D2H copy rides under chunk N's pack + TCP
    round trip and the sender's decode loop interleaves between chunks. The
    receiver scatters and commits each chunk incrementally
    (:meth:`KvTransferService._ingest_chunk` /
    :meth:`KvTransferService.duplex`).

    Returns the receiver's final summary, augmented with ``bytes`` and
    per-phase wall times ``phases = {gather_s, pack_s, wire_s}`` (phase sums
    exceed the end-to-end time exactly when the overlap is real — that is
    the number the kv_wire bench tracks). On the striped path each phase is
    per-stream-attributed wall time (busy-interval union across stripes,
    :class:`_PhaseClock`), never a sum over concurrent streams, so the
    invariant survives striping. Raises on a mid-stream failure after
    telling the receiver to roll back; callers fall back to the v1
    monolithic path.
    """
    chunk_pages = default_chunk_pages() if chunk_pages is None else chunk_pages
    streams = default_wire_streams() if streams is None else streams
    if streams >= 1:
        try:
            return await _send_blocks_striped(
                transport, address, request_id, core, block_hashes,
                chunk_pages=chunk_pages, streams=streams, context=context, trace=trace,
            )
        except DuplexUnsupportedError:
            logger.debug("kv wire v3 unavailable for %s; using v2", address)
    context = context or Context()
    loop = asyncio.get_running_loop()
    allocator = core.allocator
    runner = core.runner
    # Hold the chain's refcounts for the whole stream: the gather of chunk
    # N+1 is in flight while chunk N is on the wire, and eviction must not
    # reuse any of these pages until the last chunk is packed.
    pages = await loop.run_in_executor(None, allocator.match_prefix, block_hashes)
    phases = {"gather_s": 0.0, "pack_s": 0.0, "wire_s": 0.0}
    total_bytes = 0
    crc_retries = 0
    streaming = False  # any chunk reached the receiver (it may hold session state)
    try:
        if not pages:
            return {"request_id": request_id, "injected": 0, "total": 0, "phases": phases, "bytes": 0}
        hashes = list(block_hashes[: len(pages)])
        parents = [allocator.page_parent_hash(pid) for pid in pages]
        chunks = [
            (pages[off : off + chunk_pages], hashes[off : off + chunk_pages],
             parents[off : off + chunk_pages])
            for off in range(0, len(pages), chunk_pages)
        ]

        def _dispatch(pids: list[int]):
            return time.perf_counter(), runner.read_pages_async(pids)

        t_dispatch, inflight = await loop.run_in_executor(None, _dispatch, chunks[0][0])
        result: dict = {}
        for i, (_pids, chunk_hashes, chunk_parents) in enumerate(chunks):
            payloads = await loop.run_in_executor(None, inflight.wait)
            phases["gather_s"] += time.perf_counter() - t_dispatch
            if i + 1 < len(chunks):
                # Double buffer: next chunk's gather + D2H DMA starts now and
                # runs under THIS chunk's pack + wire.
                t_dispatch, inflight = await loop.run_in_executor(None, _dispatch, chunks[i + 1][0])
            t_pack = time.perf_counter()
            blocks = await loop.run_in_executor(
                None,
                lambda: [
                    pack_block(chunk_hashes[j], chunk_parents[j], [], k, v)
                    for j, (k, v) in enumerate(payloads)
                ],
            )
            phases["pack_s"] += time.perf_counter() - t_pack
            total_bytes += sum(len(b["k"]) + len(b["v"]) for b in blocks)
            wire_blocks = blocks
            if FAULTS.armed:
                if FAULTS.fire("kv.chunk.send") == "corrupt" and wire_blocks:
                    corrupted = dict(wire_blocks[0])
                    corrupted["k"] = corrupt_bytes(corrupted["k"])
                    wire_blocks = [corrupted, *wire_blocks[1:]]
            t_wire = time.perf_counter()
            streaming = True
            msg = {
                "request_id": request_id, "seq": i, "blocks": wire_blocks,
                "last": i == len(chunks) - 1,
            }
            if trace is not None:
                # The receiver's scatter spans link under the sender's span.
                msg["trace"] = trace.to_dict()
            resp = await _round_trip(transport, address, msg)
            if resp.get("crc_error"):
                # The receiver rejected the chunk but kept the session at
                # this seq: one transfer-level retry with freshly-packed
                # blocks (the clean copies, whatever got mangled in flight)
                # before giving up on the stream.
                logger.warning(
                    "kv chunk %d of %s failed crc at receiver; retrying once",
                    i, request_id,
                )
                crc_retries += 1
                msg["blocks"] = blocks
                resp = await _round_trip(transport, address, msg)
                if resp.get("crc_error"):
                    raise RuntimeError(f"kv chunk {i} failed crc after retry")
            phases["wire_s"] += time.perf_counter() - t_wire
            if resp.get("stream_error"):
                # The receiver already rolled the stream back.
                streaming = False
                raise RuntimeError(f"kv chunk stream rejected: {resp['stream_error']}")
            result = resp
        streaming = False
        result["phases"] = {k: round(v, 6) for k, v in phases.items()}
        result["bytes"] = total_bytes
        result["crc_retries"] = crc_retries
        # Sender-side phase telemetry: one span per phase (cumulative over
        # the stream) + histogram observations for the metrics plane.
        for phase, secs in (("gather", phases["gather_s"]), ("pack", phases["pack_s"]), ("wire", phases["wire_s"])):
            observe_kv_phase(phase, secs, core=core)
            record_span(
                f"kv_{phase}", secs * 1e3, trace=trace,
                request_id=request_id, chunks=len(chunks), bytes=total_bytes,
            )
        return result
    finally:
        if streaming:
            # Mid-stream failure on our side (or transport death): best-effort
            # tell the receiver to roll back its session before we fall back.
            try:
                await _round_trip(transport, address, {"request_id": request_id, "stream_abort": True})
            except Exception:
                logger.warning("stream abort for %s not delivered", request_id)
        await loop.run_in_executor(None, allocator.release, pages)


async def _send_blocks_striped(
    transport: Transport,
    address: str,
    request_id: str,
    core: EngineCore,
    block_hashes: list[int],
    *,
    chunk_pages: int,
    streams: int,
    context: Context | None = None,
    trace: TraceContext | None = None,
) -> dict:
    """Wire v3 sender: stripe the chunk sequence across ``streams`` duplex
    connections, each chunk one raw blob frame.

    One producer coroutine runs the v2 double-buffered gather (chunk N+1's
    device gather + D2H dispatched before chunk N is consumed) and feeds
    bounded per-stripe queues round-robin; each stripe task packs its chunk
    (metadata msgpack + zero-copy memoryview body), sends, and waits for the
    ack — which the receiver defers until the chunk *commits*, so at most
    ``streams`` chunks are un-acked and flow control falls out of the
    protocol. A ``crc_error`` ack retries that seq once on the same stripe
    with the clean buffers (v2's retry-before-rollback, per stripe); any
    stripe failure cancels the rest, tells the receiver to roll back, and
    raises so the caller can fall back.

    Raises :class:`DuplexUnsupportedError` (before any stream state exists)
    when the transport or receiver has no duplex plane — the caller then
    runs the v2 protocol.
    """
    open_duplex = getattr(transport, "open_duplex", None)
    if open_duplex is None:
        raise DuplexUnsupportedError("transport has no duplex data plane")
    context = context or Context()
    loop = asyncio.get_running_loop()
    allocator = core.allocator
    runner = core.runner
    pages = await loop.run_in_executor(None, allocator.match_prefix, block_hashes)
    pack_clock = _PhaseClock()
    wire_clock = _PhaseClock()
    gather_s = 0.0
    total_bytes = 0
    crc_retries = 0
    opened: list[Any] = []
    streaming = False
    try:
        if not pages:
            return {"request_id": request_id, "injected": 0, "total": 0,
                    "phases": {"gather_s": 0.0, "pack_s": 0.0, "wire_s": 0.0}, "bytes": 0}
        hashes = list(block_hashes[: len(pages)])
        parents = [allocator.page_parent_hash(pid) for pid in pages]
        chunks = [
            (pages[off: off + chunk_pages], hashes[off: off + chunk_pages],
             parents[off: off + chunk_pages])
            for off in range(0, len(pages), chunk_pages)
        ]
        n = len(chunks)
        n_stripes = max(1, min(streams, n))
        sid = os.urandom(8).hex()
        for s in range(n_stripes):
            req = {"request_id": request_id, "stream_open": True, "sid": sid,
                   "stripe": s, "stripes": n_stripes, "total_chunks": n}
            if trace is not None:
                req["trace"] = trace.to_dict()
            # The first open raises DuplexUnsupportedError on a v2-only
            # receiver — before any session state exists on either side.
            opened.append(await open_duplex(address, req, context))
        streaming = True
        queues: list[asyncio.Queue] = [asyncio.Queue(maxsize=2) for _ in range(n_stripes)]
        summary: dict = {}

        def _dispatch(pids: list[int]):
            return time.perf_counter(), runner.read_pages_async(pids)

        async def producer() -> None:
            nonlocal gather_s
            t_dispatch, inflight = await loop.run_in_executor(None, _dispatch, chunks[0][0])
            for i in range(n):
                payloads = await loop.run_in_executor(None, inflight.wait)
                gather_s += time.perf_counter() - t_dispatch
                if i + 1 < n:
                    t_dispatch, inflight = await loop.run_in_executor(
                        None, _dispatch, chunks[i + 1][0])
                await queues[i % n_stripes].put((i, payloads))
            for q in queues:
                await q.put(None)

        async def stripe(s: int) -> None:
            nonlocal summary, total_bytes, crc_retries
            st = opened[s]
            while True:
                item = await queues[s].get()
                if item is None:
                    return
                i, payloads = item
                _pids, chunk_hashes, chunk_parents = chunks[i]
                meta, bufs, nbytes = await loop.run_in_executor(
                    None, pack_chunk_blob, chunk_hashes, chunk_parents, payloads, pack_clock,
                )
                total_bytes += nbytes
                msg = {"request_id": request_id, "seq": i, "blocks": meta,
                       "last": i == n - 1}
                if trace is not None:
                    msg["trace"] = trace.to_dict()
                wire_bufs = bufs
                if FAULTS.armed:
                    # Same drill as v2, now per stripe: corrupt the first
                    # block's k-bytes of whichever chunk this stripe carries.
                    if FAULTS.fire("kv.chunk.send") == "corrupt" and wire_bufs:
                        wire_bufs = [corrupt_bytes(bytes(wire_bufs[0])), *wire_bufs[1:]]
                wire_clock.enter()
                try:
                    await st.send(msg, blobs=wire_bufs)
                    resp = await st.recv()
                finally:
                    wire_clock.exit()
                if resp is None:
                    raise RuntimeError(f"kv stripe {s} closed mid-stream")
                if resp.get("crc_error"):
                    logger.warning(
                        "kv chunk %d of %s failed crc at receiver; retrying once",
                        i, request_id,
                    )
                    crc_retries += 1
                    wire_clock.enter()
                    try:
                        await st.send(msg, blobs=bufs)  # clean copies
                        resp = await st.recv()
                    finally:
                        wire_clock.exit()
                    if resp is None:
                        raise RuntimeError(f"kv stripe {s} closed mid-stream")
                    if resp.get("crc_error"):
                        raise RuntimeError(f"kv chunk {i} failed crc after retry")
                if resp.get("stream_error"):
                    raise RuntimeError(f"kv chunk stream rejected: {resp['stream_error']}")
                if resp.get("last"):
                    summary = resp

        tasks = [asyncio.create_task(producer(), name=f"kv-stripe-producer-{request_id}")]
        tasks += [
            asyncio.create_task(stripe(s), name=f"kv-stripe-{s}-{request_id}")
            for s in range(n_stripes)
        ]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        streaming = False
        phases = {"gather_s": gather_s, "pack_s": pack_clock.total, "wire_s": wire_clock.total}
        result = dict(summary) if summary else {"request_id": request_id, "injected": 0}
        result["phases"] = {k: round(v, 6) for k, v in phases.items()}
        result["bytes"] = total_bytes
        result["crc_retries"] = crc_retries
        result["protocol"] = "v3"
        result["streams"] = n_stripes
        for phase, secs in (("gather", phases["gather_s"]), ("pack", phases["pack_s"]),
                            ("wire", phases["wire_s"])):
            observe_kv_phase(phase, secs, core=core)
            record_span(
                f"kv_{phase}", secs * 1e3, trace=trace,
                request_id=request_id, chunks=n, bytes=total_bytes, streams=n_stripes,
            )
        return result
    finally:
        if streaming:
            # Mid-stream failure: best-effort tell the receiver to roll back
            # (its all-stripes-closed detector is the backstop).
            try:
                await _round_trip(transport, address, {"request_id": request_id, "stream_abort": True})
            except Exception:
                logger.warning("stream abort for %s not delivered", request_id)
        for st in opened:
            try:
                await st.close()
            except Exception:
                pass
        await loop.run_in_executor(None, allocator.release, pages)


def _gather_page_stack(core: EngineCore, page_ids: list[int]):
    """Gather specific cache pages into stacked DEVICE arrays (never
    host-materialized). Page count is padded to a power of two (null page 0)
    so the gather reuses the runner's compiled shapes."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.runner import next_pow2

    runner = core.runner
    n = len(page_ids)
    padded = np.zeros(next_pow2(n), np.int32)
    padded[:n] = page_ids
    with runner.io_lock:
        return runner._gather_pages_fn(runner.k_cache, runner.v_cache, jnp.asarray(padded))


async def _round_trip(transport: Transport, address: str, request: dict) -> dict:
    result: dict = {}
    async for item in transport.generate(address, request, Context()):
        result = item
    return result


async def send_pull_offer(
    transport: Transport,
    address: str,
    request_id: str,
    core: EngineCore,
    block_hashes: list[int],
) -> dict | None:
    """Two-phase device-path pull. Returns the receiver's summary, or None
    when the pull path didn't complete (caller falls back to packed bytes).

    Phase 1 (``pull_query``) asks the receiver which chain blocks it is
    missing; phase 2 gathers and offers ONLY those pages for a
    transfer-engine pull. A fully-cached chain therefore costs one control
    message — no gather, no transfer-server staging — and an offer that the
    receiver never consumed is drained (``finish_offer(consumed=False)``)
    instead of pinning device buffers on the TransferServer forever
    (ADVICE r3)."""
    from dynamo_tpu.disagg.pull_transport import device_pull_supported, get_transport

    if not device_pull_supported():
        return None
    loop = asyncio.get_running_loop()
    allocator = core.allocator
    # Hold the chain's refcounts across both phases so eviction can't reuse
    # the source pages between the query and the gather.
    pages = await loop.run_in_executor(None, allocator.match_prefix, block_hashes)
    staged_on_receiver = False
    try:
        if not pages:
            return None
        hashes = list(block_hashes[: len(pages)])
        parents = [allocator.page_parent_hash(pid) for pid in pages]
        resp = await _round_trip(
            transport, address,
            {"request_id": request_id, "pull_query": {"hashes": hashes, "parents": parents}},
        )
        if resp.get("pull_unsupported") or not resp.get("pull"):
            return None
        miss = resp.get("miss")
        if not miss:
            # Warm cache: the receiver already has the whole chain.
            return resp if "injected" in resp else None
        staged_on_receiver = True
        k, v = await loop.run_in_executor(
            None, _gather_page_stack, core, [pages[i] for i in miss]
        )
        t = get_transport()
        uuid = t.new_uuid()
        t.offer(uuid, [k, v])
        consumed = False
        try:
            resp2 = await _round_trip(
                transport, address,
                {"request_id": request_id, "pull": {
                    "address": t.address(), "uuid": uuid, "total": len(hashes),
                    "k_shape": list(k.shape), "v_shape": list(v.shape),
                    "k_dtype": str(k.dtype), "v_dtype": str(v.dtype),
                }},
            )
            # The receiver popped its staging on any pull response (success
            # or pull_failed); only a transport failure leaves it pending.
            staged_on_receiver = False
            ok = "injected" in resp2 and not resp2.get("pull_failed")
            # Consumed also when the wire pull succeeded but the receiver's
            # scatter failed afterwards — draining a consumed one-shot offer
            # would block.
            consumed = ok or bool(resp2.get("pulled"))
            return resp2 if ok else None
        finally:
            await loop.run_in_executor(None, t.finish_offer, uuid, consumed)
    finally:
        if staged_on_receiver:
            # Best-effort: tell the receiver to roll back its staged pages
            # before we fall back to the packed-bytes path.
            try:
                await _round_trip(transport, address, {"request_id": request_id, "pull_abort": True})
            except Exception:
                logger.warning("pull abort for %s not delivered", request_id)
        await loop.run_in_executor(None, allocator.release, pages)


def collect_prefill_blocks(core: EngineCore, block_hashes: list[int]) -> list[dict]:
    """Read the committed pages for a hash chain out of a (prefill) engine.

    Acquires the pages (refcount) while reading so eviction can't reuse them
    mid-copy, then releases.
    """
    allocator = core.allocator
    pages = allocator.match_prefix(block_hashes)
    try:
        payloads = core.runner.read_pages(pages)  # one gather + one transfer
        return [
            pack_block(block_hashes[i], allocator.page_parent_hash(pid), [], k, v)
            for i, (pid, (k, v)) in enumerate(zip(pages, payloads))
        ]
    finally:
        allocator.release(pages)
