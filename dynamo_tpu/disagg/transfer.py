"""KV block transfer: the prefill->decode migration path.

Decode workers serve a ``kv_transfer`` endpoint (`KvTransferService`).
A transfer request is a stream of block payloads — each a hash-chained,
complete page of KV for all layers — which the service writes into freshly
allocated pages and *commits to the local prefix cache*. From that moment
the blocks are indistinguishable from locally-computed cache: admission
matches them, KV events announce them, eviction can offload them to tiers.

Wire format per block (msgpack-native, no base64):
  {"hash": int, "parent": int|None, "tokens": [int], "k": bytes, "v": bytes,
   "shape": [L, ps, kv, hd], "dtype": str}

Completion notifications resolve per-request futures so the disagg operator
holding the original request knows when injection is done.

Parity: replaces the reference's NIXL RDMA block writes
(`block_manager/block/transfer/nixl.rs`, vLLM patch in SURVEY.md §3C) with a
receiver-driven stream over the runtime's data plane — the DCN path. Workers
sharing a host/slice can short-circuit with device-to-device copies; that
fast path rides the same interface.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator

import numpy as np

from dynamo_tpu.engine.allocator import OutOfPagesError
from dynamo_tpu.engine.core import EngineCore
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transport import Transport

logger = logging.getLogger(__name__)

KV_TRANSFER_ENDPOINT = "kv_transfer"


def pack_block(block_hash: int, parent_hash: int | None, tokens: list[int], k: np.ndarray, v: np.ndarray) -> dict:
    return {
        "hash": block_hash,
        "parent": parent_hash,
        "tokens": list(tokens),
        "k": np.ascontiguousarray(k).tobytes(),
        "v": np.ascontiguousarray(v).tobytes(),
        "shape": list(k.shape),
        "dtype": str(k.dtype),
    }


def unpack_payload(msg: dict) -> tuple[np.ndarray, np.ndarray]:
    shape = tuple(msg["shape"])
    dtype = np.dtype(msg["dtype"])
    k = np.frombuffer(msg["k"], dtype=dtype).reshape(shape)
    v = np.frombuffer(msg["v"], dtype=dtype).reshape(shape)
    return k, v


class KvTransferService(AsyncEngine[Any, dict]):
    """Served by decode workers: ingests KV blocks into the local cache."""

    def __init__(self, core: EngineCore) -> None:
        self.core = core
        self._completions: dict[str, asyncio.Event] = {}
        self.blocks_received = 0

    def expect(self, request_id: str) -> asyncio.Event:
        """Register interest in a transfer's completion (disagg operator)."""
        ev = self._completions.setdefault(request_id, asyncio.Event())
        return ev

    def forget(self, request_id: str) -> None:
        self._completions.pop(request_id, None)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        """Request: {"request_id": str, "blocks": [packed blocks...]}.

        Responds with one summary item. Injection is atomic-enough per block:
        allocate page -> write payload -> commit hash; a mid-transfer failure
        leaves a shorter (still valid, chain-consistent) cached prefix.
        """
        request_id = request.get("request_id", "")
        blocks = request.get("blocks", [])
        injected = 0
        allocator = self.core.allocator
        runner = self.core.runner
        for blk in blocks:
            if blk["hash"] in allocator._cached:  # already have it (races are benign)
                injected += 1
                continue
            try:
                [pid] = allocator.allocate(1)
            except OutOfPagesError:
                logger.warning("kv injection out of pages after %d blocks", injected)
                break
            k, v = unpack_payload(blk)
            await asyncio.get_running_loop().run_in_executor(None, runner.write_page, pid, k, v)
            allocator.commit(pid, blk["hash"], blk.get("parent"), tuple(blk.get("tokens", ())))
            allocator.release([pid])  # refcount 0: lives as prefix cache
            injected += 1
            self.blocks_received += 1
        ev = self._completions.get(request_id)
        if ev is not None:
            ev.set()
        yield {"request_id": request_id, "injected": injected, "total": len(blocks)}


async def send_blocks(
    transport: Transport,
    address: str,
    request_id: str,
    blocks: list[dict],
    *,
    context: Context | None = None,
) -> dict:
    """Sender-side: ship packed blocks to a decode worker's transfer endpoint."""
    context = context or Context()
    result: dict = {}
    async for item in transport.generate(address, {"request_id": request_id, "blocks": blocks}, context):
        result = item
    return result


def collect_prefill_blocks(core: EngineCore, block_hashes: list[int]) -> list[dict]:
    """Read the committed pages for a hash chain out of a (prefill) engine.

    Acquires the pages (refcount) while reading so eviction can't reuse them
    mid-copy, then releases.
    """
    allocator = core.allocator
    pages = allocator.match_prefix(block_hashes)
    try:
        out = []
        for i, pid in enumerate(pages):
            k, v = core.runner.read_page(pid)
            # Parent/token metadata from the allocator's page records.
            info = allocator._pages[pid]
            out.append(pack_block(block_hashes[i], info.parent_hash, [], k, v))
        return out
    finally:
        allocator.release(pages)
