"""Conditional disaggregation decision + hot-reloaded config.

``DisaggRouter.prefill_remote(prefill_len, queue_depth)`` mirrors the
reference's decision (`disagg_router.rs:25-38`): prompts longer than
``max_local_prefill_length`` go to the prefill fleet, unless the prefill
queue is so deep that waiting would cost more than computing locally
(``max_prefill_queue_size``). The config lives in the discovery store under
``config/disagg/{namespace}`` and is watched, so operators (or the planner)
retune thresholds at runtime without restarts.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.discovery import WatchEventType

logger = logging.getLogger(__name__)


@dataclass
class DisaggConfig:
    enabled: bool = True
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 64
    # Blocks shorter than this aren't worth the transfer overhead.
    min_remote_prefill_blocks: int = 2

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "DisaggConfig":
        d = json.loads(data)
        return cls(**{k: d[k] for k in cls().__dict__ if k in d})


def config_key(namespace: str) -> str:
    return f"config/disagg/{namespace}"


class DisaggRouter:
    def __init__(self, config: DisaggConfig | None = None, *, page_size: int = 16) -> None:
        self.config = config or DisaggConfig()
        self.page_size = page_size
        self._watch_task: asyncio.Task | None = None

    def wants_remote(self, prefill_len: int) -> bool:
        """Cheap length-only screen — callers check this before paying for a
        queue-depth lookup."""
        c = self.config
        if not c.enabled:
            return False
        if prefill_len // self.page_size < c.min_remote_prefill_blocks:
            return False
        return prefill_len > c.max_local_prefill_length

    def prefill_remote(self, prefill_len: int, queue_depth: int = 0) -> bool:
        return self.wants_remote(prefill_len) and queue_depth < self.config.max_prefill_queue_size

    # -- dynamic config ----------------------------------------------------

    async def watch(self, runtime: DistributedRuntime, namespace: str) -> "DisaggRouter":
        key = config_key(namespace)
        current = await runtime.store.get(key)
        if current is not None:
            self.config = DisaggConfig.from_json(current)
        if self._watch_task is None:
            self._watch_task = asyncio.create_task(self._watch_loop(runtime, key))
        return self

    async def _watch_loop(self, runtime: DistributedRuntime, key: str) -> None:
        try:
            async for event in runtime.store.watch_prefix(key):
                if event.type is WatchEventType.PUT and event.value is not None:
                    try:
                        self.config = DisaggConfig.from_json(event.value)
                        logger.info("disagg config updated: %s", self.config)
                    except Exception:
                        logger.exception("bad disagg config at %s", key)
        except asyncio.CancelledError:
            raise

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
