"""Disaggregated prefill/decode serving.

The reference's flagship "phase parallelism" (SURVEY.md §3 call stack C):
prefill and decode run on separate worker fleets so each can be sized and
sharded for its regime (prefill = compute-bound, decode = memory-bound).

TPU-first design — **remote prefill is remote prefix-cache injection.**
There is no RDMA-write-into-remote-block-id primitive on TPU; instead of
emulating one, the prefill worker computes the prompt's full KV pages and
streams them into the *decode* worker's page allocator as committed,
hash-identified prefix-cache blocks (`disagg/transfer.py`). The decode
worker then admits the request through its completely ordinary scheduling
path: the prefix match hits the injected blocks, and only the sub-page tail
(< page_size tokens) is computed locally — which also yields the first-token
logits, so the prefill side never needs to sample or ship logits.

Components:

- :mod:`dynamo_tpu.disagg.queue` — distributed work queue on the discovery
  store with lease-protected claims (the JetStream `prefill_queue`
  equivalent; at-least-once, crash-safe reclaim).
- :mod:`dynamo_tpu.disagg.transfer` — the KV injection endpoint served by
  decode workers + the sender-side helper (DCN path over the stream
  transport; same-process meshes short-circuit to device-to-device copies).
- :mod:`dynamo_tpu.disagg.router` — conditional disagg decision
  (prefill length threshold, hot-reloaded from the store like the
  reference's etcd-watched `disagg_router.rs`).
- :mod:`dynamo_tpu.disagg.prefill_worker` — claims queue tasks, prefills on
  its local engine, ships pages.
- :mod:`dynamo_tpu.disagg.operator` — pipeline stage in front of a decode
  engine: decides, enqueues, awaits injection, falls back to local prefill
  on timeout.
"""

from dynamo_tpu.disagg.queue import DistributedQueue
from dynamo_tpu.disagg.router import DisaggConfig, DisaggRouter
from dynamo_tpu.disagg.transfer import KvTransferService, send_blocks

__all__ = ["DistributedQueue", "DisaggConfig", "DisaggRouter", "KvTransferService", "send_blocks"]
