"""Worker load-metrics plane: publisher (worker side) + aggregator (router side).

Workers periodically publish their ForwardPassMetrics snapshot into the
discovery store under ``metrics/{namespace}/{component}/{worker_id:x}``,
bound to their lease (stale workers vanish automatically). The aggregator
watches the prefix and keeps an in-memory view the scheduler reads per
request — no scrape round-trip on the request path.

Parity: reference WorkerMetricsPublisher + KvMetricsAggregator
(`kv_router/publisher.rs`, `metrics_aggregator.rs`, `scoring.rs`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Callable

from dynamo_tpu.protocols.kv import ForwardPassMetrics
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.discovery import WatchEventType

logger = logging.getLogger(__name__)

METRICS_PREFIX = "metrics"


def metrics_key(namespace: str, component: str, worker_id: int) -> str:
    return f"{METRICS_PREFIX}/{namespace}/{component}/{worker_id:x}"


class WorkerMetricsPublisher:
    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str,
        component: str,
        worker_id: int,
        snapshot_fn: Callable[[], ForwardPassMetrics],
        *,
        interval: float = 1.0,
        lease=None,
    ) -> None:
        self.runtime = runtime
        self.key = metrics_key(namespace, component, worker_id)
        self.snapshot_fn = snapshot_fn
        self.interval = interval
        self._lease = lease
        self._task: asyncio.Task | None = None

    async def publish_once(self) -> None:
        lease = self._lease or await self.runtime.primary_lease()
        m = self.snapshot_fn()
        await self.runtime.store.put(self.key, json.dumps(m.to_dict()).encode(), lease_id=lease.id)

    async def start(self) -> "WorkerMetricsPublisher":
        if self._task is None:
            await self.publish_once()
            self._task = asyncio.create_task(self._loop())
        return self

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("metrics publish failed")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class KvMetricsAggregator:
    """Live per-worker metrics view (watch-driven)."""

    def __init__(self, runtime: DistributedRuntime, namespace: str, component: str) -> None:
        self.runtime = runtime
        self.prefix = f"{METRICS_PREFIX}/{namespace}/{component}/"
        self._metrics: dict[int, ForwardPassMetrics] = {}
        self._updated: dict[int, float] = {}  # worker_id -> monotonic of last publish seen
        self._task: asyncio.Task | None = None

    async def start(self) -> "KvMetricsAggregator":
        if self._task is None:
            for key, value in (await self.runtime.store.get_prefix(self.prefix)).items():
                self._apply(key, value)
            self._task = asyncio.create_task(self._watch())
        return self

    def _apply(self, key: str, value: bytes) -> None:
        try:
            wid = int(key[len(self.prefix):], 16)
            self._metrics[wid] = ForwardPassMetrics.from_dict(json.loads(value))
            self._updated[wid] = time.monotonic()
        except Exception:
            logger.exception("bad metrics record at %s", key)

    async def _watch(self) -> None:
        try:
            async for event in self.runtime.store.watch_prefix(self.prefix):
                if event.type is WatchEventType.PUT and event.value is not None:
                    self._apply(event.key, event.value)
                elif event.type is WatchEventType.DELETE:
                    try:
                        wid = int(event.key[len(self.prefix):], 16)
                        self._metrics.pop(wid, None)
                        self._updated.pop(wid, None)
                    except ValueError:
                        pass
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("metrics watch failed")

    def snapshot(self) -> dict[int, ForwardPassMetrics]:
        return dict(self._metrics)

    def staleness_seconds(self) -> dict[int, float]:
        """Seconds since each worker's last ForwardPassMetrics publish was
        seen. A worker whose staleness keeps growing past its publish
        interval is wedged or partitioned — the scheduler is routing on old
        load data for it (surfaced as a frontend gauge)."""
        now = time.monotonic()
        return {wid: max(0.0, now - t) for wid, t in self._updated.items()}

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
