"""Standalone KV-router service: `schedule(token_ids) -> worker_id` as an
endpoint of its own.

Parity: reference `components/router` binary
(`components/router/src/main.rs:38-97`) — a router other ingresses (or
external gateways) can query for placement without going through this
framework's HTTP frontend. It watches the same worker component the
embedded router does, so its world model is identical.

Served as ``--role router`` by the launch CLI; request shape
``{"token_ids": [...]}`` -> one response ``{"worker_id": int,
"overlap_blocks": int}``.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator

from dynamo_tpu.router.router import build_kv_router
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

ROUTER_ENDPOINT = "route"


class RouterService(AsyncEngine[Any, dict]):
    """Serves placement decisions (no proxying of the actual request)."""

    def __init__(self, push_router, subscriber, aggregator) -> None:
        self._push = push_router
        self._aux = [subscriber, aggregator]
        self.decisions = 0

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        token_ids = list(request.get("token_ids", []))
        client = self._push.client
        await client.start()
        worker_ids = client.instance_ids()
        if not worker_ids:
            yield {"error": "no workers available"}
            return
        wid, overlap = self._push.router.schedule(token_ids, worker_ids)
        self.decisions += 1
        yield {"worker_id": wid, "overlap_blocks": overlap}

    async def close(self) -> None:
        for a in self._aux:
            await a.close()
        self._aux = []


async def serve_router(
    runtime: DistributedRuntime,
    *,
    namespace: str = "dynamo",
    component: str = "backend",
    block_size: int = 16,
    lease=None,
) -> RouterService:
    """Bring up the router stack and serve it on
    ``{namespace}/router/{ROUTER_ENDPOINT}``."""
    push, subscriber, aggregator = await build_kv_router(
        runtime, namespace=namespace, component=component, block_size=block_size
    )
    service = RouterService(push, subscriber, aggregator)
    await runtime.namespace(namespace).component("router").endpoint(ROUTER_ENDPOINT).serve(
        service, metadata={"component": component}, lease=lease
    )
    logger.info("router service up for %s/%s (block_size=%d)", namespace, component, block_size)
    return service
