"""KV-aware worker selection: cost function + softmax sampling.

Cost per candidate worker (parity with reference `kv_router/scheduler.rs:298-360`):

    cost(w) = overlap_weight * new_blocks(w) / total_blocks
            + cache_usage(w)
            + waiting(w) / slots(w)

``new_blocks`` is the prefill work this worker would actually do after its
cached overlap; usage and queue depth keep load spread. Selection is softmax
over ``-cost / temperature`` (temperature 0 => deterministic argmin), which
probabilistically spreads near-ties instead of thundering-herding the single
best worker. A pluggable ``WorkerSelector`` hook mirrors the reference's
trait for custom policies.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Mapping

from dynamo_tpu.protocols.kv import ForwardPassMetrics
from dynamo_tpu.router.indexer import OverlapScores


@dataclass
class SchedulerConfig:
    overlap_weight: float = 1.0
    temperature: float = 0.0  # 0 => argmin cost
    seed: int | None = None
    # Attainment-aware term (dynamo_tpu/sched, DYN_SLO_SCHED): penalize
    # workers whose predicted TTFT at their current load eats into (or
    # blows past) the TTFT budget. 0 disables; ``profile`` must be a
    # planner.core.WorkerProfile for the term to engage (no profile, no
    # prediction — the base cost already spreads load).
    attainment_weight: float = 0.0
    ttft_slo_s: float = 0.5
    profile: object | None = None  # planner.core.WorkerProfile
    # Cache-aware term (DYN_CACHE_AWARE): add each worker's predicted
    # *residual prefill* — the seconds of prefill its cache misses imply,
    # normalized by the TTFT budget — so a worker already holding the
    # request's blocks wins even when base overlap scores near-tie. A
    # worker whose KV-event feed is staler than ``cache_max_staleness_s``
    # is priced as cold — a stale index claims overlap the worker may have
    # evicted, and placement must not chase ghosts. 0 weight disables
    # (bit-identical base cost).
    cache_aware_weight: float = 0.0
    cache_block_tokens: int = 16  # tokens per KV block (engine page_size)
    cache_rate_tokens_per_s: float = 20000.0  # assumed prefill throughput
    cache_max_staleness_s: float = 10.0


# (worker_id -> cost) -> chosen worker id
WorkerSelector = Callable[[dict[int, float]], int]


class KvScheduler:
    def __init__(self, config: SchedulerConfig | None = None, *, selector: WorkerSelector | None = None) -> None:
        self.config = config or SchedulerConfig()
        self._rng = random.Random(self.config.seed)
        self._selector = selector

    def costs(
        self,
        num_request_blocks: int,
        overlaps: OverlapScores,
        metrics: Mapping[int, ForwardPassMetrics],
        worker_ids: list[int],
        *,
        staleness: Mapping[int, float] | None = None,
    ) -> dict[int, float]:
        total = max(num_request_blocks, 1)
        cfg = self.config
        out: dict[int, float] = {}
        for wid in worker_ids:
            overlap = min(overlaps.scores.get(wid, 0), num_request_blocks)
            new_blocks = num_request_blocks - overlap
            m = metrics.get(wid)
            usage = m.cache_usage if m else 0.0
            waiting = (m.num_requests_waiting / max(m.request_total_slots, 1)) if m else 0.0
            cost = cfg.overlap_weight * (new_blocks / total) + usage + waiting
            if cfg.attainment_weight > 0 and cfg.profile is not None:
                # Predicted TTFT from the profiler surface at this worker's
                # reported load; stale metrics inflate the prediction (a
                # worker we haven't heard from is *assumed* busier, not
                # idler). ratio < 1 nudges toward slack; the extra
                # max(0, ratio-1) hinge makes predicted SLO misses hurt
                # twice — attainment, not raw latency, is the objective.
                load = (
                    (m.num_requests_running + m.num_requests_waiting)
                    / max(m.request_total_slots, 1)
                ) if m else 0.0
                pred = cfg.profile.ttft_at(min(load, 1.0), pct=99)
                if staleness:
                    pred *= 1.0 + min(staleness.get(wid, 0.0), 10.0)
                ratio = pred / max(cfg.ttft_slo_s, 1e-9)
                cost += cfg.attainment_weight * (ratio + max(0.0, ratio - 1.0))
            if cfg.cache_aware_weight > 0:
                # A worker whose KV-event feed is stale gets priced as cold
                # (full residual): its claimed overlap may be evicted ghosts,
                # and trusting it would *reward* staleness. When every
                # worker is stale the term is a constant and selection falls
                # back to the existing cost ordering.
                stale = (
                    staleness is not None
                    and staleness.get(wid, 0.0) > cfg.cache_max_staleness_s
                )
                eff_new = num_request_blocks if stale else new_blocks
                resid_s = (
                    eff_new * cfg.cache_block_tokens
                    / max(cfg.cache_rate_tokens_per_s, 1e-9)
                )
                cost += cfg.cache_aware_weight * (
                    resid_s / max(cfg.ttft_slo_s, 1e-9)
                )
            out[wid] = cost
        return out

    def select(self, costs: dict[int, float]) -> int:
        if not costs:
            raise ValueError("no candidate workers")
        if self._selector is not None:
            return self._selector(costs)
        if self.config.temperature <= 0:
            best = min(costs.values())
            # Deterministic tie-break on lowest id for reproducibility.
            return min(w for w, c in costs.items() if c == best)
        import math

        ids = list(costs)
        logits = [-costs[w] / self.config.temperature for w in ids]
        mx = max(logits)
        weights = [math.exp(l - mx) for l in logits]
        return self._rng.choices(ids, weights=weights, k=1)[0]

    def schedule(
        self,
        num_request_blocks: int,
        overlaps: OverlapScores,
        metrics: Mapping[int, ForwardPassMetrics],
        worker_ids: list[int],
        *,
        staleness: Mapping[int, float] | None = None,
    ) -> int:
        return self.select(
            self.costs(num_request_blocks, overlaps, metrics, worker_ids, staleness=staleness)
        )
