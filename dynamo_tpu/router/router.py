"""KvRouter: ties indexer + scheduler + metrics into a routing engine.

``KvPushRouter`` is the pipeline stage the frontend uses in ``kv`` router
mode: for each PreprocessedRequest it computes the prompt's chained block
hashes, asks the indexer for per-worker overlaps, scores candidates with the
scheduler, and opens the stream *direct* to the chosen worker instance.

Parity: reference `kv_router.rs:104-199,220` (KvRouter + KvPushRouter).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import PreprocessedRequest
from dynamo_tpu.router.events import KV_EVENTS_ENDPOINT, KvEventSubscriber
from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.metrics import KvMetricsAggregator
from dynamo_tpu.router.scheduler import KvScheduler, SchedulerConfig
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.tokens import compute_block_hashes

logger = logging.getLogger(__name__)


class KvRouter:
    """Scheduling brain: returns the best worker for a token sequence."""

    def __init__(
        self,
        indexer: KvIndexer,
        scheduler: KvScheduler,
        aggregator: KvMetricsAggregator | None,
        *,
        block_size: int,
        salt: int | None = None,
    ) -> None:
        self.indexer = indexer
        self.scheduler = scheduler
        self.aggregator = aggregator
        self.block_size = block_size
        self.salt = salt

    def schedule(self, token_ids: list[int], worker_ids: list[int], *, salt_fold: int = 0) -> tuple[int, int]:
        """Returns (worker_id, overlap_blocks) for the given prompt.

        ``salt_fold``: multimodal content hash (tokens.mm_salt_fold) so the
        lookup hashes match what the serving engine published."""
        from dynamo_tpu.tokens import DEFAULT_SALT

        base = self.salt if self.salt is not None else DEFAULT_SALT
        hashes = compute_block_hashes(token_ids, self.block_size, salt=base ^ salt_fold)
        overlaps = self.indexer.find_matches(hashes)
        metrics = self.aggregator.snapshot() if self.aggregator else {}
        stale = self.aggregator.staleness_seconds() if self.aggregator else None
        num_blocks = max(len(hashes), 1)
        wid = self.scheduler.schedule(num_blocks, overlaps, metrics, worker_ids, staleness=stale)
        return wid, overlaps.scores.get(wid, 0)


class KvPushRouter(AsyncEngine[Any, Any]):
    """Pipeline stage: route each request to its best worker, then go direct."""

    def __init__(self, client: Client, router: KvRouter) -> None:
        self.client = client
        self.router = router

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        body = request if isinstance(request, dict) else request.to_dict()
        token_ids = list(body.get("token_ids", []))
        await self.client.start()
        worker_ids = self.client.instance_ids()
        if not worker_ids:
            worker_ids = [i.instance_id for i in await self.client.wait_for_instances(count=1)]
        from dynamo_tpu.tokens import mm_salt_fold
        from dynamo_tpu.tracing import Span, trace_of

        with Span(
            "router_decision", trace=trace_of(context), request_id=context.id,
            candidates=len(worker_ids),
        ) as span:
            wid, overlap = self.router.schedule(
                token_ids, worker_ids, salt_fold=mm_salt_fold(body.get("mm_inputs"))
            )
            span.fields["worker"] = f"{wid:x}"
            span.fields["overlap_blocks"] = overlap
        logger.debug("kv-routed %d tokens -> worker %x (overlap %d blocks)", len(token_ids), wid, overlap)
        async for item in self.client.generate(body, context, instance_id=wid):
            yield item


async def build_kv_router(
    runtime: DistributedRuntime,
    *,
    namespace: str,
    component: str,
    endpoint: str = "generate",
    block_size: int,
    salt: int | None = None,
    scheduler_config: SchedulerConfig | None = None,
) -> tuple[KvPushRouter, KvEventSubscriber, KvMetricsAggregator]:
    """Assemble the full KV routing stack against a worker component."""
    indexer = KvIndexer()
    events_ep = runtime.namespace(namespace).component(component).endpoint(KV_EVENTS_ENDPOINT)
    subscriber = await KvEventSubscriber(events_ep, indexer).start()
    aggregator = await KvMetricsAggregator(runtime, namespace, component).start()
    if scheduler_config is None:
        # Default config picks up the SLO attainment term (no-op unless
        # DYN_SLO_SCHED is on) and the cache-aware residual term (no-op
        # unless DYN_CACHE_AWARE is on) from the environment; an explicit
        # config is the caller's to arm.
        from dynamo_tpu.sched import configure_attainment, configure_cache_aware

        scheduler_config = SchedulerConfig()
        configure_attainment(scheduler_config)
        configure_cache_aware(scheduler_config, block_tokens=block_size)
    scheduler = KvScheduler(scheduler_config)
    router = KvRouter(indexer, scheduler, aggregator, block_size=block_size, salt=salt)
    client = runtime.namespace(namespace).component(component).endpoint(endpoint).client(router_mode="direct")
    return KvPushRouter(client, router), subscriber, aggregator
