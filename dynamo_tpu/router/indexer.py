"""Global KV index: which worker holds which cache blocks.

The reference maintains an explicit radix tree over block hashes
(`kv_router/indexer.rs:187-441`). Here the *chained* sequence hash
(dynamo_tpu.tokens) already encodes the full prefix path in each block hash
— two workers share a hash iff they computed the same prefix — so the tree
collapses to a flat ``hash -> {workers}`` map, and ``find_matches`` walks the
request's hash chain in order, intersecting the live worker set. Same
observable behavior (consecutive-prefix overlap scores), O(1) event
application, trivially correct worker removal.

Events arrive ordered per worker (parents stored before children), tagged
with the emitting worker's instance id (`RouterEvent`).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Sequence

from dynamo_tpu.protocols.kv import KvCacheEvent, RouterEvent


@dataclass
class OverlapScores:
    """Per-worker count of consecutive leading blocks already cached."""

    scores: dict[int, int] = field(default_factory=dict)

    def best(self) -> tuple[int, int] | None:
        if not self.scores:
            return None
        wid = max(self.scores, key=lambda w: self.scores[w])
        return wid, self.scores[wid]


class KvIndexer:
    def __init__(self, *, ttl_seconds: float | None = None) -> None:
        self._blocks: dict[int, set[int]] = {}  # block_hash -> worker ids
        self._worker_blocks: dict[int, set[int]] = {}  # worker -> block hashes
        self._touched: dict[int, float] = {}  # block_hash -> last match time (expiry)
        self._ttl = ttl_seconds
        self.events_applied = 0
        self._queries = 0

    # -- event plane -------------------------------------------------------

    def apply_event(self, event: RouterEvent) -> None:
        wid = event.worker_id
        ev: KvCacheEvent = event.event
        self.events_applied += 1
        if ev.cleared:
            self.remove_worker(wid)
            return
        wb = self._worker_blocks.setdefault(wid, set())
        now = time.monotonic()
        for s in ev.stored:
            self._blocks.setdefault(s.block_hash, set()).add(wid)
            self._touched.setdefault(s.block_hash, now)
            wb.add(s.block_hash)
        for r in ev.removed:
            holders = self._blocks.get(r.block_hash)
            if holders is not None:
                holders.discard(wid)
                if not holders:
                    self._blocks.pop(r.block_hash, None)
                    self._touched.pop(r.block_hash, None)
            wb.discard(r.block_hash)

    def remove_worker(self, worker_id: int) -> None:
        for h in self._worker_blocks.pop(worker_id, ()):  # noqa: B020
            holders = self._blocks.get(h)
            if holders is not None:
                holders.discard(worker_id)
                if not holders:
                    self._blocks.pop(h, None)
                    self._touched.pop(h, None)

    # -- queries -----------------------------------------------------------

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        """Walk the chain; score[w] = number of leading blocks worker w holds."""
        # Amortized TTL enforcement: no separate maintenance task needed.
        self._queries += 1
        if self._ttl is not None and self._queries % 512 == 0:
            self.expire()
        now = time.monotonic()
        scores: dict[int, int] = {}
        alive: set[int] | None = None
        for i, h in enumerate(block_hashes):
            holders = self._blocks.get(h)
            if not holders:
                break
            self._touched[h] = now
            alive = set(holders) if alive is None else alive & holders
            if not alive:
                break
            for w in alive:
                scores[w] = i + 1
        return OverlapScores(scores)

    def expire(self) -> int:
        """Drop blocks not matched within the TTL (optional memory bound)."""
        if self._ttl is None:
            return 0
        cutoff = time.monotonic() - self._ttl
        stale = [h for h, t in self._touched.items() if t < cutoff]
        for h in stale:
            for w in self._blocks.pop(h, ()):  # noqa: B020
                self._worker_blocks.get(w, set()).discard(h)
            self._touched.pop(h, None)
        return len(stale)

    # -- introspection -----------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def worker_block_counts(self) -> dict[int, int]:
        return {w: len(b) for w, b in self._worker_blocks.items()}
