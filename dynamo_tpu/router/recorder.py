"""KV router event recorder: capture RouterEvents to JSONL and replay them.

Used for offline analysis of routing behavior and for tests that replay a
captured production event stream against a fresh indexer.

Parity: reference `kv_router/recorder.rs` / `lib/llm/src/recorder.rs:37-287`.
"""

from __future__ import annotations

import json
import pathlib
import time
from typing import Iterator

from dynamo_tpu.protocols.kv import RouterEvent


class KvRecorder:
    def __init__(self, path: str | pathlib.Path) -> None:
        self.path = pathlib.Path(path)
        self._fh = None
        self.count = 0

    def __enter__(self) -> "KvRecorder":
        self._fh = self.path.open("a")
        return self

    def __exit__(self, *exc) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def record(self, event: RouterEvent) -> None:
        if self._fh is None:
            raise RuntimeError("recorder not open (use as context manager)")
        self._fh.write(json.dumps({"ts": time.time(), **event.to_dict()}) + "\n")
        self.count += 1

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()


def replay(path: str | pathlib.Path) -> Iterator[tuple[float, RouterEvent]]:
    """Yield (timestamp, RouterEvent) from a recorded JSONL file."""
    with pathlib.Path(path).open() as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            ts = d.pop("ts", 0.0)
            yield ts, RouterEvent.from_dict(d)
