"""KV event plane: worker-side broadcast endpoint + router-side subscriber.

The engine is in-process (unlike the reference's ZMQ->NATS bridge,
`kv_router/publisher.rs`), so the worker wires its allocator's event callback
straight into a ``KvEventBroadcaster`` served on the worker's ``kv_events``
endpoint. The router discovers worker instances and holds one server-stream
per worker; instance death (lease expiry) removes the worker's blocks from
the index.

A monotonically increasing per-worker sequence number lets subscribers detect
gaps (a reconnect after missed events must resync by clearing that worker).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.kv import KvCacheEvent, RouterEvent
from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.runtime.component import Endpoint, Instance, instance_prefix
from dynamo_tpu.runtime.discovery import WatchEventType
from dynamo_tpu.runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

KV_EVENTS_ENDPOINT = "kv_events"

#: Index reconstructions this process has performed: every snapshot rebase
#: (fresh subscription — including each one a restarted frontend issues) and
#: every gap-forced resync. Sync-on-render source for the frontend's
#: ``dynamo_router_index_resyncs_total`` gauge; the counter being per-process
#: is the point — a bounced frontend proves reconstruction by counting again
#: from zero.
_RESYNCS = 0


def router_resync_snapshot() -> dict:
    return {"resyncs": _RESYNCS}


def _count_resync() -> None:
    global _RESYNCS
    _RESYNCS += 1


class KvEventBroadcaster(AsyncEngine[Any, dict]):
    """Fans the engine's KV events out to any number of stream subscribers.

    Serves the ``kv_events`` endpoint: a subscriber calls ``generate({})`` and
    receives an infinite stream of `{"seq": n, "event": {...}}` messages.
    """

    def __init__(self, snapshot_fn=None) -> None:
        """``snapshot_fn() -> KvCacheEvent`` re-announces current cache
        contents to each new subscriber (reconnect-safe; see allocator
        ``cache_snapshot``)."""
        self._subscribers: set[asyncio.Queue] = set()
        self._seq = 0
        self._snapshot_fn = snapshot_fn
        self._loop: asyncio.AbstractEventLoop | None = None

    def publish(self, event: KvCacheEvent) -> None:
        """Engine-side callback (may be called from the engine's step thread)."""
        if self._loop is None:
            try:
                self._loop = asyncio.get_running_loop()
            except RuntimeError:
                self._loop = None
        msg = {"seq": self._seq, "event": event.to_dict()}
        self._seq += 1
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None and (self._loop is None or running is self._loop):
            # Already on the subscribers' loop: deliver in order, immediately
            # (deferring would let a pre-subscribe event leak into a new
            # subscription after its snapshot).
            self._fanout(msg)
        elif self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._fanout, msg)
        else:
            self._fanout(msg)

    def bind_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def bind_snapshot(self, snapshot_fn) -> None:
        self._snapshot_fn = snapshot_fn

    def _fanout(self, msg: dict) -> None:
        for q in list(self._subscribers):
            q.put_nowait(msg)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        q: asyncio.Queue = asyncio.Queue()
        self._subscribers.add(q)
        try:
            # First message: a snapshot of everything currently cached, stamped
            # with the subscription's starting sequence number. Seq is read
            # BEFORE the snapshot so an event racing in between is delivered
            # normally afterwards (re-applying stored blocks is idempotent).
            seq0 = self._seq
            snapshot = KvCacheEvent()
            if self._snapshot_fn is not None:
                for _ in range(5):  # engine thread may mutate mid-iteration
                    try:
                        snapshot = self._snapshot_fn()
                        break
                    except RuntimeError:
                        await asyncio.sleep(0.01)
            yield {"seq": seq0, "snapshot": True, "event": snapshot.to_dict()}
            while not context.is_stopped:
                get = asyncio.ensure_future(q.get())
                stop = asyncio.ensure_future(context.wait_stopped())
                done, pending = await asyncio.wait({get, stop}, return_when=asyncio.FIRST_COMPLETED)
                for p in pending:
                    p.cancel()
                if get in done:
                    yield get.result()
                else:
                    return
        finally:
            self._subscribers.discard(q)


class KvEventSubscriber:
    """Router side: one stream per live worker instance, feeding the indexer."""

    def __init__(self, endpoint: Endpoint, indexer: KvIndexer) -> None:
        from dynamo_tpu.config import load_router_resync_settings

        self.endpoint = endpoint
        self.indexer = indexer
        self._resync = load_router_resync_settings()
        self._tasks: dict[int, asyncio.Task] = {}
        self._watch_task: asyncio.Task | None = None

    async def start(self) -> "KvEventSubscriber":
        if self._watch_task is None:
            ep = self.endpoint
            prefix = instance_prefix(ep.namespace, ep.component, ep.name)
            for value in (await ep.runtime.store.get_prefix(prefix)).values():
                self._add(Instance.from_bytes(value))
            self._watch_task = asyncio.create_task(self._watch(prefix))
        return self

    def _add(self, inst: Instance) -> None:
        if inst.instance_id in self._tasks:
            return
        self._tasks[inst.instance_id] = asyncio.create_task(self._consume(inst))

    def _drop(self, worker_id: int) -> None:
        task = self._tasks.pop(worker_id, None)
        if task is not None:
            task.cancel()
        self.indexer.remove_worker(worker_id)

    async def _watch(self, prefix: str) -> None:
        try:
            async for event in self.endpoint.runtime.store.watch_prefix(prefix):
                if event.type is WatchEventType.PUT and event.value is not None:
                    self._add(Instance.from_bytes(event.value))
                elif event.type is WatchEventType.DELETE:
                    self._drop(int(event.key.rsplit(":", 1)[-1], 16))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("kv event instance watch failed")

    async def _consume(self, inst: Instance) -> None:
        wid = inst.instance_id
        transport = self.endpoint.runtime.transport
        backoff = self._resync.backoff_s
        while True:
            expected_seq = 0
            try:
                ctx = Context()
                async for msg in transport.generate(inst.address, {}, ctx):
                    seq = msg.get("seq", expected_seq)
                    if msg.get("snapshot"):
                        # Fresh subscription: rebase our view on the snapshot.
                        # This is the reconstruction path — a restarted
                        # frontend rebuilds its whole prefix index from these.
                        self.indexer.remove_worker(wid)
                        _count_resync()
                        expected_seq = seq
                    elif seq != expected_seq:
                        # Missed events: our view of this worker is stale; the
                        # next reconnect snapshot will rebuild it.
                        logger.warning("kv event gap for worker %x (%d != %d); resync", wid, seq, expected_seq)
                        self.indexer.remove_worker(wid)
                        _count_resync()
                        expected_seq = seq
                    if not msg.get("snapshot"):
                        expected_seq += 1
                    self.indexer.apply_event(RouterEvent(wid, KvCacheEvent.from_dict(msg["event"])))
                    backoff = self._resync.backoff_s
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if wid not in self._tasks:
                    return
                logger.info("kv event stream to %x dropped (%s); retrying", wid, exc)
                self.indexer.remove_worker(wid)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self._resync.max_backoff_s)

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
