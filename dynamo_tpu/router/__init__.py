"""KV-cache-aware request routing.

The router keeps a live world-model of every worker's prefix cache (fed by
the KV event plane) plus load metrics, and schedules each request to the
worker where prefill cost is lowest:

- :mod:`dynamo_tpu.router.indexer` — global block-hash index per worker
  (the reference's RadixTree; hash chaining makes an explicit trie
  unnecessary here — see module docstring).
- :mod:`dynamo_tpu.router.scheduler` — cost = overlap-weighted new blocks +
  cache usage + queue depth, softmax-sampled with temperature.
- :mod:`dynamo_tpu.router.events` — worker-side event broadcast endpoint +
  router-side subscriber.
- :mod:`dynamo_tpu.router.metrics` — ForwardPassMetrics publisher/aggregator.
- :mod:`dynamo_tpu.router.router` — KvRouter + the KvPushRouter engine that
  plugs into the frontend pipeline.
- :mod:`dynamo_tpu.router.recorder` — JSONL event record/replay.

Parity: reference `lib/llm/src/kv_router/*` (SURVEY.md §2 rows 22-26).
"""

from dynamo_tpu.router.indexer import KvIndexer, OverlapScores
from dynamo_tpu.router.scheduler import KvScheduler, SchedulerConfig
from dynamo_tpu.router.router import KvRouter, KvPushRouter

__all__ = [
    "KvIndexer",
    "OverlapScores",
    "KvScheduler",
    "SchedulerConfig",
    "KvRouter",
    "KvPushRouter",
]
