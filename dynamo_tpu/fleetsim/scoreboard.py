"""Measurement plane: open-loop injection, SLO ledger, control-plane scrape.

The client is **open-loop**: every trace event fires at its scheduled time
regardless of how many earlier requests are still in flight, and TTFT is
measured from the *intended* injection time, not from when the send
actually left. A closed-loop (or send-clocked) measurement hides stalls —
when the server wedges, a closed loop simply stops offering load and the
recorded latencies stay rosy (coordinated omission). Here a wedged second
shows up as exactly the tail inflation a real user population would see.

Tails come from P² streaming estimators (``observability/slo.py``) at
p50/p95/p99/p99.9 — fleet runs are long enough that keeping every sample
is wasteful and fixed histogram buckets would distort the exact quantiles
the SLO is stated on.

Per-request SLO classification reuses the frontend's accountant semantics
(TTFT within target AND the request's own p99 inter-token gap within
target); goodput is tokens from attaining, successful requests. Per-tenant
ledgers give attainment and the fairness ratio (min/max across tenants).

Control-plane behavior (breaker trips, watch restarts, prefill requeues,
live engine registries) is scraped from the frontend's federated
``/metrics`` by a background poller — peak values survive even when the
condition heals before the run ends.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time

import aiohttp

from dynamo_tpu.fleetsim.trace import TraceEvent
from dynamo_tpu.observability.slo import StreamingQuantiles, percentile

logger = logging.getLogger(__name__)

QUANTILES = (0.5, 0.95, 0.99, 0.999)


@dataclasses.dataclass
class RequestOutcome:
    request_id: str
    tenant: str
    injected_at_s: float  # intended injection offset (trace time)
    ttft_s: float
    gaps: list[float]
    output_tokens: int
    ok: bool
    mid_stream_failure: bool = False
    error: str = ""


@dataclasses.dataclass
class SloTarget:
    ttft_ms: float = 500.0
    itl_p99_ms: float = 50.0


class _TenantLedger:
    def __init__(self) -> None:
        self.requests = 0
        self.attained = 0
        self.goodput_tokens = 0
        self.output_tokens = 0

    def frac(self) -> float:
        return self.attained / self.requests if self.requests else 0.0


class Scoreboard:
    """Folds request outcomes + control-plane scrapes into one report."""

    def __init__(self, slo: SloTarget | None = None) -> None:
        self.slo = slo or SloTarget()
        self.ttft = StreamingQuantiles(QUANTILES)
        self.itl = StreamingQuantiles(QUANTILES)
        self.outcomes: list[RequestOutcome] = []
        self.tenants: dict[str, _TenantLedger] = {}
        self.attained = 0
        self.goodput_tokens = 0
        self.output_tokens = 0
        self.mid_stream_failures = 0
        self.errors = 0
        # Peak/final control-plane counters from the /metrics poller.
        self.scrape: dict[str, float] = {
            "breaker_open_max": 0.0, "watch_restarts": 0.0,
            "prefill_requeues": 0.0, "engine_registries_max": 0.0,
            # HA control plane: failover/retry peaks plus the *final* values
            # of the reconstruction signals — a frontend bounce resets the
            # registry, so "what the last scrape saw" is exactly "what the
            # replacement frontend rebuilt".
            "store_failovers": 0.0, "store_client_retries": 0.0,
            "router_resyncs_final": 0.0, "cached_tokens_final": 0.0,
        }
        # Fleet-wide time-loss ledger, folded from the same poller: seconds
        # lost per cause, step-time composition (wall/dispatch/gap), and the
        # anomaly sentinel's fired counters — all max-folded so the peak
        # survives worker churn shrinking the federated sum.
        self.lost_time_s: dict[str, float] = {}
        self.step_time_s: dict[str, float] = {}
        self.anomaly_fired: dict[str, float] = {}
        self.anomaly_active_max: dict[str, float] = {}
        self.planner_decisions: list[dict] = []

    # -- per-request accounting --------------------------------------------

    def observe(self, out: RequestOutcome) -> None:
        self.outcomes.append(out)
        ledger = self.tenants.setdefault(out.tenant, _TenantLedger())
        ledger.requests += 1
        if out.mid_stream_failure:
            self.mid_stream_failures += 1
        if not out.ok:
            self.errors += 1
            return
        self.ttft.observe(out.ttft_s)
        for g in out.gaps:
            self.itl.observe(g)
        self.output_tokens += out.output_tokens
        ledger.output_tokens += out.output_tokens
        ttft_ok = out.ttft_s * 1e3 <= self.slo.ttft_ms
        itl_ok = (
            percentile(sorted(out.gaps), 0.99) * 1e3 <= self.slo.itl_p99_ms
            if out.gaps else True
        )
        if ttft_ok and itl_ok:
            self.attained += 1
            self.goodput_tokens += out.output_tokens
            ledger.attained += 1
            ledger.goodput_tokens += out.output_tokens

    # -- report ------------------------------------------------------------

    def tenant_fairness(self) -> float:
        fracs = [t.frac() for t in self.tenants.values() if t.requests]
        if not fracs:
            return 1.0
        hi = max(fracs)
        return min(fracs) / hi if hi > 0 else 0.0

    def top_loss_causes(self, n: int = 5) -> list[dict]:
        ranked = sorted(self.lost_time_s.items(), key=lambda kv: -kv[1])
        return [
            {"cause": cause, "seconds": round(sec, 3)}
            for cause, sec in ranked[:n] if sec > 0.0
        ]

    def loss_accounting(self) -> dict:
        """Lost-time coverage: how much non-compute wall the ledger explains.

        Non-compute wall = step wall + inter-step gap - device dispatch.
        The step-side ledger excludes queue/admission (those waits happen
        before the step loop and are not part of step wall)."""
        wall = self.step_time_s.get("wall", 0.0)
        gap = self.step_time_s.get("gap", 0.0)
        dispatch = self.step_time_s.get("dispatch", 0.0)
        noncompute = max(0.0, wall + gap - dispatch)
        step_lost = sum(
            sec for cause, sec in self.lost_time_s.items()
            if cause not in ("queue", "admission")
        )
        unattributed = max(0.0, noncompute - step_lost)
        return {
            "noncompute_wall_s": round(noncompute, 3),
            "step_lost_s": round(step_lost, 3),
            "lost_s_total": round(sum(self.lost_time_s.values()), 3),
            "unattributed_frac": round(
                unattributed / noncompute, 4) if noncompute > 0 else 0.0,
            "top_loss_causes": self.top_loss_causes(),
        }

    def report(self, *, duration_s: float) -> dict:
        total = len(self.outcomes)
        ok = total - self.errors

        def q_ms(qs: StreamingQuantiles) -> dict:
            return {
                ("p" + format(q * 100, "g").replace(".", "_")): round(v * 1e3, 3)
                for q, v in qs.snapshot().items()
            }

        return {
            "duration_s": round(duration_s, 3),
            "requests": {
                "total": total, "ok": ok, "error": self.errors,
                "mid_stream_failure": self.mid_stream_failures,
            },
            "goodput_frac_at_slo": round(self.attained / total, 4) if total else 0.0,
            "goodput_tokens_per_s_at_slo": round(
                self.goodput_tokens / duration_s, 2) if duration_s > 0 else 0.0,
            "output_tokens_total": self.output_tokens,
            "ttft_ms": q_ms(self.ttft),
            "itl_ms": q_ms(self.itl),
            "slo": {"ttft_ms": self.slo.ttft_ms, "itl_p99_ms": self.slo.itl_p99_ms},
            "tenants": {
                name: {
                    "requests": t.requests,
                    "goodput_frac": round(t.frac(), 4),
                    "goodput_tokens": t.goodput_tokens,
                    "output_tokens": t.output_tokens,
                }
                for name, t in sorted(self.tenants.items())
            },
            "tenant_fairness": round(self.tenant_fairness(), 4),
            "control_plane": {k: v for k, v in self.scrape.items()},
            "loss": self.loss_accounting(),
            "anomalies": {
                "fired_total": round(sum(self.anomaly_fired.values())),
                "by_kind": {
                    k: round(v) for k, v in sorted(self.anomaly_fired.items()) if v > 0
                },
                "active_peak": {
                    k: round(v) for k, v in sorted(self.anomaly_active_max.items()) if v > 0
                },
            },
            "planner": {
                "decisions": self.planner_decisions,
                "max_decode_workers": max(
                    (d["decode_workers"] for d in self.planner_decisions), default=0),
                "final_decode_workers": (
                    self.planner_decisions[-1]["decode_workers"]
                    if self.planner_decisions else 0),
            },
        }


# -- open-loop client ------------------------------------------------------


async def _one_request(
    session: aiohttp.ClientSession,
    base: str,
    model: str,
    ev: TraceEvent,
    intended_t: float,
) -> RequestOutcome:
    """Stream one completion; clock TTFT/done from ``intended_t`` (the
    loop-time instant the trace scheduled this arrival)."""
    loop = asyncio.get_running_loop()
    body = {
        "model": model,
        "prompt": ev.token_ids,
        "max_tokens": ev.max_tokens,
        "temperature": 0,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    headers = {"x-dynamo-tenant": ev.tenant}
    ttft = 0.0
    gaps: list[float] = []
    chunks = 0
    usage_tokens = None
    prev = None
    mid_stream = False
    error = ""
    try:
        async with session.post(f"{base}/v1/completions", json=body, headers=headers) as resp:
            if resp.status != 200:
                return RequestOutcome(
                    ev.request_id, ev.tenant, ev.t_s, 0.0, [], 0, ok=False,
                    error=f"http {resp.status}",
                )
            async for line in resp.content:
                if not line.startswith(b"data:"):
                    continue
                payload = line[5:].strip()
                if payload == b"[DONE]":
                    continue
                now = loop.time()
                try:
                    obj = json.loads(payload)
                except Exception:
                    continue
                if "error" in obj:
                    code = (obj["error"] or {}).get("code", "")
                    mid_stream = mid_stream or code == "mid_stream_failure"
                    error = code or "stream_error"
                    continue
                usage = obj.get("usage")
                if usage and usage.get("completion_tokens"):
                    usage_tokens = usage["completion_tokens"]
                if prev is None:
                    ttft = now - intended_t  # open-loop: from intended arrival
                else:
                    gaps.append(now - prev)
                prev = now
                chunks += 1
    except Exception as exc:
        return RequestOutcome(
            ev.request_id, ev.tenant, ev.t_s, 0.0, [], 0, ok=False,
            mid_stream_failure=mid_stream or prev is not None,
            error=error or f"{type(exc).__name__}",
        )
    tokens = usage_tokens if usage_tokens is not None else chunks
    if chunks > 1 and tokens > chunks:
        # Burst streaming (decode_steps > 1): normalize gaps to per-token.
        gaps = [g * chunks / tokens for g in gaps]
    if error:
        return RequestOutcome(
            ev.request_id, ev.tenant, ev.t_s, ttft, gaps, tokens, ok=False,
            mid_stream_failure=mid_stream, error=error,
        )
    return RequestOutcome(ev.request_id, ev.tenant, ev.t_s, ttft, gaps, tokens, ok=True)


async def run_open_loop(
    base: str,
    model: str,
    events: list[TraceEvent],
    scoreboard: Scoreboard,
    *,
    t0: float | None = None,
    request_timeout_s: float = 120.0,
) -> None:
    """Replay ``events`` open-loop against the frontend at ``base``.

    ``t0`` is the loop-time origin of the scenario clock (shared with the
    churn script); injection of event ``e`` is scheduled at ``t0 + e.t_s``
    no matter what earlier requests are doing.
    """
    loop = asyncio.get_running_loop()
    t0 = loop.time() if t0 is None else t0
    connector = aiohttp.TCPConnector(limit=0)  # open loop: no client-side cap
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)
    async with aiohttp.ClientSession(connector=connector, timeout=timeout) as session:

        async def one(ev: TraceEvent) -> RequestOutcome:
            intended = t0 + ev.t_s
            delay = intended - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            return await _one_request(session, base, model, ev, intended)

        for out in await asyncio.gather(*(one(ev) for ev in events)):
            scoreboard.observe(out)


# -- federated /metrics scrape ---------------------------------------------


def _label(rest: str, key: str) -> str | None:
    marker = key + '="'
    if marker not in rest:
        return None
    return rest.split(marker, 1)[1].split('"', 1)[0]


def parse_control_plane(text: str) -> dict:
    """Pull the control-plane counters out of a federated /metrics body.

    Besides the scalar counters, folds the attribution families across all
    workers: lost seconds per ``cause``, step-time seconds per ``kind``,
    and the anomaly sentinel's active/fired gauges per ``kind``."""
    breaker_open = 0
    watch_restarts = 0.0
    requeues = 0.0
    router_resyncs = 0.0
    store_failovers = 0.0
    store_client_retries = 0.0
    cached_tokens = 0.0
    engine_workers: set[str] = set()
    lost_time: dict[str, float] = {}
    step_time: dict[str, float] = {}
    anomaly_active: dict[str, float] = {}
    anomaly_fired: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, rest = line.partition("{") if "{" in line else (line.split()[0], "", line)
        try:
            value = float(line.rsplit(None, 1)[-1])
        except ValueError:
            continue
        if name == "dynamo_client_breaker_state" and value >= 2.0:
            breaker_open += 1
        elif name == "dynamo_client_watch_restarts_total":
            watch_restarts += value
        elif name == "dynamo_engine_lost_time_seconds_total":
            cause = _label(rest, "cause")
            if cause is not None:
                lost_time[cause] = lost_time.get(cause, 0.0) + value
        elif name == "dynamo_engine_step_time_seconds_total":
            kind = _label(rest, "kind")
            if kind is not None:
                step_time[kind] = step_time.get(kind, 0.0) + value
        elif name == "dynamo_anomaly_active":
            kind = _label(rest, "kind")
            if kind is not None:
                anomaly_active[kind] = anomaly_active.get(kind, 0.0) + value
        elif name == "dynamo_anomaly_fired_total":
            kind = _label(rest, "kind")
            if kind is not None:
                anomaly_fired[kind] = anomaly_fired.get(kind, 0.0) + value
        elif name.startswith("dynamo_engine_prefill_requeues"):
            requeues += value
        elif name == "dynamo_router_index_resyncs_total":
            router_resyncs += value
        elif name == "dynamo_store_failovers_total":
            store_failovers += value
        elif name == "dynamo_store_client_op_retries_total":
            store_client_retries += value
        elif name == "dynamo_frontend_cached_prompt_tokens_total":
            cached_tokens += value  # summed across model labels
        if name.startswith("dynamo_engine_") and 'worker="' in rest:
            engine_workers.add(rest.split('worker="', 1)[1].split('"', 1)[0])
    return {
        "breaker_open": float(breaker_open),
        "watch_restarts": watch_restarts,
        "prefill_requeues": requeues,
        "router_resyncs": router_resyncs,
        "store_failovers": store_failovers,
        "store_client_retries": store_client_retries,
        "cached_tokens": cached_tokens,
        "engine_registries": float(len(engine_workers)),
        "lost_time_s": lost_time,
        "step_time_s": step_time,
        "anomaly_active": anomaly_active,
        "anomaly_fired": anomaly_fired,
    }


async def poll_control_plane(
    base: str, scoreboard: Scoreboard, *, interval_s: float = 1.0
) -> None:
    """Scrape the federated /metrics until cancelled, folding peaks and
    finals into the scoreboard (breaker trips recover; peaks must not)."""
    async with aiohttp.ClientSession() as session:
        while True:
            try:
                async with session.get(f"{base}/metrics") as resp:
                    if resp.status == 200:
                        snap = parse_control_plane(await resp.text())
                        s = scoreboard.scrape
                        s["breaker_open_max"] = max(s["breaker_open_max"], snap["breaker_open"])
                        s["watch_restarts"] = max(s["watch_restarts"], snap["watch_restarts"])
                        s["prefill_requeues"] = max(s["prefill_requeues"], snap["prefill_requeues"])
                        s["engine_registries_max"] = max(
                            s["engine_registries_max"], snap["engine_registries"])
                        s["store_failovers"] = max(
                            s["store_failovers"], snap["store_failovers"])
                        s["store_client_retries"] = max(
                            s["store_client_retries"], snap["store_client_retries"])
                        # Last-seen, not max: cached tokens live in the
                        # frontend registry and reset when a bounce rebuilds
                        # it, so the final value is what the *replacement*
                        # frontend served warm; resyncs are process-global
                        # and only grow, so last-seen == total either way.
                        s["router_resyncs_final"] = snap["router_resyncs"]
                        s["cached_tokens_final"] = snap["cached_tokens"]
                        # Cumulative families max-fold per key: monotone
                        # within a worker, and the peak survives a dead
                        # worker dropping out of the federated sum.
                        for dst, key in (
                            (scoreboard.lost_time_s, "lost_time_s"),
                            (scoreboard.step_time_s, "step_time_s"),
                            (scoreboard.anomaly_fired, "anomaly_fired"),
                            (scoreboard.anomaly_active_max, "anomaly_active"),
                        ):
                            for k, v in snap[key].items():
                                dst[k] = max(dst.get(k, 0.0), v)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # scrape failures must not kill the run
                logger.debug("metrics scrape failed: %s", exc)
            await asyncio.sleep(interval_s)


def wall_clock() -> float:
    """Report-stamp helper (kept here so scenario code avoids bare time)."""
    return time.time()
