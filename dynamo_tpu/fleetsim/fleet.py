"""Fleet plane: tens of mock workers as real OS processes.

Each worker is a ``python -m dynamo_tpu.launch --role worker --mock``
subprocess joined to the scenario's store — the same spawn contract as the
planner's :class:`~dynamo_tpu.planner.connector.LocalProcessConnector`, but
with the fidelity the fleet scenarios need on top:

- **per-worker timing profiles** (:class:`WorkerTimingProfile` → the
  mocker's ``DYN_MOCK_*`` env overlay): heterogeneous speeds, jitter, and
  cold-start warm-up ramps, so planner scale-ups see realistic TTFT;
- **full lifecycle control**: spawn (wait for READY), ``drain`` (SIGTERM →
  the launch CLI's graceful drain: draining=True republish, in-flight work
  finishes, lease revoked), ``kill`` (SIGKILL → crash; lease expiry cleans
  up, mid-stream requests see the structured failure SSE);
- **planner actuation**: the manager implements the planner ``Connector``
  protocol, so a ``PlannerLoop`` scales this fleet directly;
- **scripted churn**: a timed kill/drain/spawn schedule running alongside
  the trace (:class:`ChurnEvent`), chaos faults armed via ``DYN_FAULTS`` in
  each worker's environment.

Process-per-worker is the point, not an implementation detail: on a 1-core
host an in-process fleet serializes on the GIL and flattens every latency
measurement (the r06 striping sweep hit exactly this), while mock workers
in separate processes spend their time in ``time.sleep`` and interleave
like a real fleet.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import signal
import socket
import subprocess
import sys
import threading

from dynamo_tpu.config import load_fleet_settings
from dynamo_tpu.planner.core import PlanDecision

logger = logging.getLogger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _wait_ready_line(proc: subprocess.Popen, what: str, timeout: float) -> None:
    """Block until the subprocess prints its READY line (or fail loudly)."""

    def read() -> None:
        while True:
            line = proc.stdout.readline() if proc.stdout else ""
            if not line:
                raise RuntimeError(f"{what} pid={proc.pid} exited rc={proc.poll()} before READY")
            if line.startswith("READY"):
                return

    try:
        await asyncio.wait_for(asyncio.get_running_loop().run_in_executor(None, read), timeout)
    except (asyncio.TimeoutError, TimeoutError):
        proc.kill()
        raise TimeoutError(f"{what} pid={proc.pid} not READY in {timeout}s") from None


@dataclasses.dataclass(frozen=True)
class WorkerTimingProfile:
    """One worker's timing model, carried to the subprocess as env."""

    prefill_us_per_token: float = 50.0
    decode_us_base: float = 2000.0
    decode_us_per_seq: float = 100.0
    jitter: float = 0.0  # lognormal sigma on per-step compute (0 = exact)
    warmup_s: float = 0.0  # cold-start ramp duration (0 = instant capacity)
    warmup_factor: float = 1.0  # compute multiplier at t=0, decaying to 1.0
    seed: int = 0

    def to_env(self) -> dict[str, str]:
        return {
            "DYN_MOCK_PREFILL_US_PER_TOKEN": str(self.prefill_us_per_token),
            "DYN_MOCK_DECODE_US_BASE": str(self.decode_us_base),
            "DYN_MOCK_DECODE_US_PER_SEQ": str(self.decode_us_per_seq),
            "DYN_MOCK_JITTER": str(self.jitter),
            "DYN_MOCK_WARMUP_S": str(self.warmup_s),
            "DYN_MOCK_WARMUP_FACTOR": str(self.warmup_factor),
            "DYN_MOCK_SEED": str(self.seed),
        }


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """A scripted fleet mutation at ``at_s`` seconds into the scenario."""

    at_s: float
    action: str  # "kill" | "drain" | "spawn"
    count: int = 1
    # Index into the live fleet for kill/drain. -1 = youngest; 0 = oldest —
    # the one KV-affinity concentrates shared-prefix streams on, so kill @ 0
    # is the "worker with work in flight" case.
    which: int = -1


@dataclasses.dataclass
class WorkerHandle:
    proc: subprocess.Popen
    profile: WorkerTimingProfile
    index: int  # stable spawn ordinal (profile assignment, logs)


class FleetManager:
    """Owns the worker subprocesses of one scenario run.

    Implements the planner ``Connector`` protocol (``apply``/``close``) so a
    ``PlannerLoop`` can drive the same fleet the churn script mutates.
    """

    def __init__(
        self,
        *,
        store_url: str,
        model: str = "test-tiny",
        host: str = "127.0.0.1",
        router_mode: str = "kv",
        base_env: dict[str, str] | None = None,
        profiles: tuple[WorkerTimingProfile, ...] = (),
        spawn_timeout: float | None = None,
        drain_timeout: float | None = None,
    ) -> None:
        settings = load_fleet_settings()
        self.store_url = store_url
        self.model = model
        self.host = host
        self.router_mode = router_mode
        self.base_env = dict(base_env or {})
        self.profiles = tuple(profiles)
        self.spawn_timeout = spawn_timeout if spawn_timeout is not None else settings.spawn_timeout_s
        self.drain_timeout = drain_timeout if drain_timeout is not None else settings.drain_timeout_s
        self.workers: list[WorkerHandle] = []
        self._spawned_total = 0
        self.counters = {"spawns": 0, "kills": 0, "drains": 0,
                         "scale_ups": 0, "scale_downs": 0}

    # -- spawn -------------------------------------------------------------

    def _profile_for(self, ordinal: int) -> WorkerTimingProfile:
        if not self.profiles:
            return WorkerTimingProfile(seed=ordinal)
        p = self.profiles[ordinal % len(self.profiles)]
        # Distinct jitter streams per worker even when profiles repeat.
        return dataclasses.replace(p, seed=p.seed + ordinal)

    def _spawn_one(self) -> WorkerHandle:
        import dynamo_tpu

        ordinal = self._spawned_total
        self._spawned_total += 1
        profile = self._profile_for(ordinal)
        cmd = [
            sys.executable, "-m", "dynamo_tpu.launch",
            "--role", "worker", "--model", self.model,
            "--store", self.store_url, "--host", self.host,
            "--router-mode", self.router_mode, "--mock",
        ]
        env = dict(os.environ)
        env.update(self.base_env)
        env.update(profile.to_env())
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(dynamo_tpu.__file__)))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                                text=True, env=env)
        logger.info("fleet: spawned worker #%d pid=%d", ordinal, proc.pid)
        return WorkerHandle(proc=proc, profile=profile, index=ordinal)

    async def _wait_ready(self, handle: WorkerHandle) -> None:
        proc = handle.proc

        def read() -> None:
            while True:
                line = proc.stdout.readline() if proc.stdout else ""
                if not line:
                    raise RuntimeError(
                        f"worker #{handle.index} pid={proc.pid} exited rc={proc.poll()} before READY"
                    )
                if line.startswith("READY"):
                    return

        try:
            await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(None, read), self.spawn_timeout
            )
        except (asyncio.TimeoutError, TimeoutError):
            proc.kill()  # EOFs the pipe, unblocking the reader thread
            raise TimeoutError(
                f"worker #{handle.index} pid={proc.pid} not READY in {self.spawn_timeout}s"
            ) from None
        # Keep the pipe drained for life: a full 64KB pipe would eventually
        # block the worker's own log writes and wedge it mid-serve.
        threading.Thread(target=self._drain_pipe, args=(proc,), daemon=True).start()

    @staticmethod
    def _drain_pipe(proc: subprocess.Popen) -> None:
        try:
            while proc.stdout and proc.stdout.readline():
                pass
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    async def spawn_workers(self, n: int) -> list[WorkerHandle]:
        """Spawn ``n`` workers and wait for all READY lines concurrently
        (cold starts overlap instead of serializing)."""
        handles = [self._spawn_one() for _ in range(n)]
        results = await asyncio.gather(
            *(self._wait_ready(h) for h in handles), return_exceptions=True
        )
        failures: list[BaseException] = []
        for h, r in zip(handles, results):
            if isinstance(r, BaseException):
                logger.error("fleet: worker #%d failed to start: %s", h.index, r)
                if h.proc.poll() is None:
                    h.proc.kill()
                failures.append(r)
            else:
                self.workers.append(h)
                self.counters["spawns"] += 1
        if failures:
            raise failures[0]
        return handles

    # -- lifecycle ---------------------------------------------------------

    def reap(self) -> None:
        self.workers = [h for h in self.workers if h.proc.poll() is None]

    def live_count(self) -> int:
        self.reap()
        return len(self.workers)

    def kill(self, which: int = -1) -> WorkerHandle | None:
        """SIGKILL a live worker (default: the youngest). A crash, not a
        shutdown: lease expiry removes its records, in-flight streams get
        the structured mid_stream_failure SSE."""
        self.reap()
        if not self.workers:
            return None
        handle = self.workers.pop(which)
        handle.proc.kill()
        self.counters["kills"] += 1
        logger.info("fleet: killed worker #%d pid=%d", handle.index, handle.proc.pid)
        return handle

    async def drain(self, which: int = -1) -> WorkerHandle | None:
        """SIGTERM a live worker (default: the youngest) and wait for the
        launch CLI's graceful drain to finish, escalating to SIGKILL at the
        drain deadline."""
        self.reap()
        if not self.workers:
            return None
        handle = self.workers.pop(which)
        handle.proc.send_signal(signal.SIGTERM)
        self.counters["drains"] += 1

        def wait() -> None:
            try:
                handle.proc.wait(timeout=self.drain_timeout)
            except subprocess.TimeoutExpired:
                logger.warning("fleet: drain deadline hit for worker #%d; killing", handle.index)
                handle.proc.kill()
                handle.proc.wait(timeout=5)

        await asyncio.get_running_loop().run_in_executor(None, wait)
        logger.info("fleet: drained worker #%d", handle.index)
        return handle

    async def run_churn(self, events: list[ChurnEvent], t0: float) -> None:
        """Execute a churn script against the scenario clock (``t0`` is the
        loop-time origin shared with the open-loop client)."""
        loop = asyncio.get_running_loop()
        for ev in sorted(events, key=lambda e: e.at_s):
            delay = ev.at_s - (loop.time() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            for _ in range(ev.count):
                if ev.action == "kill":
                    self.kill(ev.which)
                elif ev.action == "drain":
                    await self.drain(ev.which)
                elif ev.action == "spawn":
                    await self.spawn_workers(1)
                else:
                    raise ValueError(f"unknown churn action {ev.action!r}")

    # -- planner Connector protocol ----------------------------------------

    async def apply(self, decision: PlanDecision) -> None:
        self.reap()
        target = max(decision.decode_workers, 0)
        if len(self.workers) < target:
            await self.spawn_workers(target - len(self.workers))
            self.counters["scale_ups"] += 1
        elif len(self.workers) > target:
            while len(self.workers) > target:
                handle = self.workers.pop()  # youngest first (coldest cache)
                handle.proc.terminate()
            self.counters["scale_downs"] += 1

    async def close(self) -> None:
        procs = [h.proc for h in self.workers]
        self.workers = []
        for p in procs:
            if p.poll() is None:
                p.terminate()

        def wait_all() -> None:
            for p in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass

        await asyncio.get_running_loop().run_in_executor(None, wait_all)


class StoreFleet:
    """A replicated control-plane store as real OS processes.

    Spawns ``n`` ``python -m dynamo_tpu.launch --role store`` replicas, each
    serving its own port and joined into one replication group via
    ``--store-replicas``/``--store-replica-index``. Replica 0 bootstraps as
    leader; the others follow. ``kill(0)`` is the kill-the-leader scenario
    primitive: SIGKILL, no goodbye, the survivors must fence and promote on
    their own. Ports are allocated up front so every replica knows the full
    peer list before any of them starts.
    """

    def __init__(self, n: int, *, base_env: dict[str, str] | None = None,
                 spawn_timeout: float | None = None) -> None:
        if n < 2:
            raise ValueError("StoreFleet needs >= 2 replicas; use an in-process StoreServer for 1")
        settings = load_fleet_settings()
        self.base_env = dict(base_env or {})
        self.spawn_timeout = spawn_timeout if spawn_timeout is not None else settings.spawn_timeout_s
        self.ports = [_free_port() for _ in range(n)]
        self.urls = [f"tcp://127.0.0.1:{p}" for p in self.ports]
        self.procs: list[subprocess.Popen | None] = [None] * n
        self.counters = {"kills": 0}

    def _spawn_one(self, index: int) -> subprocess.Popen:
        import dynamo_tpu

        cmd = [
            sys.executable, "-m", "dynamo_tpu.launch",
            "--role", "store", "--host", "127.0.0.1",
            "--serve-store-port", str(self.ports[index]),
            "--store-replicas", ",".join(self.urls),
            "--store-replica-index", str(index),
        ]
        env = dict(os.environ)
        env.update(self.base_env)
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(dynamo_tpu.__file__)))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                                text=True, env=env)
        logger.info("store-fleet: spawned replica #%d pid=%d port=%d",
                    index, proc.pid, self.ports[index])
        return proc

    async def start(self) -> None:
        """Spawn every replica and wait for all READY lines concurrently."""
        procs = [self._spawn_one(i) for i in range(len(self.ports))]
        results = await asyncio.gather(
            *(_wait_ready_line(p, f"store replica #{i}", self.spawn_timeout)
              for i, p in enumerate(procs)),
            return_exceptions=True,
        )
        failures = [r for r in results if isinstance(r, BaseException)]
        if failures:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            raise failures[0]
        for i, p in enumerate(procs):
            self.procs[i] = p
            threading.Thread(target=FleetManager._drain_pipe, args=(p,), daemon=True).start()

    def kill(self, index: int) -> None:
        """SIGKILL replica ``index`` — a crash, not a shutdown. No lease is
        revoked, no demotion record is shipped; the survivors must notice."""
        proc = self.procs[index]
        if proc is None or proc.poll() is not None:
            return
        proc.kill()
        self.counters["kills"] += 1
        logger.info("store-fleet: killed replica #%d pid=%d", index, proc.pid)

    async def close(self) -> None:
        procs = [p for p in self.procs if p is not None and p.poll() is None]
        self.procs = [None] * len(self.procs)
        for p in procs:
            p.terminate()

        def wait_all() -> None:
            for p in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass

        await asyncio.get_running_loop().run_in_executor(None, wait_all)
