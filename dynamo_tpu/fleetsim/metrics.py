"""Fleet scoreboard Prometheus families (``dynamo_fleet_*``).

One registry per scenario run, synced from the final
:class:`~dynamo_tpu.fleetsim.scoreboard.Scoreboard` report, so a soak run
can be scraped live and a CI run can assert on the same names the
dashboards use. Enumerated by ``tools/check_metric_names.py`` next to the
frontend and engine registries — names must stay ``dynamo_``-prefixed,
globally unique, HELP'd, and label-consistent.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Gauge, generate_latest


class FleetMetrics:
    def __init__(self, registry: CollectorRegistry | None = None) -> None:
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_fleet"
        self.goodput_frac = Gauge(
            f"{ns}_goodput_frac_at_slo",
            "Fraction of finished requests that attained the scenario SLO "
            "(TTFT and per-request p99 ITL within targets)",
            registry=self.registry,
        )
        self.goodput_tokens_per_s = Gauge(
            f"{ns}_goodput_tokens_per_s",
            "Output tokens/s from SLO-attaining requests over the scenario wall time",
            registry=self.registry,
        )
        self.tenant_fairness = Gauge(
            f"{ns}_tenant_fairness",
            "min/max ratio of per-tenant SLO-attainment fractions (1.0 = perfectly fair)",
            registry=self.registry,
        )
        self.requests = Gauge(
            f"{ns}_requests",
            "Scenario requests by outcome (ok / error / mid_stream_failure)",
            ["outcome"], registry=self.registry,
        )
        self.tenant_goodput_frac = Gauge(
            f"{ns}_tenant_goodput_frac",
            "Per-tenant fraction of requests that attained the scenario SLO",
            ["tenant"], registry=self.registry,
        )
        self.ttft_quantile = Gauge(
            f"{ns}_ttft_quantile_seconds",
            "Open-loop TTFT quantile (P^2), measured from intended injection time",
            ["quantile"], registry=self.registry,
        )
        self.itl_quantile = Gauge(
            f"{ns}_itl_quantile_seconds",
            "Open-loop inter-token-latency quantile (P^2) across all streams",
            ["quantile"], registry=self.registry,
        )
        self.workers_live = Gauge(
            f"{ns}_workers_live",
            "Worker processes alive at the last fleet reap",
            registry=self.registry,
        )
        self.lifecycle = Gauge(
            f"{ns}_lifecycle_events",
            "Fleet lifecycle event counts (spawns / kills / drains / scale_ups / scale_downs)",
            ["event"], registry=self.registry,
        )

    def sync_report(self, report: dict) -> None:
        """Load a finished scenario report's fields into the gauges."""
        self.goodput_frac.set(report.get("goodput_frac_at_slo", 0.0))
        self.goodput_tokens_per_s.set(report.get("goodput_tokens_per_s_at_slo", 0.0))
        self.tenant_fairness.set(report.get("tenant_fairness", 0.0))
        req = report.get("requests", {})
        for outcome in ("ok", "error", "mid_stream_failure"):
            self.requests.labels(outcome).set(req.get(outcome, 0))
        for tenant, t in report.get("tenants", {}).items():
            self.tenant_goodput_frac.labels(tenant).set(t.get("goodput_frac", 0.0))
        for q, v in report.get("ttft_ms", {}).items():
            self.ttft_quantile.labels(q).set(v / 1e3)
        for q, v in report.get("itl_ms", {}).items():
            self.itl_quantile.labels(q).set(v / 1e3)
        self.workers_live.set(report.get("fleet", {}).get("live", 0))
        for event, n in report.get("fleet", {}).items():
            if event != "live":
                self.lifecycle.labels(event).set(n)

    def render(self) -> bytes:
        return generate_latest(self.registry)
