"""Workload plane: deterministic arrival traces with fleet-scale structure.

Arrivals follow an inhomogeneous Poisson process (thinning over a rate
envelope) so load has the statistics real frontends see — bursty
interarrivals, not a metronome. The rate function composes the fleet
phenomena the scenarios exercise:

- a **diurnal** sinusoid (amplitude as a fraction of the base rate),
- a **period shift** (the rate steps to a new scale at a given time — the
  planner's scale-up/scale-down trigger),
- **burst episodes** (multiplicative windows over the base rate),
- a **heavy-tenant flood** (an independent homogeneous stream for one
  tenant over a window, on top of the organic mix).

Prompts carry the two-level prefix structure of ``bench/synthesizer.py``
(one corpus-wide shared prefix, G group prefixes, unique tails) so the KV
router and prefix cache see realistic sharing.

Everything derives from one ``numpy`` Generator seeded by
``TraceConfig.seed``: the same config always produces the bit-identical
event list, serialized as JSONL (one header line, one line per event) so
traces are replayable and diffable across runs and machines.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math

import numpy as np

TRACE_FORMAT = "dynamo-fleet-trace"
TRACE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class BurstEpisode:
    """A multiplicative rate window: ``rate *= rate_scale`` inside it."""

    start_s: float
    duration_s: float
    rate_scale: float


@dataclasses.dataclass(frozen=True)
class TenantFlood:
    """An independent homogeneous arrival stream for one tenant."""

    tenant: str = "heavy"
    start_s: float = 0.0
    duration_s: float = 0.0
    qps: float = 0.0


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    duration_s: float = 10.0
    base_qps: float = 4.0
    # Diurnal modulation: rate(t) = base * (1 + amplitude * sin(2πt/period)).
    diurnal_amplitude: float = 0.0
    diurnal_period_s: float = 60.0
    # Period shift: from shift_at_s on, the whole envelope scales by
    # shift_scale (a step change in offered load, not a burst).
    period_shift_at_s: float = -1.0  # < 0 disables
    period_shift_scale: float = 1.0
    bursts: tuple[BurstEpisode, ...] = ()
    flood: TenantFlood | None = None
    # Organic tenant mix: (name, weight) pairs; weights need not sum to 1.
    tenants: tuple[tuple[str, float], ...] = (("default", 1.0),)
    # Prompt structure (two-level prefix tree, see bench/synthesizer.py).
    shared_prefix_len: int = 32
    num_groups: int = 4
    group_prefix_len: int = 32
    unique_len: int = 16
    vocab: int = 250
    osl_mean: int = 24
    osl_cv: float = 0.3
    seed: int = 0

    def rate_at(self, t: float) -> float:
        """The arrival-rate envelope (req/s) at time ``t``, floods excluded."""
        rate = self.base_qps
        if self.diurnal_amplitude > 0.0 and self.diurnal_period_s > 0.0:
            rate *= 1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s
            )
        if 0.0 <= self.period_shift_at_s <= t:
            rate *= self.period_shift_scale
        for b in self.bursts:
            if b.start_s <= t < b.start_s + b.duration_s:
                rate *= b.rate_scale
        return max(rate, 0.0)

    def rate_max(self) -> float:
        """An upper bound on :meth:`rate_at` (the thinning envelope)."""
        rate = self.base_qps * (1.0 + max(self.diurnal_amplitude, 0.0))
        if self.period_shift_at_s >= 0.0:
            rate *= max(self.period_shift_scale, 1.0)
        for b in self.bursts:
            rate *= max(b.rate_scale, 1.0)
        return rate


@dataclasses.dataclass
class TraceEvent:
    t_s: float  # arrival offset from trace start
    request_id: str
    tenant: str
    token_ids: list[int]
    max_tokens: int
    group: int

    def to_dict(self) -> dict:
        return {
            "t": round(self.t_s, 6),
            "id": self.request_id,
            "tenant": self.tenant,
            "tokens": self.token_ids,
            "max_tokens": self.max_tokens,
            "group": self.group,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        return cls(
            t_s=float(d["t"]), request_id=d["id"], tenant=d["tenant"],
            token_ids=[int(t) for t in d["tokens"]],
            max_tokens=int(d["max_tokens"]), group=int(d["group"]),
        )


def _arrival_times(cfg: TraceConfig, rng: np.random.Generator) -> list[float]:
    """Inhomogeneous Poisson arrivals on [0, duration) by thinning."""
    lam = cfg.rate_max()
    out: list[float] = []
    t = 0.0
    if lam <= 0.0:
        return out
    while True:
        t += float(rng.exponential(1.0 / lam))
        if t >= cfg.duration_s:
            return out
        if float(rng.random()) * lam <= cfg.rate_at(t):
            out.append(t)


def generate_trace(cfg: TraceConfig) -> list[TraceEvent]:
    rng = np.random.default_rng(cfg.seed)
    shared = rng.integers(5, cfg.vocab, cfg.shared_prefix_len).tolist()
    groups = [
        rng.integers(5, cfg.vocab, cfg.group_prefix_len).tolist()
        for _ in range(max(cfg.num_groups, 1))
    ]
    names = [name for name, _ in cfg.tenants]
    weights = np.array([max(w, 0.0) for _, w in cfg.tenants], np.float64)
    weights = weights / weights.sum() if weights.sum() > 0 else None

    arrivals = [(t, None) for t in _arrival_times(cfg, rng)]
    if cfg.flood is not None and cfg.flood.qps > 0.0 and cfg.flood.duration_s > 0.0:
        t = cfg.flood.start_s
        end = min(cfg.flood.start_s + cfg.flood.duration_s, cfg.duration_s)
        while True:
            t += float(rng.exponential(1.0 / cfg.flood.qps))
            if t >= end:
                break
            arrivals.append((t, cfg.flood.tenant))
    arrivals.sort(key=lambda a: a[0])

    events: list[TraceEvent] = []
    for i, (t, tenant) in enumerate(arrivals):
        if tenant is None:
            tenant = names[int(rng.choice(len(names), p=weights))]
        g = int(rng.integers(0, len(groups)))
        unique = rng.integers(5, cfg.vocab, cfg.unique_len).tolist()
        sigma = max(cfg.osl_mean * cfg.osl_cv, 1e-6)
        osl = int(np.clip(rng.normal(cfg.osl_mean, sigma), 1, cfg.osl_mean * 4))
        events.append(TraceEvent(
            t_s=round(t, 6),
            request_id=f"r{i:05d}",
            tenant=tenant,
            token_ids=shared + groups[g] + unique,
            max_tokens=osl,
            group=g,
        ))
    return events


def trace_digest(events: list[TraceEvent]) -> str:
    """Canonical content hash: the determinism assertion (same seed -> same
    trace) and the replay-identity assertion (load(save(t)) == t) both
    reduce to digest equality."""
    h = hashlib.sha256()
    for ev in events:
        h.update(json.dumps(ev.to_dict(), sort_keys=True).encode())
        h.update(b"\n")
    return h.hexdigest()


def _config_to_dict(cfg: TraceConfig) -> dict:
    d = dataclasses.asdict(cfg)
    d["bursts"] = [dataclasses.asdict(b) for b in cfg.bursts]
    d["flood"] = dataclasses.asdict(cfg.flood) if cfg.flood is not None else None
    d["tenants"] = [[name, w] for name, w in cfg.tenants]
    return d


def config_from_dict(d: dict) -> TraceConfig:
    d = dict(d)
    d["bursts"] = tuple(BurstEpisode(**b) for b in d.get("bursts", ()))
    flood = d.get("flood")
    d["flood"] = TenantFlood(**flood) if flood else None
    d["tenants"] = tuple((name, float(w)) for name, w in d.get("tenants", []))
    return TraceConfig(**d)


def save_trace(path: str, cfg: TraceConfig, events: list[TraceEvent]) -> None:
    with open(path, "w") as f:
        f.write(json.dumps({
            "format": TRACE_FORMAT,
            "version": TRACE_VERSION,
            "seed": cfg.seed,
            "events": len(events),
            "digest": trace_digest(events),
            "config": _config_to_dict(cfg),
        }, sort_keys=True) + "\n")
        for ev in events:
            f.write(json.dumps(ev.to_dict(), sort_keys=True) + "\n")


def load_trace(path: str) -> tuple[TraceConfig, list[TraceEvent]]:
    with open(path) as f:
        header = json.loads(f.readline())
        if header.get("format") != TRACE_FORMAT:
            raise ValueError(f"{path}: not a {TRACE_FORMAT} file")
        events = [TraceEvent.from_dict(json.loads(line)) for line in f if line.strip()]
    cfg = config_from_dict(header["config"])
    digest = header.get("digest")
    if digest and digest != trace_digest(events):
        raise ValueError(f"{path}: event digest mismatch (truncated or edited trace)")
    return cfg, events
