"""Fleet-scale simulation harness: diurnal traffic against the real control plane.

The north star is "heavy traffic from millions of users", and every
ingredient exists in isolation — the mocker timing-model runner, the
seasonal load predictor, the SLA planner, the chaos plane, EDF admission
with per-tenant quotas, predicted-TTFT + cache-aware routing. This package
composes them into a regression gate:

- :mod:`trace` — the **workload plane**: a deterministic arrival-trace
  generator (inhomogeneous Poisson with diurnal modulation, period shifts,
  burst episodes, a heavy-tenant flood, and a prefix-sharing token mix)
  serialized as JSONL so runs are replayable and diffable.
- :mod:`fleet` — the **fleet plane**: tens of mock workers spread across
  OS processes (on a 1-core box an in-process fleet serializes and flattens
  every measurement — real processes sleep on their timing models instead),
  with per-worker timing profiles, spawn / SIGTERM-drain / SIGKILL
  lifecycle, planner actuation, and scripted churn.
- :mod:`scoreboard` — the **measurement plane**: an open-loop client that
  timestamps at *intended* injection (coordinated omission can't hide
  stalls), P² p99/p99.9 tails, goodput-under-SLO, per-tenant attainment and
  fairness, breaker/restart/requeue counts scraped from the federated
  ``/metrics``, and ``dynamo_fleet_*`` Prometheus families.
- :mod:`scenario` — **scenarios as code**: a Scenario spec (trace + fleet
  shape + fault script + churn + pass/fail checks) with fast-tier
  deterministic scenarios (seconds, tier-1) and hours-long soak scenarios,
  plus the ``python -m dynamo_tpu.fleetsim run <scenario>`` CLI.

Determinism boundary: the same seed always produces the same trace
(bit-identical JSONL, asserted by digest) and therefore the same request
sequence, tenants, prefixes, and fault arming; wall-clock interleaving
across real OS processes is not replayed — checks assert on distributional
invariants (SLO attainment, fairness floors, event counts), which are
stable under that boundary.
"""

from dynamo_tpu.fleetsim.fleet import ChurnEvent, FleetManager, WorkerTimingProfile
from dynamo_tpu.fleetsim.metrics import FleetMetrics
from dynamo_tpu.fleetsim.scenario import SCENARIOS, Check, Scenario, run_scenario
from dynamo_tpu.fleetsim.scoreboard import Scoreboard
from dynamo_tpu.fleetsim.trace import (
    BurstEpisode,
    TenantFlood,
    TraceConfig,
    TraceEvent,
    generate_trace,
    load_trace,
    save_trace,
    trace_digest,
)

__all__ = [
    "BurstEpisode",
    "Check",
    "ChurnEvent",
    "FleetManager",
    "FleetMetrics",
    "SCENARIOS",
    "Scenario",
    "Scoreboard",
    "TenantFlood",
    "TraceConfig",
    "TraceEvent",
    "WorkerTimingProfile",
    "generate_trace",
    "load_trace",
    "run_scenario",
    "save_trace",
    "trace_digest",
]
