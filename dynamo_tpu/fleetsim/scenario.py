"""Scenarios as code: trace + fleet shape + fault script + assertions.

A :class:`Scenario` is the complete, reviewable description of one fleet
run — the workload (a :class:`~dynamo_tpu.fleetsim.trace.TraceConfig`),
the fleet (worker count, per-worker timing profiles, optional planner),
the chaos (``DYN_FAULTS`` spec + scripted churn), the SLO the run is
judged against, and machine-checkable pass/fail :class:`Check` assertions
over the scoreboard report. Nothing about a run lives outside the spec,
so the same scenario line in CI and on an operator's laptop is the same
experiment.

:func:`run_scenario` is the harness: it brings up the **real** control
plane in-process (store server, distributed runtime, frontend with the
ModelWatcher-built router, optionally the metrics aggregator + planner
loop) and the fleet as worker OS processes, replays the trace open-loop,
and folds everything into one report dict.

Tiers: ``fast`` scenarios finish in seconds and run in tier-1 CI;
``soak`` scenarios run for hours behind the ``slow`` marker.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import socket
import tempfile

import aiohttp

from dynamo_tpu.config import load_fleet_settings
from dynamo_tpu.fleetsim.fleet import (
    ChurnEvent,
    FleetManager,
    StoreFleet,
    WorkerTimingProfile,
)
from dynamo_tpu.fleetsim.scoreboard import (
    Scoreboard,
    SloTarget,
    poll_control_plane,
    run_open_loop,
    wall_clock,
)
from dynamo_tpu.fleetsim.trace import (
    BurstEpisode,
    TenantFlood,
    TraceConfig,
    generate_trace,
    trace_digest,
)
from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile

logger = logging.getLogger(__name__)

_OPS = {
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    "==": lambda a, b: a == b,
}


@dataclasses.dataclass(frozen=True)
class Check:
    """One pass/fail assertion over the report: ``key op value`` where
    ``key`` is a dotted path into the report dict (``itl_ms.p99``,
    ``tenants.light.goodput_frac``, ``planner.max_decode_workers``)."""

    key: str
    op: str
    value: float

    def evaluate(self, report: dict) -> dict:
        node: object = report
        found = True
        for part in self.key.split("."):
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                found = False
                break
        ok = found and isinstance(node, (int, float)) and _OPS[self.op](node, self.value)
        return {
            "key": self.key, "op": self.op, "value": self.value,
            "actual": node if found and isinstance(node, (int, float)) else None,
            "ok": bool(ok),
        }


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    trace: TraceConfig
    workers: int = 2
    profiles: tuple[WorkerTimingProfile, ...] = ()
    # Optional autoscaling: planner config + the capacity profile it plans
    # with. When set, the fleet starts at min_workers and the planner loop
    # (not ``workers``) owns the fleet size.
    planner: PlannerConfig | None = None
    planner_profile: WorkerProfile | None = None
    faults: str = ""  # DYN_FAULTS grammar, armed in every worker process
    churn: tuple[ChurnEvent, ...] = ()
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    slo: SloTarget = dataclasses.field(default_factory=SloTarget)
    checks: tuple[Check, ...] = ()
    tier: str = "fast"  # "fast" (tier-1 CI) | "soak" (behind the slow marker)
    router_mode: str = "kv"
    model: str = "test-tiny"
    # Keep the planner ticking this long after the trace drains, so
    # scale-DOWN decisions land inside the run (and the report).
    cooldown_s: float = 0.0
    request_timeout_s: float = 60.0
    # HA control plane: >1 runs the store as that many replica OS processes
    # (leader + followers, ``launch --role store``) instead of the in-process
    # StoreServer, with everything — harness, frontend, workers — connected
    # through the multi-endpoint StoreClient.
    store_replicas: int = 1
    # SIGKILL the store *leader* this far into the trace (0 = never; needs
    # store_replicas > 1). The report gains ``store_ha``: declarative keys
    # lost, worker deregistrations, and the measured failover time.
    store_kill_at_s: float = 0.0
    # Stop + rebuild the frontend (HTTP service, watcher, router, metrics
    # registry) this far into the trace (0 = never). The report gains
    # ``frontend``: bounce count and resyncs observed during reconstruction.
    frontend_bounce_at_s: float = 0.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


async def _wait_model(base: str, model: str, timeout_s: float = 60.0) -> None:
    """Poll /v1/models until the watcher has discovered the fleet's model."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    async with aiohttp.ClientSession() as session:
        while loop.time() < deadline:
            try:
                async with session.get(f"{base}/v1/models") as resp:
                    if resp.status == 200:
                        doc = await resp.json()
                        if any(m.get("id") == model for m in doc.get("data", [])):
                            return
            except Exception:
                pass
            await asyncio.sleep(0.2)
    raise TimeoutError(f"model {model!r} not discoverable at {base} in {timeout_s}s")


async def _collect_incidents(base: str) -> dict:
    """Fold the incident plane into the report: the fleet-wide bundle
    listing from ``GET /debug/incidents``, plus a round-trip fetch of the
    newest bundle through ``GET /debug/incidents/{id}`` (``fetch_ok``) so a
    Check can assert the black-box path works end-to-end, not just that
    files landed on disk."""
    out: dict = {"bundles": 0, "kinds": {}, "fetch_ok": 0}
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{base}/debug/incidents") as resp:
                if resp.status != 200:
                    return out
                doc = await resp.json()
            items = doc.get("incidents") or []
            out["bundles"] = len(items)
            kinds: dict[str, int] = {}
            for item in items:
                kind = item.get("kind", "?")
                kinds[kind] = kinds.get(kind, 0) + 1
            out["kinds"] = kinds
            if items:
                newest = max(items, key=lambda i: i.get("ts", 0))
                async with session.get(
                    f"{base}/debug/incidents/{newest['id']}"
                ) as resp:
                    if resp.status == 200:
                        bundle = await resp.json()
                        out["fetch_ok"] = int(bool(bundle.get("flight") is not None))
    except Exception:
        logger.exception("fleetsim: incident collection failed (report stays 0)")
    return out


async def _store_failover_drill(
    store_fleet: StoreFleet, store, at_s: float, t0: float, out: dict
) -> None:
    """Kill the store leader at ``at_s`` and clock the failover: how long
    until the harness's own client sees a promoted leader (epoch >= 2)."""
    loop = asyncio.get_running_loop()
    delay = at_s - (loop.time() - t0)
    if delay > 0:
        await asyncio.sleep(delay)
    try:
        out["instances_before_kill"] = float(len(await store.get_prefix("instances/")))
    except Exception:
        logger.exception("fleetsim: pre-kill instance census failed")
    killed_at = loop.time()
    store_fleet.kill(0)  # replica 0 bootstrapped as leader
    while True:
        try:
            info = await store.who_leads()
            if info.get("role") == "leader" and float(info.get("epoch", 0)) >= 2:
                break
        except asyncio.CancelledError:
            raise
        except Exception:
            pass
        await asyncio.sleep(0.05)
    out["failover_s"] = round(loop.time() - killed_at, 3)


class _LoggingConnector:
    """Planner Connector that records every decision (scenario-relative
    time) before delegating to the fleet."""

    def __init__(self, fleet: FleetManager, scoreboard: Scoreboard, t0: float) -> None:
        self.fleet = fleet
        self.scoreboard = scoreboard
        self.t0 = t0

    async def apply(self, decision) -> None:
        self.scoreboard.planner_decisions.append({
            "t_s": round(asyncio.get_running_loop().time() - self.t0, 3),
            "decode_workers": decision.decode_workers,
            "prefill_workers": decision.prefill_workers,
        })
        await self.fleet.apply(decision)

    async def close(self) -> None:
        pass  # the fleet is torn down by run_scenario's finally block


async def run_scenario(
    scn: Scenario,
    *,
    dry_run: bool = False,
    report_path: str | None = None,
    workers_override: int = 0,
) -> dict:
    """Run one scenario end-to-end and return the report dict.

    ``dry_run`` generates and digests the trace and returns the report
    skeleton without starting any process — the cheap determinism /
    structure check. ``workers_override`` (or ``DYN_FLEET_WORKERS``)
    resizes a fixed fleet; planner-owned fleets ignore it.
    """
    settings = load_fleet_settings()
    events = generate_trace(scn.trace)
    digest = trace_digest(events)
    report: dict = {
        "scenario": scn.name,
        "tier": scn.tier,
        "seed": scn.trace.seed,
        "trace": {
            "digest": digest,
            "events": len(events),
            "duration_s": scn.trace.duration_s,
        },
        "dry_run": dry_run,
    }
    if dry_run:
        report.update({
            "checks": [dataclasses.asdict(c) for c in scn.checks],
            "passed": None,
        })
        return report

    workers = workers_override or settings.workers or scn.workers
    run_env = dict(scn.env)
    # Fresh incident dir per run: the default store dir is shared per host,
    # so without this the report's incident count would include bundles left
    # over from earlier runs (and other fleets on the same box).
    run_env.setdefault(
        "DYN_INCIDENT_DIR",
        tempfile.mkdtemp(prefix=f"dynamo-incidents-{scn.name}-"),
    )
    saved_env = {k: os.environ.get(k) for k in run_env}
    os.environ.update(run_env)  # frontend/router-side toggles live here

    from dynamo_tpu.launch import serve_frontend
    from dynamo_tpu.router.events import router_resync_snapshot
    from dynamo_tpu.router.metrics import KvMetricsAggregator
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer
    from dynamo_tpu.runtime.tcp import TcpTransport

    loop = asyncio.get_running_loop()
    started = wall_clock()
    server = runtime = aggregator = http = watcher = fleet = planner_loop = None
    store_fleet: StoreFleet | None = None
    store_client: StoreClient | None = None
    tasks: list[asyncio.Task] = []
    scoreboard = Scoreboard(slo=scn.slo)
    ha: dict = {}
    frontend_info: dict = {"bounces": 0.0, "resyncs": 0.0}
    probe_keys: dict[str, bytes] = {}
    try:
        if scn.store_replicas > 1:
            store_fleet = StoreFleet(scn.store_replicas, base_env=run_env)
            await store_fleet.start()
            store_url = ",".join(store_fleet.urls)
            store_client = StoreClient.from_url(store_url)
            store = store_client
        else:
            port = _free_port()
            server = await StoreServer(host="127.0.0.1", port=port).start()
            store = server.store
            store_url = f"tcp://127.0.0.1:{port}"
        runtime = DistributedRuntime(store, TcpTransport(host="127.0.0.1"))
        http, watcher, http_port = await serve_frontend(runtime, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{http_port}"

        if store_fleet is not None:
            # Declarative canaries: a failover must carry every one of these
            # to the promoted follower, byte-exact.
            for i in range(16):
                key, value = f"ha_probe/{i:02d}", f"probe-{i}".encode()
                await store.put(key, value)
                probe_keys[key] = value

        base_env = dict(run_env)
        if scn.faults:
            base_env["DYN_FAULTS"] = scn.faults
            base_env.setdefault("DYN_FAULTS_SEED", str(scn.trace.seed))
        fleet = FleetManager(
            store_url=store_url, model=scn.model,
            router_mode=scn.router_mode, base_env=base_env,
            profiles=scn.profiles,
        )
        initial = scn.planner.min_workers if scn.planner is not None else workers
        await fleet.spawn_workers(initial)
        await _wait_model(base, scn.model, timeout_s=fleet.spawn_timeout)

        t0 = loop.time()
        if scn.planner is not None:
            from dynamo_tpu.planner.connector import PlannerLoop

            aggregator = await KvMetricsAggregator(runtime, "dynamo", "backend").start()
            planner = Planner(scn.planner, scn.planner_profile or WorkerProfile())
            planner_loop = PlannerLoop(planner, aggregator,
                                       _LoggingConnector(fleet, scoreboard, t0))
            await planner_loop.start()
        tasks.append(asyncio.create_task(
            poll_control_plane(base, scoreboard, interval_s=settings.metrics_poll_s)))
        if scn.churn:
            tasks.append(asyncio.create_task(fleet.run_churn(list(scn.churn), t0)))
        if store_fleet is not None and scn.store_kill_at_s > 0:
            tasks.append(asyncio.create_task(
                _store_failover_drill(store_fleet, store, scn.store_kill_at_s, t0, ha)))

        if scn.frontend_bounce_at_s > 0:
            async def _bounce() -> None:
                nonlocal http, watcher
                delay = scn.frontend_bounce_at_s - (loop.time() - t0)
                if delay > 0:
                    await asyncio.sleep(delay)
                pre_resyncs = router_resync_snapshot()["resyncs"]
                await http.stop()
                await watcher.close()
                # Rebind the same port; the old listener can linger a beat.
                for _ in range(25):
                    try:
                        http, watcher, _ = await serve_frontend(
                            runtime, host="127.0.0.1", port=http_port)
                        break
                    except OSError:
                        await asyncio.sleep(0.2)
                frontend_info["bounces"] += 1.0
                # Reconstruction evidence: the replacement's subscribers must
                # resync from the workers' sequence-numbered snapshots.
                deadline = loop.time() + 10.0
                while loop.time() < deadline:
                    delta = router_resync_snapshot()["resyncs"] - pre_resyncs
                    if delta > 0:
                        break
                    await asyncio.sleep(0.1)
                frontend_info["resyncs"] = float(
                    router_resync_snapshot()["resyncs"] - pre_resyncs)

            tasks.append(asyncio.create_task(_bounce()))

        await run_open_loop(base, scn.model, events, scoreboard, t0=t0,
                            request_timeout_s=scn.request_timeout_s)
        if scn.cooldown_s > 0:
            await asyncio.sleep(scn.cooldown_s)
        duration = loop.time() - t0

        report.update(scoreboard.report(duration_s=duration))
        report["fleet"] = {**fleet.counters, "live": fleet.live_count()}
        report["incidents"] = await _collect_incidents(base)
        report["frontend"] = dict(frontend_info)
        if store_fleet is not None:
            survivors = await store.get_prefix("ha_probe/")
            ha["declarative_lost"] = float(sum(
                1 for k, v in probe_keys.items() if survivors.get(k) != v))
            ha["instances_final"] = float(len(await store.get_prefix("instances/")))
            before = ha.get("instances_before_kill", ha["instances_final"])
            ha["worker_deregistrations"] = max(0.0, before - ha["instances_final"])
            try:
                info = await store.who_leads()
                ha["epoch"] = float(info.get("epoch", 0))
            except Exception:
                logger.exception("fleetsim: post-run who_leads failed")
            report["store_ha"] = ha
    finally:
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        if planner_loop is not None:
            await planner_loop.close()
        if fleet is not None:
            await fleet.close()
        if aggregator is not None:
            await aggregator.close()
        if watcher is not None:
            await watcher.close()
        if http is not None:
            await http.stop()
        if runtime is not None:
            await runtime.close()
        if store_client is not None:
            try:
                await store_client.close()
            except Exception:  # replicas may already be gone
                pass
        if server is not None:
            await server.close()
        if store_fleet is not None:
            await store_fleet.close()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    report["started_unix"] = round(started, 3)
    results = [c.evaluate(report) for c in scn.checks]
    report["checks"] = results
    report["passed"] = all(r["ok"] for r in results)

    out_path = report_path or (
        os.path.join(settings.report_dir, f"{scn.name}.json") if settings.report_dir else None
    )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        logger.info("fleetsim: report written to %s", out_path)
    return report


# -- scenario registry -----------------------------------------------------

# Heterogeneous fleet: a fast half and a slower, noisier half with real
# cold-start ramps — what a planner scale-up actually lands on.
_MIXED_PROFILES = (
    WorkerTimingProfile(jitter=0.05, warmup_s=1.0, warmup_factor=3.0),
    WorkerTimingProfile(prefill_us_per_token=80.0, decode_us_base=3000.0,
                        jitter=0.15, warmup_s=2.0, warmup_factor=4.0),
)

SCENARIOS: dict[str, Scenario] = {}


def _register(scn: Scenario) -> Scenario:
    SCENARIOS[scn.name] = scn
    return scn


_register(Scenario(
    name="smoke",
    description="Tiny steady trace on a 2-worker fleet; the bench probe and "
                "CLI default. Seconds, no chaos.",
    trace=TraceConfig(duration_s=3.0, base_qps=4.0, osl_mean=16, seed=7),
    workers=2,
    checks=(
        Check("requests.total", ">=", 6),
        Check("goodput_frac_at_slo", ">=", 0.5),
        # Quiet fleet: the anomaly sentinel must stay silent (false
        # positives here mean the detectors are armed too aggressively).
        Check("anomalies.fired_total", "==", 0),
    ),
))

_register(Scenario(
    name="burst_absorb",
    description="4x Poisson burst mid-trace: the fleet must absorb it "
                "without blowing the ITL tail (decode steps keep their "
                "cadence while the prefill backlog drains).",
    trace=TraceConfig(duration_s=6.0, base_qps=4.0, osl_mean=24,
                      bursts=(BurstEpisode(start_s=2.0, duration_s=1.5, rate_scale=4.0),),
                      seed=11),
    workers=2,
    profiles=(WorkerTimingProfile(jitter=0.05),),
    checks=(
        Check("requests.total", ">=", 20),
        Check("itl_ms.p99", "<=", 50.0),
        Check("goodput_frac_at_slo", ">=", 0.7),
    ),
))

_register(Scenario(
    name="tenant_flood",
    description="A heavy tenant floods 8x the organic rate; per-tenant "
                "quotas + the admission plane must keep the light tenant's "
                "attainment above the fairness floor.",
    trace=TraceConfig(duration_s=6.0, base_qps=3.0, osl_mean=20,
                      tenants=(("light", 1.0),),
                      flood=TenantFlood(tenant="heavy", start_s=1.5, duration_s=3.0, qps=25.0),
                      seed=13),
    workers=2,
    env={
        "DYN_SLO_SCHED": "1",
        "DYN_TENANT_QUOTAS": json.dumps({
            "heavy": {"rate_tokens_per_s": 400, "max_inflight_tokens": 1024},
        }),
    },
    checks=(
        Check("requests.total", ">=", 30),
        Check("tenants.light.goodput_frac", ">=", 0.6),
    ),
))

_register(Scenario(
    name="kill_midstream",
    description="SIGKILL a worker while long streams are in flight: clients "
                "on the dead worker get the structured mid_stream_failure "
                "SSE, the breaker sheds the corpse, the survivor keeps "
                "serving.",
    trace=TraceConfig(duration_s=5.0, base_qps=3.0, osl_mean=80, osl_cv=0.2, seed=17),
    workers=2,
    # Slow decode (~20ms/token) so streams span the kill point.
    profiles=(WorkerTimingProfile(decode_us_base=20000.0, jitter=0.05),),
    slo=SloTarget(ttft_ms=500.0, itl_p99_ms=80.0),
    # Round-robin (not KV) routing: the shared trace prefix makes
    # KV-affinity concentrate every stream on whichever worker caches it
    # first — a race — so a fixed-index kill sometimes hits an idle worker.
    # Round-robin guarantees both workers hold streams at the kill point.
    router_mode="round_robin",
    churn=(ChurnEvent(at_s=2.0, action="kill", which=0),),
    checks=(
        Check("requests.total", ">=", 10),
        Check("requests.mid_stream_failure", ">=", 1),
        Check("requests.ok", ">=", 3),
        Check("fleet.kills", ">=", 1),
    ),
))

_register(Scenario(
    name="incident_capture",
    description="Deterministic engine-step crash (fault plane, 40th step in "
                "every worker): the black-box recorder must land crash "
                "bundles in the incident store and the frontend must serve "
                "them back through GET /debug/incidents/{id}.",
    trace=TraceConfig(duration_s=4.0, base_qps=4.0, osl_mean=24, seed=31),
    workers=2,
    profiles=(WorkerTimingProfile(jitter=0.05),),
    faults="engine.step:crash@40",
    checks=(
        Check("requests.total", ">=", 10),
        Check("requests.ok", ">=", 3),
        Check("incidents.bundles", ">=", 1),
        Check("incidents.kinds.crash", ">=", 1),
        # The newest bundle round-trips through the frontend fetch path
        # with its flight excerpt intact.
        Check("incidents.fetch_ok", ">=", 1),
    ),
))

_register(Scenario(
    name="store_failover",
    description="SIGKILL the store leader mid-trace on a 3-replica control "
                "plane: a follower promotes under the epoch fence inside the "
                "failover budget, every declarative key survives byte-exact, "
                "no worker loses its registration (leases ride the handoff), "
                "and the serving plane barely notices — requests flow on "
                "cached discovery while clients chase the new leader.",
    trace=TraceConfig(duration_s=6.0, base_qps=4.0, osl_mean=24, seed=37),
    workers=2,
    store_replicas=3,
    store_kill_at_s=2.0,
    # Tight fence timings so promotion lands well inside the run (defaults
    # are sized for real fleets, not 6-second traces).
    env={"DYN_STORE_PROMOTE_AFTER_S": "0.4", "DYN_STORE_POLL_S": "0.1"},
    checks=(
        Check("requests.total", ">=", 15),
        Check("requests.ok", ">=", 10),
        # Bounded goodput dip: a control-plane failover must not collapse
        # the serving plane.
        Check("goodput_frac_at_slo", ">=", 0.5),
        Check("store_ha.declarative_lost", "==", 0),
        Check("store_ha.worker_deregistrations", "==", 0),
        # Recovery well under the 10s worker-lease TTL — the margin that
        # makes zero deregistrations structural, not lucky.
        Check("store_ha.failover_s", "<=", 5.0),
        Check("store_ha.epoch", ">=", 2),
        Check("control_plane.store_failovers", ">=", 1),
    ),
))

_register(Scenario(
    name="frontend_restart",
    description="Bounce the frontend mid-trace: stop the HTTP service and "
                "watcher, rebuild both on the same port. The replacement "
                "must reconstruct its prefix index from the workers' "
                "sequence-numbered KV-event snapshots (resyncs observed "
                "during the bounce), recover warm routing (cache hits on "
                "the *fresh* metrics registry), and wedge nothing — the "
                "open-loop client keeps scoring through the gap.",
    trace=TraceConfig(duration_s=6.0, base_qps=4.0, osl_mean=24, seed=41),
    workers=2,
    frontend_bounce_at_s=2.5,
    checks=(
        Check("requests.total", ">=", 15),
        Check("requests.ok", ">=", 8),
        Check("frontend.bounces", ">=", 1),
        # State reconstruction: the replacement's subscribers resynced from
        # worker snapshots (delta across the bounce, so accumulation from
        # earlier runs in the same process can't fake a pass).
        Check("frontend.resyncs", ">=", 1),
        # Warm routing after the bounce: the post-bounce registry starts at
        # zero, so any cached prompt tokens were served by the replacement.
        Check("control_plane.cached_tokens_final", ">", 0),
    ),
))

_register(Scenario(
    name="period_shift",
    description="Diurnal period shift (5x rate step): the planner loop must "
                "scale the decode fleet up into the shift and back down in "
                "the cooldown drain.",
    trace=TraceConfig(duration_s=10.0, base_qps=2.0, osl_mean=40,
                      period_shift_at_s=4.0, period_shift_scale=5.0, seed=19),
    planner=PlannerConfig(mode="load", predictor="linear", min_workers=1,
                          max_workers=3, target_utilization=0.7,
                          interval_seconds=1.5),
    # Capacity far under the mocker's real throughput: measured token rate
    # forces the scale-up deterministically (same trick as the planner
    # connector's live-fleet test).
    planner_profile=WorkerProfile(prefill_tokens_per_sec=1e5, decode_tokens_per_sec=150.0),
    profiles=(WorkerTimingProfile(warmup_s=1.0, warmup_factor=3.0),),
    cooldown_s=8.0,
    checks=(
        Check("requests.total", ">=", 15),
        Check("planner.max_decode_workers", ">=", 2),
        Check("planner.final_decode_workers", "<=", 1),
        Check("fleet.scale_ups", ">=", 1),
        Check("fleet.scale_downs", ">=", 1),
    ),
))

_register(Scenario(
    name="fleet_accept",
    description="The acceptance gate: 8 heterogeneous workers, diurnal + "
                "burst + two tenants, chaos delays armed in every worker, "
                "kill-then-respawn churn — goodput, fairness, and lifecycle "
                "accounting all asserted in one run.",
    trace=TraceConfig(duration_s=8.0, base_qps=6.0, osl_mean=24,
                      diurnal_amplitude=0.3, diurnal_period_s=8.0,
                      bursts=(BurstEpisode(start_s=3.0, duration_s=1.0, rate_scale=3.0),),
                      tenants=(("alpha", 0.6), ("beta", 0.4)),
                      seed=23),
    workers=8,
    profiles=_MIXED_PROFILES,
    faults="store.op:delay@0.05,tcp.read:delay@0.05",
    churn=(ChurnEvent(at_s=2.5, action="kill"), ChurnEvent(at_s=4.0, action="spawn")),
    checks=(
        Check("requests.total", ">=", 30),
        Check("goodput_frac_at_slo", ">=", 0.5),
        Check("tenant_fairness", ">=", 0.5),
        Check("fleet.spawns", ">=", 9),
        Check("fleet.kills", ">=", 1),
        # Time-loss ledger coverage: the per-cause accounting must explain
        # all but a sliver of the fleet's non-compute wall time.
        Check("loss.unattributed_frac", "<=", 0.25),
    ),
))

_register(Scenario(
    name="diurnal_soak",
    description="Hour-scale diurnal soak with a mid-cycle tenant flood and "
                "planner-owned fleet: the long-haul stability run (leaks, "
                "lease churn, predictor drift).",
    trace=TraceConfig(duration_s=1800.0, base_qps=5.0, osl_mean=32,
                      diurnal_amplitude=0.6, diurnal_period_s=300.0,
                      bursts=(BurstEpisode(start_s=600.0, duration_s=30.0, rate_scale=3.0),),
                      tenants=(("light", 0.7), ("steady", 0.3)),
                      flood=TenantFlood(tenant="heavy", start_s=900.0, duration_s=120.0, qps=20.0),
                      seed=29),
    planner=PlannerConfig(mode="load", predictor="seasonal", min_workers=2,
                          max_workers=8, interval_seconds=10.0),
    planner_profile=WorkerProfile(prefill_tokens_per_sec=1e5, decode_tokens_per_sec=200.0),
    profiles=_MIXED_PROFILES,
    faults="store.op:delay@0.02,lease.keepalive:drop@0.02",
    cooldown_s=60.0,
    tier="soak",
    checks=(
        Check("requests.total", ">=", 5000),
        Check("goodput_frac_at_slo", ">=", 0.6),
        Check("planner.max_decode_workers", ">=", 3),
    ),
))
