"""CLI: ``python -m dynamo_tpu.fleetsim <command>``.

``run <scenario>`` executes a registered scenario end-to-end and prints
the report (exit code 1 when any check fails); ``list`` shows the
registry; ``trace`` generates or replays a serialized arrival trace
without starting any process.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.fleetsim.scenario import SCENARIOS, run_scenario
from dynamo_tpu.fleetsim.trace import generate_trace, load_trace, save_trace, trace_digest


def _cmd_list(_args: argparse.Namespace) -> int:
    for name, scn in sorted(SCENARIOS.items()):
        print(f"{name:16s} [{scn.tier}]  {scn.description}")
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    scn = SCENARIOS.get(args.scenario)
    if scn is None:
        print(f"unknown scenario {args.scenario!r}; try: {', '.join(sorted(SCENARIOS))}",
              file=sys.stderr)
        return 2
    report = asyncio.run(run_scenario(
        scn, dry_run=args.dry_run, report_path=args.report,
        workers_override=args.workers,
    ))
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.dry_run:
        return 0
    return 0 if report.get("passed") else 1


def _cmd_trace(args: argparse.Namespace) -> int:
    if args.replay:
        cfg, events = load_trace(args.replay)
        print(json.dumps({
            "replay": args.replay, "seed": cfg.seed, "events": len(events),
            "digest": trace_digest(events), "duration_s": cfg.duration_s,
        }, indent=2))
        return 0
    scn = SCENARIOS.get(args.scenario)
    if scn is None:
        print(f"unknown scenario {args.scenario!r}", file=sys.stderr)
        return 2
    events = generate_trace(scn.trace)
    if args.out:
        save_trace(args.out, scn.trace, events)
        print(f"wrote {len(events)} events to {args.out}")
    else:
        print(json.dumps({
            "scenario": scn.name, "seed": scn.trace.seed,
            "events": len(events), "digest": trace_digest(events),
        }, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m dynamo_tpu.fleetsim")
    sub = parser.add_subparsers(dest="command", required=True)

    p_run = sub.add_parser("run", help="run a scenario end-to-end")
    p_run.add_argument("scenario")
    p_run.add_argument("--report", default=None, help="write the report JSON here")
    p_run.add_argument("--dry-run", action="store_true",
                       help="generate + digest the trace only; no processes")
    p_run.add_argument("--workers", type=int, default=0,
                       help="override the scenario's fixed fleet size")
    p_run.set_defaults(fn=_cmd_run)

    p_list = sub.add_parser("list", help="list registered scenarios")
    p_list.set_defaults(fn=_cmd_list)

    p_trace = sub.add_parser("trace", help="generate or inspect a trace file")
    p_trace.add_argument("scenario", nargs="?", default="smoke")
    p_trace.add_argument("--out", default=None, help="write the trace JSONL here")
    p_trace.add_argument("--replay", default=None,
                         help="load + digest-check an existing trace file")
    p_trace.set_defaults(fn=_cmd_trace)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
