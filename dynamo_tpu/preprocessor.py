"""Preprocessor stage: OpenAI request -> tokens (forward), deltas (backward).

Forward edge: render the model's Jinja chat template over the messages (chat)
or take the raw prompt (completions), tokenize, extract sampling + stop
conditions (including nvext-style extension fields) into a
``PreprocessedRequest``. Backward edge is identity — OpenAI delta formatting
lives in the HTTP frontend so the preprocessor stays protocol-output-agnostic
(router and disagg stages splice in between preprocessor and engine).

Parity: reference `lib/llm/src/preprocessor.rs:98-265` + prompt templates
(`preprocessor/prompt/template/*`). Template rendering uses jinja2 with the
HF-convention variables (``messages``, ``add_generation_prompt``, ``bos_token``,
``eos_token``).
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.engine import AsyncEngine, Context, Operator
from dynamo_tpu.tokenizer import BaseTokenizer

logger = logging.getLogger(__name__)

# Minimal fallback template (ChatML-ish) for models shipping none.
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|im_start|>{{ message['role'] }}\n{{ message['content'] }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)


class PromptFormatter:
    """Jinja chat-template renderer."""

    def __init__(self, template: str | None = None, *, bos_token: str = "", eos_token: str = "") -> None:
        import jinja2

        self._env = jinja2.Environment(keep_trailing_newline=True)  # noqa: S701 — prompts, not HTML
        self._template = self._env.from_string(template or DEFAULT_CHAT_TEMPLATE)
        self._bos = bos_token
        self._eos = eos_token

    def render(self, messages: list[dict[str, Any]], *, add_generation_prompt: bool = True, **extra: Any) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self._bos,
            eos_token=self._eos,
            **extra,
        )


def extract_sampling(body: dict[str, Any]) -> SamplingOptions:
    nvext = body.get("nvext") or {}
    temperature = body.get("temperature")
    # OpenAI logprobs: chat sends `logprobs: true` (+ `top_logprobs: N`,
    # which may legitimately be 0 = chosen token only); completions sends
    # `logprobs: N` (N alternatives; 0 = chosen only). SamplingOptions
    # encodes "enabled with A alternatives" as A + 1 so 0 stays "off".
    raw_lp = body.get("logprobs")
    try:
        if raw_lp is True:
            n_alts = int(body.get("top_logprobs") or 0)
            lp = 1 + n_alts
        elif raw_lp is None or raw_lp is False or raw_lp == "":
            lp, n_alts = 0, 0
        else:
            n_alts = int(raw_lp)
            lp = 1 + n_alts
    except (TypeError, ValueError):
        raise ValueError(f"logprobs/top_logprobs must be integers, got {raw_lp!r}")
    if n_alts < 0 or n_alts > 20:  # OpenAI's top_logprobs range
        raise ValueError(f"logprobs/top_logprobs must be in [0, 20], got {n_alts}")
    return SamplingOptions(
        temperature=1.0 if temperature is None else float(temperature),
        top_k=int(nvext.get("top_k", body.get("top_k", 0)) or 0),
        top_p=float(body.get("top_p", 1.0) if body.get("top_p") is not None else 1.0),
        seed=body.get("seed"),
        frequency_penalty=float(body.get("frequency_penalty", 0.0) or 0.0),
        presence_penalty=float(body.get("presence_penalty", 0.0) or 0.0),
        logprobs=lp,  # +1 encoding; range-checked above (OpenAI cap 20)
        json_mode=_json_mode_from(body.get("response_format")),
    )


def _json_mode_from(rf) -> bool:
    """Validate response_format: silently ignoring an unsupported type
    would return unconstrained output to a caller who asked for schema
    compliance."""
    if rf is None:
        return False
    if not isinstance(rf, dict) or "type" not in rf:
        raise ValueError(f"response_format must be an object with a 'type', got {rf!r}")
    kind = rf["type"]
    if kind == "json_object":
        return True
    if kind == "text":
        return False
    raise ValueError(f"unsupported response_format type {kind!r} (supported: text, json_object)")


def extract_stop(body: dict[str, Any], *, default_max_tokens: int) -> StopConditions:
    nvext = body.get("nvext") or {}
    stop = body.get("stop")
    if stop is None:
        stop_strings = []
    elif isinstance(stop, str):
        stop_strings = [stop]
    else:
        stop_strings = [s for s in stop if s]
    max_tokens = body.get("max_completion_tokens", body.get("max_tokens"))
    return StopConditions(
        max_tokens=int(max_tokens) if max_tokens is not None else default_max_tokens,
        stop_token_ids=list(nvext.get("stop_token_ids", body.get("stop_token_ids", []) or [])),
        stop_strings=stop_strings,
        ignore_eos=bool(nvext.get("ignore_eos", False)),
        min_tokens=int(nvext.get("min_tokens", 0) or 0),
    )


class OpenAIPreprocessor(Operator):
    """Operator: OpenAI chat/completions body (dict) -> PreprocessedRequest."""

    def __init__(
        self,
        downstream: AsyncEngine[Any, Any],
        tokenizer: BaseTokenizer,
        *,
        chat_template: str | None = None,
        default_max_tokens: int = 512,
        add_bos: bool = True,
        max_embed_tokens: int = 2048,
        encoder=None,  # async (media: [(kind, bytes)]) -> (embeds, counts, grids|None)
        image_token_id: int | None = None,
        video_token_id: int | None = None,
    ) -> None:
        super().__init__(downstream)
        self.tokenizer = tokenizer
        self.formatter = PromptFormatter(chat_template)
        self.default_max_tokens = default_max_tokens
        self.add_bos = add_bos
        self.max_embed_tokens = max_embed_tokens
        self.encoder = encoder
        self.image_token_id = image_token_id
        # Models without a distinct video placeholder (LLaVA-class) expand
        # video frames under the image token, like the reference's video
        # prefill workers do.
        self.video_token_id = video_token_id

    IMAGE_SENTINEL = "<|dyn_image|>"

    def _extract_images(self, body: dict[str, Any]) -> tuple[dict[str, Any], list]:
        """Pull data-URL media out of chat content parts; each becomes a
        sentinel in the flattened text that tokenization replaces with
        placeholder tokens. Returns (copied body, [(kind, bytes)] in
        order) — kind "image" (``image_url`` parts) or "video"
        (``video_url`` parts, reference video workers)."""
        from dynamo_tpu.models.vision import decode_data_url

        media: list = []
        if not isinstance(body.get("messages"), list):
            return body, media
        out = dict(body)
        messages = []
        for msg in body["messages"]:
            content = msg.get("content")
            if isinstance(content, list):
                parts = []
                for part in content:
                    if isinstance(part, dict) and part.get("type") == "image_url":
                        media.append(("image", decode_data_url(part["image_url"]["url"])))
                        parts.append(self.IMAGE_SENTINEL)
                    elif isinstance(part, dict) and part.get("type") == "video_url":
                        media.append(("video", decode_data_url(part["video_url"]["url"])))
                        parts.append(self.IMAGE_SENTINEL)
                    elif isinstance(part, dict) and part.get("type") == "text":
                        parts.append(part.get("text", ""))
                msg = {**msg, "content": "".join(parts)}
            messages.append(msg)
        out["messages"] = messages
        return out, media

    def preprocess(self, body: dict[str, Any], *, image_patches: list[tuple[int, int]] | None = None) -> PreprocessedRequest:
        prompt: str | None
        token_ids: list[int] | None = None
        if "messages" in body:
            extra = {}
            if body.get("tools"):
                # Tool schemas render through the model's chat template (HF
                # templates accept a `tools` kwarg); responses are parsed by
                # frontend/tool_calls.py.
                extra["tools"] = body["tools"]
            prompt = self.formatter.render(body["messages"], add_generation_prompt=True, **extra)
        else:
            raw = body.get("prompt", "")
            if isinstance(raw, str):
                prompt = raw
            elif isinstance(raw, list) and all(isinstance(t, int) for t in raw):
                # OpenAI allows pre-tokenized prompts (array of token ids).
                prompt, token_ids = None, list(raw)
            elif isinstance(raw, list) and len(raw) == 1 and isinstance(raw[0], str):
                prompt = raw[0]
            else:
                raise ValueError("unsupported 'prompt' type: expected string, token-id array, or single-element string array")
        if token_ids is None:
            if image_patches and prompt is not None:
                # image_patches: per-media (count, placeholder_token_id).
                segments = prompt.split(self.IMAGE_SENTINEL)
                if len(segments) != len(image_patches) + 1:
                    raise ValueError(
                        f"{len(segments) - 1} media sentinels in the rendered prompt "
                        f"vs {len(image_patches)} media items (does the chat template drop content?)"
                    )
                token_ids = self.tokenizer.encode(segments[0], add_bos=self.add_bos)
                for (n_patches, tok_id), seg in zip(image_patches, segments[1:]):
                    token_ids += [tok_id] * n_patches
                    if seg:
                        token_ids += self.tokenizer.encode(seg, add_bos=False)
            else:
                token_ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        req = PreprocessedRequest(
            token_ids=token_ids,
            sampling=extract_sampling(body),
            stop=extract_stop(body, default_max_tokens=self.default_max_tokens),
            model=body.get("model"),
            request_id=body.get("request_id") or uuid.uuid4().hex,
            # Multi-tenant admission (dynamo_tpu/sched): tenant_id is stamped
            # into the body from the x-dynamo-tenant header by the frontend;
            # priority is client-settable (higher tier = relaxed deadline).
            tenant_id=(body.get("tenant_id") or None),
            priority=int(body.get("priority") or 0),
        )
        annotations = body.get("nvext", {}).get("annotations") or []
        if "formatted_prompt" in annotations:
            req.annotations["formatted_prompt"] = prompt
        if "token_ids" in annotations:
            req.annotations["token_ids"] = list(token_ids)
        if body.get("embed"):
            # /v1/embeddings: the engine runs the encoder, not generation.
            # All inputs of the request ride as one annotated batch so the
            # worker encodes them in a single device dispatch; lengths are
            # capped because the encoder materializes O(T^2) attention
            # (unlike the paged generation path).
            inputs = [token_ids]
            for item in body.get("embed_batch") or []:
                if isinstance(item, list) and all(isinstance(t, int) for t in item):
                    inputs.append(list(item))
                elif isinstance(item, str):
                    inputs.append(self.tokenizer.encode(item, add_bos=self.add_bos))
                else:
                    raise ValueError("embedding inputs must be strings or token-id arrays")
            for ids in inputs:
                if not ids:
                    raise ValueError("embedding input must not be empty")
                if len(ids) > self.max_embed_tokens:
                    raise ValueError(
                        f"embedding input of {len(ids)} tokens exceeds the "
                        f"{self.max_embed_tokens}-token limit"
                    )
            req.annotations["embed"] = True
            req.annotations["embed_inputs"] = inputs
            req.stop.max_tokens = 1
        return req

    async def transform_request(self, request: Any, context: Context) -> dict:
        if not isinstance(request, dict):
            raise TypeError(f"preprocessor expects an OpenAI body dict, got {type(request)}")
        if self.encoder is not None and self.image_token_id is not None:
            body, media = self._extract_images(request)
            if media:
                import base64

                import numpy as np

                embeds, patches, grids = await self.encoder(media)
                expansion = [
                    (n, self.video_token_id if kind == "video" and self.video_token_id is not None
                     else self.image_token_id)
                    for n, (kind, _b) in zip(patches, media)
                ]
                req = self.preprocess(body, image_patches=expansion)
                req.mm_inputs = {
                    "embeds_b64": base64.b64encode(
                        np.ascontiguousarray(embeds, np.float32).tobytes()
                    ).decode(),
                    "shape": list(embeds.shape),
                    "dtype": "float32",
                }
                if grids:  # Qwen2-VL: engine builds M-RoPE positions from these
                    req.mm_inputs["grids"] = grids
                return req.to_dict()
            request = body
        return self.preprocess(request).to_dict()

    def transform_stream(self, stream: AsyncIterator[Any], request: Any, context: Context) -> AsyncIterator[Any]:
        return stream
