"""Benchmark suite: single-chip serving throughput on the real TPU.

Runs the engine core directly (no HTTP) over a SUITE of model configs
(BASELINE.md tracked classes, sized to one chip):

  llama-3.2-1b            bf16  — round-over-round headline (fixed target)
  llama-3-8b              int8  — 8B-class dense; proves int8 8B fits 16 GB
  deepseek-r1-distill-8b  int8  — BASELINE tracked config #2's model
  olmoe-1b-7b             int8  — real 7B-total MoE (64 experts / top-8)
  mla-8b-proxy            int8  — DeepSeek-V3 MLA geometry on an 8B trunk

Each config runs a continuous-batching decode phase (ISL/OSL scaled from
the reference recipe `benchmarks/llm/perf.sh`: ISL 3000 / OSL 150,
concurrency to 256) and a packed-prefill TTFT phase. The TTFT here is
measured on an otherwise-idle engine (the decode batch has drained) — a
best-case number, labeled ``ttft_idle_*``; TTFT under live decode load is
measured by the closed-loop harness (`python -m dynamo_tpu.bench.pareto`,
committed artifacts in `bench/results/`).

Perf accounting (honest by construction, VERDICT r4 weak #3):

- ``vs_target``: measured / a FIXED external anchor — the 8000 tok/s
  north-star proxy for the 1B, round-4 measured results pinned as
  continuity anchors for the rest. Never the repo's own roofline estimate.
- ``vs_roofline``: measured / the physical ceiling (modeled bytes per
  decode step at the page-granular cache layout, divided by the v5e SPEC
  HBM bandwidth 819 GB/s) — cannot exceed 1 when the byte model is right.
- ``hbm_gbps_achieved`` / ``hbm_utilization``: modeled bytes over measured
  time, and that as a fraction of spec — the bandwidth-utilization view
  (modeled bytes floor real traffic, so utilization is a floor).

Also probes the device-path KV pull bandwidth (loopback
`jax.experimental.transfer` pull of a page stack — the NIXL-equivalent
wire; falls back to the in-process gather→put→scatter path where the PJRT
plugin lacks the transfer engine).

Prints a cumulative JSON snapshot line after every config (a driver
timeout mid-suite still leaves a parseable last line) and the final line
after the KV-pull probe; the headline metric/value is the 1B config
(continuity with BENCH_r01..r03), with every config under detail.configs.
"""

import gc
import json
import os
import time

import numpy as np

# Run on the real chip: do NOT force a platform here.
# Physical HBM bandwidth (v5e datasheet): the roofline denominator. A
# correct byte model divided by the spec ceiling can never yield
# vs_roofline > 1 — r4's "beat the roofline" artifacts came from using a
# practical-bandwidth estimate calibrated on the 1B config as if it were a
# ceiling for every access pattern (VERDICT r4 weak #3).
SPEC_HBM_GBPS = float(os.environ.get("BENCH_SPEC_HBM_GBPS", "819"))
HEADLINE_TARGET = float(os.environ.get("BENCH_TARGET", "8000"))

# Fixed per-config anchors (tok/s/chip), external to the byte model: the 1B
# anchor is the round-1 north-star proxy; the others were pinned from the
# round-4 measured results and stay FIXED so vs_target is comparable across
# rounds (beating your own roofline estimate is not a target).
ANCHOR_TOK_PER_SEC = {
    "llama-3.2-1b": HEADLINE_TARGET,
    "llama-3-8b": 2000.0,
    "deepseek-r1-distill-8b": 2000.0,
    "olmoe-1b-7b": 2600.0,
    "mla-8b-proxy": 3700.0,
}

# (preset, quant, batch, isl, osl, decode_steps)
DEFAULT_SUITE = [
    ("llama-3.2-1b", "", 256, 512, 256, 32),
    ("llama-3-8b", "int8", 48, 512, 128, 32),
    ("deepseek-r1-distill-8b", "int8", 48, 512, 128, 32),
    ("olmoe-1b-7b", "int8", 64, 512, 128, 32),
    ("mla-8b-proxy", "int8", 96, 512, 128, 32),
]


def parse_suite() -> list[tuple[str, str, int, int, int, int]]:
    """BENCH_SUITE="preset:quant:batch:isl:osl:steps,..." overrides; the
    legacy single-config env vars (BENCH_PRESET/BATCH/ISL/OSL/QUANT) select
    a one-entry suite for ad-hoc runs."""
    if os.environ.get("BENCH_SUITE"):
        suite = []
        for part in os.environ["BENCH_SUITE"].split(","):
            f = part.split(":")
            suite.append((f[0], f[1] if len(f) > 1 else "",
                          int(f[2]) if len(f) > 2 else 64,
                          int(f[3]) if len(f) > 3 else 512,
                          int(f[4]) if len(f) > 4 else 128,
                          int(f[5]) if len(f) > 5 else 32))
        return suite
    if os.environ.get("BENCH_PRESET"):
        return [(
            os.environ["BENCH_PRESET"], os.environ.get("BENCH_QUANT", ""),
            int(os.environ.get("BENCH_BATCH", "64")),
            int(os.environ.get("BENCH_ISL", "512")),
            int(os.environ.get("BENCH_OSL", "128")),
            int(os.environ.get("BENCH_DECODE_STEPS", "32")),
        )]
    return DEFAULT_SUITE


def tree_nbytes(tree) -> int:
    # Single source of truth for byte accounting lives in the device-cost
    # plane (observability/cost.py) — the serving-path ledger and this
    # offline suite must agree by construction, not by parallel tree-walks.
    from dynamo_tpu.observability.cost import tree_nbytes as _tree_nbytes

    return _tree_nbytes(tree)


def kv_bytes_per_token(cfg, cache_itemsize: int = 2) -> int:
    """HBM bytes read per cached token per decode step, across all layers.

    Delegates to ModelConfig.kv_bytes_per_token so the MLA accounting uses
    the *physical* cache layout (rope stream lane-padded to 128 — a local
    re-derivation here under-counted the streamed bytes by ~10%, ADVICE r4).
    """
    return cfg.kv_bytes_per_token(itemsize=cache_itemsize)


def decode_step_bytes(params, cfg, batch: int, isl: int, osl: int,
                      page_size: int, cache_itemsize: int = 2) -> int:
    """Mean HBM bytes streamed per decode step, from the ACTUAL geometry:

    - weights: measured tree bytes, minus the embedding table when it is
      untied (decode gathers ``batch`` rows of it, it never streams the
      full table; a tied table IS fully read as the lm_head). MoE expert
      weights are charged in full — correct for every dispatch this suite
      runs: dense reads all experts by definition, the capacity dispatch's
      batched einsum streams all E weight slabs, and at bench decode shapes
      (batch*k >= 8x experts) the dropless ragged_dot touches essentially
      every expert too. A genuinely sparse regime (tiny batch, huge E)
      would overstate bytes, understate the roofline, and could push
      vs_roofline back over 1 — don't use this model there;
    - KV: page-granular — the paged kernels DMA whole pages, so each
      sequence's window is its context rounded up to the page size,
      averaged over the osl decode steps.
    """
    weight_read = decode_weight_bytes(params, cfg)
    per_tok = kv_bytes_per_token(cfg, cache_itemsize)
    page_tokens = sum(
        -(-(isl + s + 1) // page_size) * page_size for s in range(osl)
    ) / max(osl, 1)
    return int(weight_read + batch * page_tokens * per_tok)


def decode_weight_bytes(params, cfg) -> int:
    """The weights component of :func:`decode_step_bytes`: measured tree
    bytes (packed quantized leaves count at their true size, so int8 is
    ~1 byte/elem and int4 ~0.5) minus the embedding table when untied —
    decode gathers ``batch`` rows of it, never the full table."""
    from dynamo_tpu.observability.cost import weight_stream_bytes

    return weight_stream_bytes(params, cfg)


def roofline_tok_per_sec(step_bytes: int, batch: int) -> float:
    """Decode throughput ceiling at the PHYSICAL (spec) HBM bandwidth; one
    step yields ``batch`` tokens. vs_roofline <= 1 by construction."""
    return batch / (step_bytes / (SPEC_HBM_GBPS * 1e9))


def run_config(preset: str, quant: str, batch: int, isl: int, osl: int,
               decode_steps: int) -> dict:
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.models.quant import init_params_quantized
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    cfg = PRESETS[preset]
    # Page 128 is the TPU-idiomatic serving page (JetStream-class stacks use
    # 128-512): each page is one large DMA slab, which the paged-attention
    # kernel needs to stay HBM-bound rather than descriptor-issue-bound
    # (measured: 8.6k tok/s at page 16 -> 11.6k at page 128 on v5e).
    page_size = int(os.environ.get("BENCH_PAGE_SIZE", "128"))
    pages_per_seq = (isl + osl) // page_size + 2
    num_pages = batch * pages_per_seq + 8

    t_init = time.perf_counter()
    if quant:
        # Direct-to-int8 random init: an 8B-class bf16 tree would OOM the
        # chip before quantize_params could shrink it.
        params = init_params_quantized(cfg, 0, mode=quant)
    else:
        params = llama.init_params(cfg, 0)
    weight_bytes = tree_nbytes(params)
    runner_kw = {}
    if os.environ.get("BENCH_KV_DTYPE"):
        import jax.numpy as jnp

        runner_kw["cache_dtype"] = jnp.dtype(os.environ["BENCH_KV_DTYPE"])
    runner = ModelRunner(
        cfg, params, num_pages=num_pages, page_size=page_size,
        max_batch_size=batch, prefill_bucket=max(isl, 64), **runner_kw,
    )
    core = EngineCore(
        runner,
        EngineConfig(
            num_pages=num_pages, page_size=page_size, max_batch_size=batch,
            # Prefill-batch budget per step: on a tunneled chip each step
            # pays a fixed ~100 ms dispatch round-trip, so TTFT at moderate
            # concurrency is minimized by packing many prompts per step.
            max_prefill_tokens=int(os.environ.get("BENCH_MAX_PREFILL", isl * 32)),
            max_seq_len=isl + osl + 8,
            enable_prefix_caching=False,  # uniform-random prompts: raw decode
            decode_steps=decode_steps,
        ),
    )

    rng = np.random.default_rng(0)
    for _ in range(batch):
        prompt = rng.integers(1, cfg.vocab_size - 1, size=isl).tolist()
        core.add_request(PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        ))

    # Warmup: prefills + enough decode dispatches to compile the burst
    # programs (the pipelined path returns the first burst one step late).
    while core.waiting:
        core.step()
    for _ in range(2):
        core.step()
    compile_s = time.perf_counter() - t_init

    start = time.perf_counter()
    generated = 0
    while core.has_work:
        outputs = core.step()
        generated += sum(len(o.token_ids) for _, o in outputs)
    elapsed = time.perf_counter() - start
    tok_per_sec = generated / elapsed if elapsed > 0 else 0.0

    # -- TTFT phase (IDLE-ENGINE BEST CASE: decode batch has drained; the
    # under-load number comes from the pareto harness) -------------------
    ttft_batch = min(batch, int(os.environ.get("BENCH_TTFT_CONCURRENCY", "32")))
    prompts = [rng.integers(1, cfg.vocab_size - 1, size=isl).tolist()
               for _ in range(ttft_batch)]
    submitted: dict[int, float] = {}
    for prompt in prompts:
        seq = core.add_request(PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=1, ignore_eos=True),
        ))
        submitted[id(seq)] = time.perf_counter()
    first_seen: dict[int, float] = {}
    while core.has_work and len(first_seen) < ttft_batch:
        outputs = core.step()
        now = time.perf_counter()
        for seq, out in outputs:
            if id(seq) not in first_seen and out.token_ids:
                first_seen[id(seq)] = now - submitted[id(seq)]
    ttfts = sorted(first_seen.values())

    def pct(p: float) -> float:
        return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))] if ttfts else 0.0

    slo_ttft_s = float(os.environ.get("BENCH_SLO_TTFT_MS", "500")) / 1e3
    slo_attainment = (
        sum(1 for t in ttfts if t <= slo_ttft_s) / len(ttfts) if ttfts else 0.0
    )

    cache_itemsize = np.dtype(runner.k_cache.dtype).itemsize
    step_bytes = decode_step_bytes(params, cfg, batch, isl, osl, page_size,
                                   cache_itemsize)
    roofline = roofline_tok_per_sec(step_bytes, batch)
    # Achieved bandwidth: modeled bytes over MEASURED time — the honest
    # utilization number (modeled bytes are a floor on real traffic, so
    # utilization is a floor too).
    steps = generated / batch
    achieved_gbps = step_bytes * steps / elapsed / 1e9 if elapsed > 0 else 0.0
    # Serving-path ledger (device-cost plane): the decode roofline fraction
    # the production metrics export for this exact run — XLA-counted bytes
    # over measured dispatch wall, vs the auto-detected chip peak. Differs
    # from vs_roofline by construction (modeled bytes + spec bandwidth vs
    # XLA bytes + detected peak); the two bracketing each other is the
    # cross-check.
    live_roofline_frac = 0.0
    cost_reg = getattr(runner, "cost_registry", None)
    if cost_reg is not None:
        cost_reg.drain(timeout=30.0)
        live_roofline_frac = float(
            cost_reg.ledger().get("decode", {}).get("roofline_frac", 0.0)
        )
    target = ANCHOR_TOK_PER_SEC.get(preset, 0.0)
    return {
        "preset": preset, "quant": quant or "bf16", "batch": batch,
        "isl": isl, "osl": osl, "decode_steps": decode_steps,
        "tok_per_sec": round(tok_per_sec, 2),
        "decode_tokens": generated, "seconds": round(elapsed, 3),
        "weights_gb": round(weight_bytes / 2**30, 2),
        "modeled_step_bytes": step_bytes,  # raw bytes: no GB/GiB ambiguity
        "hbm_gbps_achieved": round(achieved_gbps, 1),
        "hbm_utilization": round(achieved_gbps / SPEC_HBM_GBPS, 4),
        "roofline_tok_per_sec": round(roofline, 1),
        "vs_roofline": round(tok_per_sec / roofline, 4) if roofline else 0.0,
        "live_roofline_frac": round(live_roofline_frac, 4),
        "target": round(target, 1),
        "target_kind": ("north_star_proxy" if preset == "llama-3.2-1b"
                        else "fixed_r4_anchor" if target else "none"),
        "vs_target": round(tok_per_sec / target, 4) if target else 0.0,
        "ttft_idle_p50_ms": round(pct(0.50) * 1e3, 1),
        "ttft_idle_p99_ms": round(pct(0.99) * 1e3, 1),
        "ttft_concurrency": ttft_batch,
        "compile_s": round(compile_s, 1),
        # SLO-conditioned headline (the north star is goodput AT the latency
        # target, not raw throughput): fraction of measured TTFTs within the
        # p50 target, and throughput discounted by it.
        "slo_ttft_ms": round(slo_ttft_s * 1e3, 1),
        "slo_ttft_attainment": round(slo_attainment, 4),
        "goodput_tokens_per_s_at_slo": round(tok_per_sec * slo_attainment, 2),
    }


def probe_decode_stall() -> dict:
    """Long-prefill-during-decode stall probe (the metric ISSUE 2 targets).

    A small decode batch streams tokens; mid-stream a long prompt arrives.
    Phase-exclusive scheduling (chunk_prefill_tokens=0) runs the whole
    prefill as one step, freezing every decode for its duration; mixed-step
    scheduling bounds the freeze at roughly one chunk-step. Both modes run
    the identical scenario and report:

      max_decode_stall_ms — longest gap between consecutive steps that
        emitted at least one decode token, over the window where the long
        prefill is in flight (plus the surrounding steady decode, whose
        gaps are the per-step floor);
      itl_p99_ms — p99 inter-token latency across the decode streams.

    Each mode runs the scenario TWICE on the same engine and reports the
    second pass: the step-bucket lattice (batch, time, and page-table-width
    buckets) is data-dependent, so the only warm-up that provably compiles
    every shape the measurement hits is an identical dry run.

    The chunked run's numbers are promoted to stable top-level bench JSON
    keys; detail.stall_probe carries both runs and the stall ratio.
    """
    import jax

    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    preset = os.environ.get("BENCH_STALL_PRESET", "llama-3.2-1b")
    n_decode = int(os.environ.get("BENCH_STALL_DECODERS", "8"))
    short_isl = int(os.environ.get("BENCH_STALL_ISL", "128"))
    osl = int(os.environ.get("BENCH_STALL_OSL", "192"))
    long_isl = int(os.environ.get("BENCH_STALL_PREFILL_ISL", "3072"))
    chunk = int(os.environ.get("BENCH_STALL_CHUNK", "512"))
    cfg = PRESETS[preset]
    page_size = int(os.environ.get("BENCH_PAGE_SIZE", "128"))
    num_pages = (n_decode * ((short_isl + osl) // page_size + 2)
                 + long_isl // page_size + 12)
    params = llama.init_params(cfg, 0)

    def run(chunk_tokens: int) -> dict:
        runner = ModelRunner(
            cfg, params, num_pages=num_pages, page_size=page_size,
            max_batch_size=n_decode + 2, prefill_bucket=max(long_isl, 64),
        )
        core = EngineCore(runner, EngineConfig(
            num_pages=num_pages, page_size=page_size,
            max_batch_size=n_decode + 2, max_prefill_tokens=long_isl,
            max_seq_len=long_isl + osl + 8, enable_prefix_caching=False,
            decode_steps=1, chunk_prefill_tokens=chunk_tokens,
        ))
        rng = np.random.default_rng(1)

        def scenario() -> dict:
            decoders = []
            for _ in range(n_decode):
                decoders.append(core.add_request(PreprocessedRequest(
                    token_ids=rng.integers(1, cfg.vocab_size - 1, size=short_isl).tolist(),
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=osl, ignore_eos=True),
                )))
            while core.waiting or core.prefilling:
                core.step()
            decode_ids = {id(s) for s in decoders}
            emit_times: list[float] = []
            per_seq: dict[int, list[float]] = {id(s): [] for s in decoders}
            injected = False
            steps = 0
            while core.has_work:
                if not injected and steps >= 4:
                    core.add_request(PreprocessedRequest(
                        token_ids=rng.integers(1, cfg.vocab_size - 1, size=long_isl).tolist(),
                        sampling=SamplingOptions(temperature=0.0),
                        stop=StopConditions(max_tokens=4, ignore_eos=True),
                    ))
                    injected = True
                outputs = core.step()
                now = time.perf_counter()
                steps += 1
                got_decode = False
                for seq, out in outputs:
                    if id(seq) in decode_ids and out.token_ids:
                        got_decode = True
                        per_seq[id(seq)].append(now)
                if got_decode:
                    emit_times.append(now)
                if all(s.is_finished for s in decoders):
                    break
            # Drain the injected long prompt so the next pass starts clean.
            while core.has_work:
                core.step()
            gaps = sorted(b - a for a, b in zip(emit_times, emit_times[1:]))
            itls = sorted(b - a for ts in per_seq.values()
                          for a, b in zip(ts, ts[1:]))

            def pct(xs, p):
                return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

            return {
                "chunk_prefill_tokens": chunk_tokens,
                "max_decode_stall_ms": round(max(gaps, default=0.0) * 1e3, 2),
                "decode_step_p50_ms": round(pct(gaps, 0.50) * 1e3, 2),
                "itl_p50_ms": round(pct(itls, 0.50) * 1e3, 2),
                "itl_p99_ms": round(pct(itls, 0.99) * 1e3, 2),
                "mixed_steps": core.mixed_steps,
                "stall_violations": core.stall_violations,
                "steps": steps,
            }

        scenario()  # dry run: compiles every bucket the measured pass hits
        return scenario()

    out = {
        "preset": preset, "decoders": n_decode, "short_isl": short_isl,
        "osl": osl, "long_isl": long_isl, "backend": jax.default_backend(),
    }
    chunked = run(chunk)
    gc.collect()
    baseline = run(0)
    gc.collect()
    out["chunked"] = chunked
    out["baseline_phase_exclusive"] = baseline
    out["stall_ratio_baseline_over_chunked"] = round(
        baseline["max_decode_stall_ms"] / chunked["max_decode_stall_ms"], 2
    ) if chunked["max_decode_stall_ms"] > 0 else 0.0
    return out


def probe_spec_decode() -> dict:
    """Speculative-decoding probe: lossless n-gram drafting vs plain decode.

    Runs the identical repetitive-prompt decode scenario twice — spec_k=0
    (plain mixed steps) and spec_k=K (draft + batched verify) — and reports
    per-mode decode throughput plus the drafter's acceptance rate from the
    engine's own counters. Prompts tile a short token pattern so the
    prompt-lookup drafter has structure to match (the regime speculative
    decoding targets; uniform-random text pins acceptance near zero and
    the probe would only measure verify overhead).

    Like the stall probe, each mode runs the scenario twice on one engine
    and reports the second pass: the verify dispatch adds a (verify_width,
    lp_k) axis to the step-bucket lattice, so only an identical dry run
    provably compiles every shape the measurement hits.

    Top-level bench JSON promotes ``spec_accept_rate`` (accepted/proposed
    draft tokens, measured pass) and ``spec_decode_speedup`` (spec tok/s
    over baseline tok/s; >1 means drafting paid for its verify overhead).
    """
    import jax

    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    preset = os.environ.get("BENCH_SPEC_PRESET", "llama-3.2-1b")
    spec_k = int(os.environ.get("BENCH_SPEC_K", "4"))
    batch = int(os.environ.get("BENCH_SPEC_BATCH", "8"))
    isl = int(os.environ.get("BENCH_SPEC_ISL", "128"))
    osl = int(os.environ.get("BENCH_SPEC_OSL", "128"))
    chunk = int(os.environ.get("BENCH_SPEC_CHUNK", "512"))
    cfg = PRESETS[preset]
    page_size = int(os.environ.get("BENCH_PAGE_SIZE", "128"))
    num_pages = batch * ((isl + osl) // page_size + 2) + 8
    params = llama.init_params(cfg, 0)
    rng = np.random.default_rng(2)
    pattern = rng.integers(1, cfg.vocab_size - 1, size=16).tolist()
    prompts = []
    for i in range(batch):
        # Rotate the shared pattern per request so rows aren't identical
        # but every prompt is still periodic (drafter-matchable).
        rot = pattern[i % len(pattern):] + pattern[:i % len(pattern)]
        prompts.append((rot * (isl // len(rot) + 1))[:isl])

    def run(k: int) -> dict:
        runner = ModelRunner(
            cfg, params, num_pages=num_pages, page_size=page_size,
            max_batch_size=batch, prefill_bucket=max(isl, 64),
        )
        core = EngineCore(runner, EngineConfig(
            num_pages=num_pages, page_size=page_size, max_batch_size=batch,
            max_prefill_tokens=isl * batch, max_seq_len=isl + osl + 8,
            enable_prefix_caching=False, chunk_prefill_tokens=chunk,
            spec_k=k,
        ))

        def scenario() -> dict:
            for prompt in prompts:
                core.add_request(PreprocessedRequest(
                    token_ids=prompt,
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=osl, ignore_eos=True),
                ))
            while core.waiting or core.prefilling:
                core.step()
            p0, a0 = core.spec_tokens_proposed, core.spec_tokens_accepted
            t0 = time.perf_counter()
            generated = 0
            steps = 0
            while core.has_work:
                outputs = core.step()
                generated += sum(len(o.token_ids) for _, o in outputs)
                steps += 1
            elapsed = time.perf_counter() - t0
            proposed = core.spec_tokens_proposed - p0
            accepted = core.spec_tokens_accepted - a0
            return {
                "spec_k": k,
                "tok_per_sec": round(generated / elapsed, 1) if elapsed > 0 else 0.0,
                "decode_tokens": generated,
                "decode_steps": steps,
                "spec_tokens_proposed": proposed,
                "spec_tokens_accepted": accepted,
                "spec_accept_rate": round(accepted / proposed, 4) if proposed else 0.0,
            }

        scenario()  # dry run: compiles every bucket the measured pass hits
        return scenario()

    out = {
        "preset": preset, "batch": batch, "isl": isl, "osl": osl,
        "backend": jax.default_backend(),
    }
    spec = run(spec_k)
    gc.collect()
    baseline = run(0)
    gc.collect()
    out["spec"] = spec
    out["baseline"] = baseline
    out["spec_accept_rate"] = spec["spec_accept_rate"]
    out["spec_decode_speedup"] = round(
        spec["tok_per_sec"] / baseline["tok_per_sec"], 4
    ) if baseline["tok_per_sec"] > 0 else 0.0
    return out


def probe_decode_kernel() -> dict:
    """Raw split-K paged-decode kernel microbench (ISSUE 7).

    Times ``paged_decode_attention`` alone — no engine, no weights — over a
    batch x context grid. Per cell it reports achieved HBM read bandwidth:
    modeled KV bytes (the kernel streams every whole page in each row's
    window, K and V) over measured wall time, a floor on real traffic just
    like the suite's utilization number. The best cell is promoted to the
    stable top-level keys ``decode_kernel_gbps`` / ``decode_roofline_frac``
    (fraction of BENCH_SPEC_HBM_GBPS).

    On non-TPU backends the kernel runs in interpret mode with a tiny
    default grid: the key contract still holds but the bandwidth numbers
    are emulation artifacts, not measurements.
    """
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.ops.pallas_paged import (
        decode_kernel_supported,
        paged_decode_attention,
    )

    interpret = jax.default_backend() != "tpu"

    def ints(name: str, default: str) -> list[int]:
        return [int(x) for x in os.environ.get(name, default).split(",") if x]

    batches = ints("BENCH_DK_BATCHES", "1,2" if interpret else "1,8,32")
    contexts = ints("BENCH_DK_CONTEXTS", "128" if interpret else "1024,4096,16384")
    page_size = int(os.environ.get("BENCH_DK_PAGE_SIZE", "16" if interpret else "128"))
    n_heads = int(os.environ.get("BENCH_DK_HEADS", "8" if interpret else "32"))
    n_kv = int(os.environ.get("BENCH_DK_KV", "2" if interpret else "8"))
    head_dim = int(os.environ.get("BENCH_DK_HEAD_DIM", "64" if interpret else "128"))
    iters = int(os.environ.get("BENCH_DK_ITERS", "2" if interpret else "32"))
    width = n_kv * head_dim
    itemsize = 2  # bf16 cache
    out: dict = {
        "backend": jax.default_backend(), "interpret": interpret,
        "page_size": page_size, "n_heads": n_heads, "n_kv_heads": n_kv,
        "head_dim": head_dim, "iters": iters,
    }
    if not decode_kernel_supported(n_heads, head_dim, width, 1, interpret=interpret):
        out.update(error="decode kernel unsupported for this geometry",
                   grid=[], decode_kernel_gbps=0.0, decode_roofline_frac=0.0)
        return out

    # Device-cost-plane ledger over the same calls: the production roofline
    # math (observability/cost.py — auto-detected chip peak, not the
    # BENCH_SPEC constant) fed with the modeled KV bytes and measured wall.
    # live_roofline_frac and decode_roofline_frac diverging flags a stale
    # BENCH_SPEC_HBM_GBPS or a mis-detected chip.
    from dynamo_tpu.observability.cost import CostRegistry, cost_plane_enabled

    cost_reg = CostRegistry() if cost_plane_enabled() else None

    rng = np.random.default_rng(0)
    grid: list[dict] = []
    best = 0.0
    scale = head_dim ** -0.5
    for batch in batches:
        for ctx in contexts:
            pages = -(-ctx // page_size)
            num_pages = batch * pages + 1  # page 0 is the null page
            k_cache = jnp.asarray(
                rng.standard_normal((num_pages, page_size, width)), jnp.bfloat16)
            v_cache = jnp.asarray(
                rng.standard_normal((num_pages, page_size, width)), jnp.bfloat16)
            tables = jnp.arange(1, num_pages, dtype=jnp.int32).reshape(batch, pages)
            q = jnp.asarray(
                rng.standard_normal((batch, 1, n_heads, head_dim)), jnp.float32)
            positions = jnp.full((batch, 1), ctx - 1, jnp.int32)
            # compile (and, per shape bucket, the only pass interpret gets)
            paged_decode_attention(
                q, k_cache, v_cache, tables, positions,
                scale=scale, interpret=interpret,
            ).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                res = paged_decode_attention(
                    q, k_cache, v_cache, tables, positions,
                    scale=scale, interpret=interpret,
                )
            res.block_until_ready()
            dt = time.perf_counter() - t0
            kv_bytes = 2 * batch * pages * page_size * width * itemsize
            gbps = kv_bytes * iters / dt / 1e9 if dt > 0 else 0.0
            best = max(best, gbps)
            if cost_reg is not None:
                key = (batch, ctx)
                if not cost_reg.seen("decode_kernel", key):
                    cost_reg.submit(
                        "decode_kernel", key, "decode",
                        estimate={"bytes": float(kv_bytes), "flops": 0.0},
                    )
                cost_reg.observe("decode_kernel", key, dt / iters, "decode")
            grid.append({
                "batch": batch, "context": ctx,
                "kv_bytes_per_call": kv_bytes,
                "us_per_call": round(dt / iters * 1e6, 1),
                "gbytes_per_sec": round(gbps, 6),
                "roofline_frac": round(gbps / SPEC_HBM_GBPS, 4),
            })
            gc.collect()
    live_frac = 0.0
    if cost_reg is not None:
        ledger = cost_reg.ledger().get("decode", {})
        live_frac = float(ledger.get("roofline_frac", 0.0))
        cost_reg.close()
    out.update(
        grid=grid,
        decode_kernel_gbps=round(best, 6),
        decode_roofline_frac=round(best / SPEC_HBM_GBPS, 6),
        live_roofline_frac=round(live_frac, 6),
    )
    return out


def probe_kv_pull_gbps() -> dict:
    """Device-path KV transfer bandwidth (BASELINE north-star metric).

    Preferred wire: loopback `jax.experimental.transfer` pull of a
    page-stack-sized array (the cross-process NIXL-equivalent). Fallback
    (plugin lacks the transfer engine — e.g. tunneled dev chips): the
    in-process device path used by DeviceKvTransfer (gather→put→scatter)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.disagg.pull_transport import device_pull_supported, get_transport

    size_mb = int(os.environ.get("BENCH_PULL_MB", "256"))
    stack = jnp.ones((size_mb * 2**20 // 2,), jnp.bfloat16)
    stack.block_until_ready()
    out: dict = {"stack_mb": size_mb}
    if device_pull_supported():
        t = get_transport()
        uuid = t.new_uuid()
        t.offer(uuid, [stack])
        sds = jax.ShapeDtypeStruct(stack.shape, stack.dtype,
                                   sharding=stack.sharding)
        t0 = time.perf_counter()
        [back] = t.pull(t.address(), uuid, [sds])
        back.block_until_ready()
        dt = time.perf_counter() - t0
        t.finish_offer(uuid)
        out.update(wire="transfer_engine_loopback",
                   gbytes_per_sec=round(stack.nbytes / dt / 1e9, 3))
        return out
    # In-process device path: a jitted page-granularity gather permutation —
    # the same read-everything/write-everything HBM operation the
    # DeviceKvTransfer gather/scatter path performs (a same-device
    # device_put can alias without copying, so it would overstate).
    pages = stack.reshape(-1, 128 * 1024 // 2)  # 128 KiB pages
    perm = jnp.asarray(np.random.default_rng(0).permutation(pages.shape[0]))
    # Two labeled numbers (VERDICT r4 weak #5 reconciliation):
    # - amortized: iterate INSIDE jit (single dispatch) — raw HBM gather
    #   bandwidth once dispatch latency is amortized;
    # - cold: ONE gather per dispatch — what a single one-shot transfer
    #   sees through the ~10-100 ms tunnel round trip.
    iters = 16
    chain = jax.jit(lambda x, p: jax.lax.fori_loop(0, iters, lambda i, y: y[p], x))
    chain(pages, perm).block_until_ready()  # compile
    t0 = time.perf_counter()
    chain(pages, perm).block_until_ready()
    dt_amortized = time.perf_counter() - t0
    single = jax.jit(lambda x, p: x[p])
    single(pages, perm).block_until_ready()  # compile
    t0 = time.perf_counter()
    single(pages, perm).block_until_ready()
    dt_cold = time.perf_counter() - t0
    out.update(
        wire="in_process_page_gather", iters=iters,
        transfer_engine="unsupported_on_this_plugin",
        definition=(
            "amortized = iters gathers inside ONE jit dispatch (raw HBM "
            "bandwidth); per_dispatch = one warm, already-compiled gather "
            "per dispatch (includes the tunnel round trip; NOT the "
            "compile-inclusive 'cold' of kv_wire_cross_process)"
        ),
        amortized_gbytes_per_sec=round(2 * stack.nbytes * iters / dt_amortized / 1e9, 3),
        per_dispatch_gbytes_per_sec=round(2 * stack.nbytes / dt_cold / 1e9, 3),
    )
    return out


def probe_cross_process_wire() -> dict:
    """The packed-bytes TCP wire between the chip process and a separate
    CPU-mesh OS process: the DCN-path prefill->decode number the in-process
    gather can't stand in for (VERDICT r4 item 3a).

    Runs the wire-v3 stream-count x chunk-size sweep (ISSUE 8): entry 0 of
    BENCH_WIRE_STREAMS is the v2 single-stream baseline the headline
    ``speedup_vs_v2`` is measured against."""
    import asyncio

    from dynamo_tpu.bench.kv_wire import sweep_cross_process

    pages = int(os.environ.get("BENCH_WIRE_PAGES", "8"))
    iters = int(os.environ.get("BENCH_WIRE_ITERS", "5"))
    chunks = tuple(
        int(c) for c in os.environ.get("BENCH_WIRE_CHUNK", "0").split(",")
    )  # 0 = auto (pages/4)
    stream_counts = tuple(
        int(s) for s in os.environ.get("BENCH_WIRE_STREAMS", "0,1,2,4,8").split(",")
    )
    return asyncio.run(sweep_cross_process(
        pages_per_chain=pages, iters=iters,
        stream_counts=stream_counts, chunk_pages_list=chunks,
    ))


def probe_slo_sched() -> dict:
    """SLO admission-control probe (ISSUE 9): EDF + tenant quotas vs FIFO.

    A mixed-tenant burst on the mock-timed engine (MockRunner realtime:
    scheduling is the production EngineCore, latency is the simulated
    timing model, so the probe isolates *policy*): a heavy tenant submits
    a burst of long prompts first, then latency-sensitive light requests
    arrive behind them. FIFO intake serves the heavy burst head-of-line
    and the light requests blow their TTFT budget; the SLO plane (EDF over
    predicted TTFT + a token-bucket quota on the heavy tenant, heavy
    requests at priority tier 1) admits the light requests first.

    Both modes run the identical scenario and report goodput *under* the
    TTFT budget (tokens from requests whose TTFT met it, per second).
    Top-level bench JSON promotes:

      slo_sched_goodput_gain — EDF-mode goodput over FIFO-mode goodput
        (>1 means the plane converted the same capacity into more
        SLO-attaining tokens);
      slo_sched_ttft_p99_ms — p99 TTFT of the tier-0 (light) requests
        under the SLO plane.
    """
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
    from dynamo_tpu.sched import (
        AdmissionConfig, AdmissionController, TenantQuota, TenantRegistry, TtftPredictor,
    )

    n_heavy = int(os.environ.get("BENCH_SLOSCHED_HEAVY", "4"))
    heavy_isl = int(os.environ.get("BENCH_SLOSCHED_HEAVY_ISL", "2048"))
    n_light = int(os.environ.get("BENCH_SLOSCHED_LIGHT", "16"))
    light_isl = int(os.environ.get("BENCH_SLOSCHED_LIGHT_ISL", "128"))
    osl = int(os.environ.get("BENCH_SLOSCHED_OSL", "32"))
    ttft_slo_ms = float(os.environ.get("BENCH_SLOSCHED_TTFT_MS", "250"))
    chunk = int(os.environ.get("BENCH_SLOSCHED_CHUNK", "512"))
    page_size = 16
    num_pages = (n_heavy * (heavy_isl + osl) + n_light * (light_isl + osl)) // page_size + 64
    rng = np.random.default_rng(7)
    heavy_prompts = [rng.integers(1, 31999, size=heavy_isl).tolist() for _ in range(n_heavy)]
    light_prompts = [rng.integers(1, 31999, size=light_isl).tolist() for _ in range(n_light)]

    def run(slo_on: bool) -> dict:
        cfg = EngineConfig(
            num_pages=num_pages, page_size=page_size,
            max_batch_size=n_heavy + n_light, max_prefill_tokens=heavy_isl,
            max_seq_len=heavy_isl + osl + 8, enable_prefix_caching=False,
            chunk_prefill_tokens=chunk,
        )
        runner = MockRunner(num_pages=num_pages, page_size=page_size, realtime=True)
        admission = None
        if slo_on:
            tenants = TenantRegistry()
            # Rate-limit the heavy tenant: the first long prompt borrows the
            # whole bucket, the rest pace in at the refill rate.
            tenants.configure("heavy", TenantQuota(
                rate_tokens_per_s=4 * heavy_isl, burst_tokens=heavy_isl,
            ))
            admission = AdmissionController(
                AdmissionConfig(ttft_budget_s=ttft_slo_ms / 1e3),
                predictor=TtftPredictor(),
                tenants=tenants,
            )
        core = EngineCore(runner, cfg, admission=admission)
        # Heavy burst first (the FIFO head-of-line scenario), lights behind.
        submit: dict[int, float] = {}
        tier0: set[int] = set()
        t0 = time.perf_counter()
        for prompt in heavy_prompts:
            seq = core.add_request(PreprocessedRequest(
                token_ids=prompt, sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
                tenant_id="heavy", priority=1,
            ))
            submit[seq.seq_id] = time.perf_counter()
        for prompt in light_prompts:
            seq = core.add_request(PreprocessedRequest(
                token_ids=prompt, sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            ))
            submit[seq.seq_id] = time.perf_counter()
            tier0.add(seq.seq_id)
        first_tok: dict[int, float] = {}
        done_tokens: dict[int, int] = {}
        while core.has_work:
            for seq, out in core.step():
                now = time.perf_counter()
                if out.token_ids and seq.seq_id not in first_tok:
                    first_tok[seq.seq_id] = now
                done_tokens[seq.seq_id] = out.cumulative_tokens
        elapsed = time.perf_counter() - t0
        ttfts = {
            sid: first_tok[sid] - submit[sid] for sid in first_tok
        }
        met = {sid for sid, t in ttfts.items() if t * 1e3 <= ttft_slo_ms}
        goodput = sum(done_tokens.get(sid, 0) for sid in met) / elapsed if elapsed > 0 else 0.0
        light_ttfts = sorted(t for sid, t in ttfts.items() if sid in tier0)

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

        return {
            "mode": "slo_sched" if slo_on else "fifo",
            "elapsed_s": round(elapsed, 3),
            "requests_met_ttft": len(met),
            "requests_total": len(submit),
            "goodput_tokens_per_s": round(goodput, 1),
            "light_ttft_p50_ms": round(pct(light_ttfts, 0.50) * 1e3, 2),
            "light_ttft_p99_ms": round(pct(light_ttfts, 0.99) * 1e3, 2),
            "deadline_misses": admission.deadline_misses if admission else 0,
            "throttle_events": admission.throttle_events if admission else 0,
            "tenant_throttled": dict(admission.tenants.throttled) if admission else {},
        }

    fifo = run(False)
    gc.collect()
    edf = run(True)
    gc.collect()
    return {
        "ttft_slo_ms": ttft_slo_ms,
        "heavy": {"n": n_heavy, "isl": heavy_isl},
        "light": {"n": n_light, "isl": light_isl},
        "osl": osl,
        "fifo": fifo,
        "slo_sched": edf,
        "slo_sched_goodput_gain": round(
            edf["goodput_tokens_per_s"] / fifo["goodput_tokens_per_s"], 4
        ) if fifo["goodput_tokens_per_s"] > 0 else 0.0,
        "slo_sched_ttft_p99_ms": edf["light_ttft_p99_ms"],
    }


def probe_engine_overlap() -> dict:
    """Overlapped-execution probe (ISSUE 10): DYN_OVERLAP off vs on.

    Identical decode-heavy work on the mock-timed engine (MockRunner
    realtime with a nonzero d2h latency — the blocking device->host result
    copy the overlapped loop exists to hide). The synchronous loop pays
    compute + d2h per token; the depth-1 pipeline dispatches step N+1 with
    device-chained input tokens before harvesting step N, so per-token wall
    collapses toward max(compute, d2h). Both modes run the same scenario and
    the probe asserts the token streams are identical. Top-level bench JSON
    promotes:

      engine_overlap_itl_gain — sync-mode mean ITL over overlap-mode mean
        ITL (>1 means overlap shortened the decode critical path);
      device_idle_frac — fraction of overlap-mode wall time the simulated
        device spent idle (strictly below the sync mode's).
    """
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    decoders = int(os.environ.get("BENCH_OVERLAP_DECODERS", "4"))
    isl = int(os.environ.get("BENCH_OVERLAP_ISL", "32"))
    osl = int(os.environ.get("BENCH_OVERLAP_OSL", "64"))
    decode_us = float(os.environ.get("BENCH_OVERLAP_DECODE_US", "2000"))
    d2h_us = float(os.environ.get("BENCH_OVERLAP_D2H_US", "1500"))
    page_size = 16
    num_pages = decoders * (isl + osl) // page_size + 32
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 31999, size=isl).tolist() for _ in range(decoders)]

    def run(overlap_on: bool) -> tuple[dict, dict[int, list[int]]]:
        cfg = EngineConfig(
            num_pages=num_pages, page_size=page_size, max_batch_size=decoders,
            max_prefill_tokens=isl, max_seq_len=isl + osl + 8,
            enable_prefix_caching=False, chunk_prefill_tokens=0,
            overlap=overlap_on,
        )
        runner = MockRunner(
            num_pages=num_pages, page_size=page_size, realtime=True,
            decode_us_base=decode_us, d2h_us=d2h_us,
        )
        core = EngineCore(runner, cfg)
        for prompt in prompts:
            core.add_request(PreprocessedRequest(
                token_ids=prompt, sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            ))
        tokens: dict[int, list[int]] = {}
        t0 = time.perf_counter()
        while core.has_work:
            for seq, out in core.step():
                tokens.setdefault(seq.seq_id, []).extend(out.token_ids)
        elapsed = time.perf_counter() - t0
        idle_frac = max(0.0, 1.0 - runner.busy_us / (elapsed * 1e6)) if elapsed > 0 else 0.0
        return {
            "mode": "overlap" if overlap_on else "sync",
            "elapsed_s": round(elapsed, 4),
            "itl_mean_ms": round(elapsed * 1e3 / osl, 3),
            "device_idle_frac": round(idle_frac, 4),
            "overlap_steps": dict(core.overlap_step_counts),
            "mean_gap_ms": round(
                core.step_gap_ms_sum / core.step_gap_ms_count, 3
            ) if core.step_gap_ms_count else 0.0,
        }, tokens

    # Mixed-traffic variant (ISSUE 11): staggered admission + chunked
    # prefill at ISL-3000 scale — the workload where PR 10's pipeline
    # barriered on nearly every step. The chained mixed path must keep the
    # pipeline hot (overlap_chained_frac is the fraction of armed steps
    # that dispatched a chained lookahead) while every stream stays
    # bit-identical to the synchronous engine.
    m_decoders = int(os.environ.get("BENCH_OVERLAP_MIXED_DECODERS", "4"))
    m_isl = int(os.environ.get("BENCH_OVERLAP_MIXED_ISL", "3000"))
    m_osl = int(os.environ.get("BENCH_OVERLAP_MIXED_OSL", "32"))
    m_chunk = int(os.environ.get("BENCH_OVERLAP_MIXED_CHUNK", "512"))
    m_stagger = int(os.environ.get("BENCH_OVERLAP_MIXED_STAGGER", "3"))
    m_pages = m_decoders * (m_isl + m_osl) // page_size + 64
    m_prompts = [
        rng.integers(1, 31999, size=m_isl + 37 * i).tolist()
        for i in range(m_decoders)
    ]

    def run_mixed(overlap_on: bool) -> tuple[dict, dict[int, list[int]]]:
        cfg = EngineConfig(
            num_pages=m_pages, page_size=page_size, max_batch_size=m_decoders,
            max_prefill_tokens=max(m_chunk, m_isl), max_seq_len=m_isl + m_osl + 64,
            enable_prefix_caching=False, chunk_prefill_tokens=m_chunk,
            overlap=overlap_on,
        )
        runner = MockRunner(
            num_pages=m_pages, page_size=page_size, realtime=True,
            decode_us_base=decode_us, d2h_us=d2h_us,
        )
        core = EngineCore(runner, cfg)
        reqs = [PreprocessedRequest(
            token_ids=p, sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=m_osl, ignore_eos=True),
        ) for p in m_prompts]
        tokens: dict[int, list[int]] = {}
        admitted = 0
        steps = 0
        t0 = time.perf_counter()
        while core.has_work or admitted < len(reqs):
            # Staggered arrivals: a new long prompt lands every few steps,
            # so admission + chunked prefill continuously interleave with
            # the earlier requests' decodes.
            if admitted < len(reqs) and steps >= admitted * m_stagger:
                core.add_request(reqs[admitted])
                admitted += 1
            for seq, out in core.step():
                tokens.setdefault(seq.seq_id, []).extend(out.token_ids)
            steps += 1
        elapsed = time.perf_counter() - t0
        counts = dict(core.overlap_step_counts)
        armed = sum(counts.values())
        # Time-loss ledger coverage (ISSUE 15): the per-cause accounting
        # must explain nearly all non-compute wall (step wall + inter-step
        # gap - device dispatch). Queue/admission waits are pre-step and
        # excluded from the step-side comparison.
        lost = dict(core.lost_time_ms)
        noncompute = max(
            0.0,
            core.step_wall_ms_total + core.step_gap_ms_sum - core.step_dispatch_ms_total,
        )
        step_lost = sum(v for k, v in lost.items() if k not in ("queue", "admission"))
        return {
            "mode": "overlap" if overlap_on else "sync",
            "elapsed_s": round(elapsed, 4),
            "itl_mean_ms": round(elapsed * 1e3 / m_osl, 3),
            "overlap_steps": counts,
            "barrier_reasons": dict(core.overlap_barrier_counts),
            "overlap_chained_frac": round(
                counts.get("overlapped", 0) / armed, 4
            ) if armed else 0.0,
            "lost_time_ms": {k: round(v, 3) for k, v in sorted(lost.items())},
            "noncompute_wall_ms": round(noncompute, 3),
            "loss_coverage_frac": round(
                min(1.0, step_lost / noncompute), 4) if noncompute > 0 else 1.0,
        }, tokens

    # Constrained-traffic variant (ISSUE 14): JSON-mode rows under overlap.
    # Without mask lookahead every chained constrained row forces a barrier
    # (reason "constraint": the next step's token mask depends on the
    # not-yet-harvested sample), degenerating the pipeline to sync timing.
    # With lookahead the scheduler pre-builds masks for every admissible
    # successor state and resolves the right one in-graph against the
    # chained token; only cold-cache steps barrier ("constraint_miss")
    # while the mask cache warms. Baseline here is overlap ON with
    # constraint_lookahead_tokens=0, isolating the lookahead itself.
    j_decoders = int(os.environ.get("BENCH_OVERLAP_JSON_DECODERS", "4"))
    j_isl = int(os.environ.get("BENCH_OVERLAP_JSON_ISL", "32"))
    j_osl = int(os.environ.get("BENCH_OVERLAP_JSON_OSL", "48"))
    j_lookahead = int(os.environ.get("BENCH_OVERLAP_JSON_LOOKAHEAD", "32"))
    # Small vocab: the digit tokenizer has 9 distinct pieces, and the pure-
    # Python mask builder walks every id — at 32k ids two cold mask builds
    # cost more than the whole decode and swamp the timing comparison.
    j_vocab = int(os.environ.get("BENCH_OVERLAP_JSON_VOCAB", "512"))
    j_pages = j_decoders * (j_isl + j_osl) // page_size + 32
    j_prompts = [rng.integers(1, j_vocab - 2, size=j_isl).tolist()
                 for _ in range(j_decoders)]

    class _DigitTokenizer:
        """Nine-piece vocabulary: every token id decodes to a nonzero digit,
        so each sampled token extends a JSON number forever — the adversarial
        case where a fresh mask must be ready before every decode step."""

        def decode(self, ids, skip_special_tokens=False):
            return "".join("123456789"[int(t) % 9] for t in ids)

    def run_json(lookahead: int) -> tuple[dict, dict[int, list[int]]]:
        cfg = EngineConfig(
            num_pages=j_pages, page_size=page_size, max_batch_size=j_decoders,
            max_prefill_tokens=j_isl, max_seq_len=j_isl + j_osl + 8,
            enable_prefix_caching=False, chunk_prefill_tokens=0,
            overlap=True, constraint_lookahead_tokens=lookahead,
        )
        runner = MockRunner(
            num_pages=j_pages, page_size=page_size, realtime=True,
            vocab_size=j_vocab, decode_us_base=decode_us, d2h_us=d2h_us,
        )
        core = EngineCore(runner, cfg)
        core.set_constraint_tokenizer(_DigitTokenizer())
        for prompt in j_prompts:
            core.add_request(PreprocessedRequest(
                token_ids=prompt,
                sampling=SamplingOptions(temperature=0.0, json_mode=True),
                stop=StopConditions(max_tokens=j_osl, ignore_eos=True),
            ))
        tokens: dict[int, list[int]] = {}
        t0 = time.perf_counter()
        while core.has_work:
            for seq, out in core.step():
                tokens.setdefault(seq.seq_id, []).extend(out.token_ids)
        elapsed = time.perf_counter() - t0
        counts = dict(core.overlap_step_counts)
        armed = sum(counts.values())
        return {
            "mode": f"lookahead_{lookahead}" if lookahead else "no_lookahead",
            "elapsed_s": round(elapsed, 4),
            "itl_mean_ms": round(elapsed * 1e3 / j_osl, 3),
            "overlap_steps": counts,
            "barrier_reasons": dict(core.overlap_barrier_counts),
            "overlap_barrier_frac": round(
                counts.get("barrier", 0) / armed, 4
            ) if armed else 0.0,
            "mask_cache_hits": core.constraint_mask_cache_hits,
            "mask_cache_misses": core.constraint_mask_cache_misses,
        }, tokens

    sync, sync_tokens = run(False)
    gc.collect()
    overlap, overlap_tokens = run(True)
    gc.collect()
    m_sync, m_sync_tokens = run_mixed(False)
    gc.collect()
    m_overlap, m_overlap_tokens = run_mixed(True)
    gc.collect()
    j_base, j_base_tokens = run_json(0)
    gc.collect()
    j_la, j_la_tokens = run_json(j_lookahead)
    gc.collect()
    return {
        "decoders": decoders, "isl": isl, "osl": osl,
        "decode_us": decode_us, "d2h_us": d2h_us,
        "sync": sync,
        "overlap": overlap,
        "bit_identical": sync_tokens == overlap_tokens,
        "engine_overlap_itl_gain": round(
            sync["itl_mean_ms"] / overlap["itl_mean_ms"], 4
        ) if overlap["itl_mean_ms"] > 0 else 0.0,
        "device_idle_frac": overlap["device_idle_frac"],
        "mixed": {
            "decoders": m_decoders, "isl": m_isl, "osl": m_osl,
            "chunk": m_chunk, "stagger_steps": m_stagger,
            "sync": m_sync,
            "overlap": m_overlap,
            "bit_identical": m_sync_tokens == m_overlap_tokens,
        },
        "overlap_chained_frac": m_overlap["overlap_chained_frac"],
        "loss_coverage_frac": m_overlap["loss_coverage_frac"],
        "engine_overlap_mixed_itl_gain": round(
            m_sync["itl_mean_ms"] / m_overlap["itl_mean_ms"], 4
        ) if m_overlap["itl_mean_ms"] > 0 else 0.0,
        "constrained": {
            "decoders": j_decoders, "isl": j_isl, "osl": j_osl,
            "lookahead": j_lookahead,
            "no_lookahead": j_base,
            "lookahead_on": j_la,
            "bit_identical": j_base_tokens == j_la_tokens,
        },
        "overlap_constrained_itl_gain": round(
            j_base["itl_mean_ms"] / j_la["itl_mean_ms"], 4
        ) if j_la["itl_mean_ms"] > 0 else 0.0,
        "overlap_barrier_frac": j_la["overlap_barrier_frac"],
    }


def probe_prefix_reuse() -> dict:
    """Cache-aware serving probe (ISSUE 12): KV-tier reuse on vs off.

    A prefix-heavy workload from the synthesizer (shared system prompt +
    per-group few-shot prefixes + unique tails) replayed open-loop at fixed
    QPS on the mock-timed engine. The warm pass runs one prefix-covering
    request per group and write-through offloads their committed pages into
    a G2 host tier whose reads carry a simulated per-block latency; the G1
    prefix cache is then cleared, so every replay hit must come back
    through async tier onboarding (DYN_ASYNC_ONBOARD path: background
    fetch + batched write_pages landing, overlapped with other rows'
    prefill/decode compute). The reuse-off pass replays the identical
    arrival schedule with prefix caching disabled. Top-level bench JSON
    promotes:

      prefix_reuse_ttft_gain — reuse-off TTFT p50 over reuse-on TTFT p50
        at the same fixed QPS (>1 means tier reuse shortened time to first
        token);
      prefix_onboard_overlap_frac — fraction of engine steps with an
        onboarding session in flight that still dispatched fresh work
        (tier fetch demonstrably overlapped with compute, not stalled).
    """
    from dynamo_tpu.bench.synthesizer import SyntheticConfig, synthesize
    from dynamo_tpu.blocks import BlockManagerConfig, KvBlockManager
    from dynamo_tpu.blocks.storage import HostStorage
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    groups = int(os.environ.get("BENCH_PREFIXREUSE_GROUPS", "4"))
    n_requests = int(os.environ.get("BENCH_PREFIXREUSE_REQUESTS", "16"))
    shared_isl = int(os.environ.get("BENCH_PREFIXREUSE_SHARED_ISL", "512"))
    group_isl = int(os.environ.get("BENCH_PREFIXREUSE_GROUP_ISL", "256"))
    unique_isl = int(os.environ.get("BENCH_PREFIXREUSE_UNIQUE_ISL", "64"))
    osl = int(os.environ.get("BENCH_PREFIXREUSE_OSL", "16"))
    qps = float(os.environ.get("BENCH_PREFIXREUSE_QPS", "40"))
    chunk = int(os.environ.get("BENCH_PREFIXREUSE_CHUNK", "256"))
    fetch_us = float(os.environ.get("BENCH_PREFIXREUSE_FETCH_US", "100"))
    page_size = 16
    isl = shared_isl + group_isl + unique_isl
    num_pages = n_requests * ((isl + osl) // page_size + 2) + 64

    workload = synthesize(SyntheticConfig(
        num_requests=n_requests, shared_prefix_len=shared_isl,
        num_groups=groups, group_prefix_len=group_isl, unique_len=unique_isl,
        osl_mean=osl, osl_cv=0.0, vocab=31999, seed=5,
    ))
    prefix_len = (shared_isl + group_isl) // page_size * page_size
    warm_prompts = {}  # group -> prefix-only prompt (page-aligned)
    for req in workload:
        warm_prompts.setdefault(req.group, req.token_ids[:prefix_len])

    class SlowHostStorage(HostStorage):
        """G2 payload reads pay a simulated tier latency — the window the
        async onboarding session exists to hide under compute."""

        def read(self, block_hash):
            payload = super().read(block_hash)
            if payload is not None and fetch_us > 0:
                time.sleep(fetch_us / 1e6)
            return payload

        def exists(self, block_hash):  # membership probes stay cheap
            return block_hash in self._data

    def run(reuse_on: bool) -> dict:
        cfg = EngineConfig(
            num_pages=num_pages, page_size=page_size,
            max_batch_size=n_requests, max_prefill_tokens=isl,
            max_seq_len=isl + osl + 8, chunk_prefill_tokens=chunk,
            enable_prefix_caching=reuse_on, async_onboard=reuse_on,
        )
        runner = MockRunner(num_pages=num_pages, page_size=page_size, realtime=True)
        bm = None
        if reuse_on:
            bm = KvBlockManager(
                BlockManagerConfig(g2_capacity_blocks=4096),
                read_page=runner.read_page, write_page=runner.write_page,
                write_pages=runner.write_pages, g2_storage=SlowHostStorage(),
            )
        core = EngineCore(runner, cfg, block_manager=bm)
        if reuse_on:
            # Warm pass: commit each group's shared prefix and write it
            # through to G2, then drop G1 — replay reuse must onboard.
            for prompt in warm_prompts.values():
                core.add_request(PreprocessedRequest(
                    token_ids=prompt, sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=2, ignore_eos=True),
                ))
            while core.has_work:
                core.step()
                core.flush_offloads()
            core.allocator.clear_cache()
        submit: dict[int, float] = {}
        first: dict[int, float] = {}
        arrivals = [i / qps for i in range(len(workload))]
        i = 0
        t0 = time.perf_counter()
        while core.has_work or i < len(workload):
            now = time.perf_counter() - t0
            while i < len(workload) and now >= arrivals[i]:
                seq = core.add_request(PreprocessedRequest(
                    token_ids=workload[i].token_ids,
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=workload[i].max_tokens,
                                        ignore_eos=True),
                ))
                submit[seq.seq_id] = time.perf_counter()
                i += 1
            if not core.has_work:
                if i < len(workload):  # open-loop: idle until next arrival
                    time.sleep(max(0.0, arrivals[i] - (time.perf_counter() - t0)))
                continue
            for seq, out in core.step():
                if out.token_ids and seq.seq_id not in first:
                    first[seq.seq_id] = time.perf_counter()
            core.flush_offloads()
        elapsed = time.perf_counter() - t0
        ttfts = sorted(first[sid] - submit[sid] for sid in first)

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else 0.0

        ob_steps = core.onboard_overlap_steps + core.onboard_stall_steps
        return {
            "mode": "reuse" if reuse_on else "cold",
            "elapsed_s": round(elapsed, 3),
            "ttft_p50_ms": round(pct(ttfts, 0.50) * 1e3, 2),
            "ttft_p99_ms": round(pct(ttfts, 0.99) * 1e3, 2),
            "onboard_sessions": core.onboard_sessions,
            "onboard_pages_by_tier": dict(core.onboard_page_counts),
            "onboard_shortfall_pages": core.onboard_shortfall_pages,
            "onboard_overlap_steps": core.onboard_overlap_steps,
            "onboard_stall_steps": core.onboard_stall_steps,
            "onboard_overlap_frac": round(
                core.onboard_overlap_steps / ob_steps, 4) if ob_steps else 0.0,
            "onboard_wait_ms_mean": round(
                core.onboard_wait_ms_sum / core.onboard_wait_count, 3
            ) if core.onboard_wait_count else 0.0,
            "cached_frac_last": core.last_admission.get("cached_frac", 0.0),
        }

    cold = run(False)
    gc.collect()
    reuse = run(True)
    gc.collect()
    return {
        "groups": groups, "requests": n_requests, "qps": qps,
        "isl": {"shared": shared_isl, "group": group_isl, "unique": unique_isl},
        "osl": osl, "fetch_us_per_block": fetch_us,
        "cold": cold,
        "reuse": reuse,
        "prefix_reuse_ttft_gain": round(
            cold["ttft_p50_ms"] / reuse["ttft_p50_ms"], 4
        ) if reuse["ttft_p50_ms"] > 0 else 0.0,
        "prefix_onboard_overlap_frac": reuse["onboard_overlap_frac"],
    }


def probe_fleet_sim() -> dict:
    """Fleet-simulation probe (ISSUE 13): a small fixed scenario end-to-end.

    Runs a registered fleetsim scenario (default ``smoke``: a deterministic
    Poisson trace replayed open-loop against the real frontend/router/store
    with mock workers as OS processes) twice — a dry run that generates and
    digests the trace without spawning anything, then the measured run.
    Top-level bench JSON promotes:

      fleet_goodput_frac_at_slo — fraction of the scenario's requests that
        attained the SLO (TTFT and per-request p99 ITL within targets),
        with TTFT clocked from intended injection time (open loop, no
        coordinated omission);
      fleet_tenant_fairness — min/max ratio of per-tenant attainment
        fractions (1.0 = perfectly fair).
    """
    import asyncio

    from dynamo_tpu.fleetsim.scenario import SCENARIOS, run_scenario

    name = os.environ.get("BENCH_FLEET_SCENARIO", "smoke")
    workers = int(os.environ.get("BENCH_FLEET_WORKERS", "0"))
    scn = SCENARIOS[name]
    dry = asyncio.run(run_scenario(scn, dry_run=True))
    report = asyncio.run(run_scenario(scn, workers_override=workers))
    return {
        "scenario": name,
        "trace_digest": dry["trace"]["digest"],
        "trace_events": dry["trace"]["events"],
        "digest_stable": dry["trace"]["digest"] == report["trace"]["digest"],
        "duration_s": report.get("duration_s", 0.0),
        "requests": report.get("requests", {}),
        "ttft_ms": report.get("ttft_ms", {}),
        "itl_ms": report.get("itl_ms", {}),
        "fleet": report.get("fleet", {}),
        "passed": report.get("passed"),
        "fleet_goodput_frac_at_slo": report.get("goodput_frac_at_slo", 0.0),
        "fleet_tenant_fairness": report.get("tenant_fairness", 0.0),
    }


def probe_quant_sweep() -> dict:
    """Quant-mode sweep (ISSUE 16): one shape, bf16 vs int8 vs int4.

    Runs the 8b proxy at an identical (batch, isl, osl) across the three
    weight formats so the bench trajectory captures the decode roofline
    burn-down directly. Top-level bench JSON promotes:

      quant_int8_decode_gain — int8 decode tok/s over the bf16 baseline
      quant_int4_decode_gain — int4 decode tok/s over the bf16 baseline
      quant_int4_vs_int8_decode_gain — int4 over int8, both measured

    The bf16 leg of an 8B-class proxy does not fit a 16 GB chip; when it
    OOMs, the baseline falls back to a bandwidth-modeled figure (the int4
    run's MEASURED achieved GB/s against the bf16 step's modeled bytes)
    and ``bf16_basis`` says so — on larger-HBM parts all three legs
    measure for real.
    """
    from dynamo_tpu.models.config import PRESETS

    spec = os.environ.get("BENCH_QUANT_SWEEP", "mla-8b-proxy:48:512:64:32")
    f = spec.split(":")
    preset, batch = f[0], int(f[1]) if len(f) > 1 else 48
    isl = int(f[2]) if len(f) > 2 else 512
    osl = int(f[3]) if len(f) > 3 else 64
    steps = int(f[4]) if len(f) > 4 else 32
    cfg = PRESETS[preset]
    modes: dict = {}
    for quant in ("", "int8", "int4"):
        label = quant or "bf16"
        try:
            modes[label] = run_config(preset, quant, batch, isl, osl, steps)
        except Exception as e:  # OOM (bf16 8B on a 16 GB chip) or compile
            modes[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        gc.collect()

    def tps(label: str) -> float:
        return modes.get(label, {}).get("tok_per_sec", 0.0)

    bf16_basis = "measured"
    bf16_tps = tps("bf16")
    if not bf16_tps and tps("int4"):
        # Model the baseline from the int4 leg's measured bandwidth: same
        # achieved GB/s, bf16-sized step bytes (weights at 2 bytes/elem).
        int4 = modes["int4"]
        bf16_params_bytes = tree_nbytes_modeled_bf16(cfg)
        int4_step = int4["modeled_step_bytes"]
        int4_weight = int4["weights_gb"] * 2**30
        bf16_step = int4_step - int4_weight + bf16_params_bytes
        bf16_tps = int4["hbm_gbps_achieved"] * 1e9 / bf16_step * batch
        bf16_basis = "modeled_from_int4_achieved_bw"
    return {
        "preset": preset, "batch": batch, "isl": isl, "osl": osl,
        "decode_steps": steps, "modes": modes,
        "bf16_basis": bf16_basis,
        "bf16_baseline_tok_per_sec": round(bf16_tps, 2),
        "quant_int8_decode_gain": round(tps("int8") / bf16_tps, 4) if bf16_tps else 0.0,
        "quant_int4_decode_gain": round(tps("int4") / bf16_tps, 4) if bf16_tps else 0.0,
        "quant_int4_vs_int8_decode_gain": round(
            tps("int4") / tps("int8"), 4) if tps("int8") else 0.0,
    }


def tree_nbytes_modeled_bf16(cfg) -> int:
    """Weight bytes of the preset AT bf16 without materializing the tree
    (the whole point is that the bf16 tree may not fit)."""
    import jax

    from dynamo_tpu.models import llama

    shapes = jax.eval_shape(lambda: llama.init_params(cfg, 0))
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(shapes))


def probe_mask_build() -> dict:
    """Constrained-decoding cold-mask-build probe (ISSUE 16).

    Builds masks for a corpus of JSON-machine summaries over a synthetic
    128k-piece vocab with the vectorized builder and the pure-Python one,
    asserting bitwise identity (masks, close budgets, transition
    descriptors). Top-level bench JSON promotes:

      constraint_mask_build_ms — mean vectorized cold-build wall ms
      constraint_mask_build_gain — pure-Python ms over vectorized ms
    """
    import random

    from dynamo_tpu import constrained as C

    vocab = int(os.environ.get("BENCH_MASK_VOCAB", "128000"))
    rnd = random.Random(7)
    chars = list('{}[]",: \t\n0123456789.-+eE') + list(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_\\/"
    ) + ["٣", "é", "世", "�"]
    pieces = [""]
    while len(pieces) < vocab:
        n = rnd.choice((1, 1, 2, 3, 4, 5, 6, 8, 12))
        pieces.append("".join(rnd.choice(chars) for _ in range(n)))

    class _Tok:
        def decode(self, ids, skip_special_tokens=False):
            return pieces[ids[0]]

    states = [
        C.advance_text(C.MachineState(), t)
        for t in ("", "{", '{"', '{"k": ', '{"k": "v', '{"k": [1, ', "[1")
    ]
    cache = C.TokenMaskCache(_Tok(), len(pieces), (0,))
    plist = cache._ensure_pieces()
    t0 = time.perf_counter()
    cache._vocab_table()
    table_s = time.perf_counter() - t0
    vec_s = py_s = 0.0
    mismatches = 0
    for st in states:
        key = st.summary()
        t0 = time.perf_counter()
        av, cv = cache._build_mask_vectorized(st, key, plist)
        vec_s += time.perf_counter() - t0
        dv = cache._descs[key]
        t0 = time.perf_counter()
        ap, cp = cache._build_mask_python(st, key, plist)
        py_s += time.perf_counter() - t0
        dp = cache._descs[key]
        if not (np.array_equal(av, ap) and np.array_equal(cv, cp)
                and np.array_equal(dv[0], dp[0]) and dv[1] == dp[1]):
            mismatches += 1
    n = len(states)
    return {
        "vocab": vocab, "summaries": n, "mismatches": mismatches,
        "table_build_ms": round(table_s * 1e3, 1),
        "python_build_ms": round(py_s / n * 1e3, 1),
        "constraint_mask_build_ms": round(vec_s / n * 1e3, 2),
        "constraint_mask_build_gain": round(py_s / vec_s, 1) if vec_s else 0.0,
    }


def build_doc(configs, pull, wire=None, stall=None, spec=None,
              decode_kernel=None, slo_sched=None, overlap=None,
              prefix_reuse=None, fleet=None, quant_sweep=None,
              mask_build=None) -> dict:
    """The bench JSON document (one stdout line per emit).

    Module-level (not a closure) so its top-level key contract — the stable
    serving-quality keys downstream BENCH_*.json tracking reads — is directly
    testable without running the suite.
    """
    import jax

    head = next((c for c in configs if c.get("preset") == "llama-3.2-1b"
                 and "error" not in c), None) or \
        next((c for c in configs if "error" not in c), {})
    return {
        "metric": "output_tokens_per_sec_per_chip",
        "value": head.get("tok_per_sec", 0.0),
        "unit": "tok/s",
        "vs_baseline": round(head.get("tok_per_sec", 0.0) / HEADLINE_TARGET, 4),
        # Stable top-level serving-quality keys (ISSUE 2): from the
        # chunked run of the long-prefill-during-decode stall probe.
        "itl_p99_ms": (stall or {}).get("chunked", {}).get("itl_p99_ms", 0.0),
        "max_decode_stall_ms": (stall or {}).get("chunked", {}).get(
            "max_decode_stall_ms", 0.0),
        # SLO-conditioned headline keys (ISSUE 4): the north-star metric is
        # goodput at p50 TTFT <= 500 ms, so BENCH_*.json tracks it directly.
        "goodput_tokens_per_s_at_slo": head.get("goodput_tokens_per_s_at_slo", 0.0),
        "slo_ttft_attainment": head.get("slo_ttft_attainment", 0.0),
        # Speculative decoding headline keys (ISSUE 6): acceptance rate and
        # spec-over-baseline decode speedup from the spec probe's measured
        # pass (repetitive-prompt scenario, see probe_spec_decode).
        "spec_accept_rate": (spec or {}).get("spec_accept_rate", 0.0),
        "spec_decode_speedup": (spec or {}).get("spec_decode_speedup", 0.0),
        # Decode-kernel headline keys (ISSUE 7): best achieved HBM bandwidth
        # of the raw split-K paged-decode kernel and its roofline fraction
        # (see probe_decode_kernel; meaningless off-TPU but always present).
        "decode_kernel_gbps": (decode_kernel or {}).get("decode_kernel_gbps", 0.0),
        "decode_roofline_frac": (decode_kernel or {}).get("decode_roofline_frac", 0.0),
        # Device-cost-plane headline key (ISSUE 19): the serving-path
        # ledger's decode roofline fraction — XLA/estimate bytes over
        # measured dispatch wall against the auto-detected chip peak, the
        # same number dynamo_engine_roofline_frac exports in production.
        # Taken from the engine suite's head config when it ran with the
        # cost plane on, else from the kernel probe's ledger.
        "live_roofline_frac": head.get(
            "live_roofline_frac",
            (decode_kernel or {}).get("live_roofline_frac", 0.0),
        ) or (decode_kernel or {}).get("live_roofline_frac", 0.0),
        # KV-wire headline keys (ISSUE 8): best amortized cross-process wire
        # bandwidth from the stream-count x chunk-size sweep and its overlap
        # fraction (see probe_cross_process_wire / bench/kv_wire.py).
        "kv_wire_gbps": (wire or {}).get("kv_wire_gbps", 0.0),
        "kv_wire_overlap_frac": (wire or {}).get("kv_wire_overlap_frac", 0.0),
        # SLO admission-control headline keys (ISSUE 9): EDF+quota goodput
        # over FIFO goodput under the TTFT budget, and the light-tier TTFT
        # tail under the SLO plane (see probe_slo_sched).
        "slo_sched_goodput_gain": (slo_sched or {}).get("slo_sched_goodput_gain", 0.0),
        "slo_sched_ttft_p99_ms": (slo_sched or {}).get("slo_sched_ttft_p99_ms", 0.0),
        # Overlapped-execution headline keys (ISSUE 10): sync-over-overlap
        # mean ITL ratio and the overlapped mode's device-idle fraction on
        # identical decode-heavy work (see probe_engine_overlap).
        "engine_overlap_itl_gain": (overlap or {}).get("engine_overlap_itl_gain", 0.0),
        "device_idle_frac": (overlap or {}).get("device_idle_frac", 0.0),
        # Always-on overlap headline keys (ISSUE 11): fraction of armed
        # steps that dispatched a chained lookahead on the mixed-traffic
        # workload (staggered ISL-3000 admission + chunked prefill riding
        # live decodes), and the sync-over-overlap mean ITL ratio there.
        "overlap_chained_frac": (overlap or {}).get("overlap_chained_frac", 0.0),
        "engine_overlap_mixed_itl_gain": (overlap or {}).get(
            "engine_overlap_mixed_itl_gain", 0.0),
        # Attribution headline key (ISSUE 15): fraction of non-compute wall
        # in the mixed overlap probe explained by the time-loss ledger.
        "loss_coverage_frac": (overlap or {}).get("loss_coverage_frac", 0.0),
        # Chained constrained decode headline keys (ISSUE 14): ITL ratio of
        # lookahead-off over lookahead-on JSON-mode traffic under overlap
        # (both bit-identical streams), and the lookahead-on run's residual
        # barrier fraction (cold mask-cache steps only).
        "overlap_constrained_itl_gain": (overlap or {}).get(
            "overlap_constrained_itl_gain", 0.0),
        "overlap_barrier_frac": (overlap or {}).get(
            "overlap_barrier_frac", 0.0),
        # Cache-aware serving headline keys (ISSUE 12): cold-over-reuse TTFT
        # p50 at fixed QPS on the prefix-heavy workload, and the fraction of
        # onboarding-pending steps that still dispatched fresh work (tier
        # fetch overlapped with compute; see probe_prefix_reuse).
        "prefix_reuse_ttft_gain": (prefix_reuse or {}).get(
            "prefix_reuse_ttft_gain", 0.0),
        "prefix_onboard_overlap_frac": (prefix_reuse or {}).get(
            "prefix_onboard_overlap_frac", 0.0),
        # Fleet-simulation headline keys (ISSUE 13): goodput-under-SLO and
        # per-tenant fairness from the fixed fleet scenario replayed against
        # the real control plane with process-per-worker mock engines (see
        # probe_fleet_sim / dynamo_tpu/fleetsim).
        "fleet_goodput_frac_at_slo": (fleet or {}).get(
            "fleet_goodput_frac_at_slo", 0.0),
        "fleet_tenant_fairness": (fleet or {}).get("fleet_tenant_fairness", 0.0),
        # Quantization headline keys (ISSUE 16): decode tok/s of each weight
        # format over the bf16 baseline on one 8b-proxy shape, plus the
        # always-measured int4-over-int8 ratio (see probe_quant_sweep for
        # the bf16 OOM fallback semantics).
        "quant_int8_decode_gain": (quant_sweep or {}).get(
            "quant_int8_decode_gain", 0.0),
        "quant_int4_decode_gain": (quant_sweep or {}).get(
            "quant_int4_decode_gain", 0.0),
        "quant_int4_vs_int8_decode_gain": (quant_sweep or {}).get(
            "quant_int4_vs_int8_decode_gain", 0.0),
        # Constrained-decoding cold-build headline keys (ISSUE 16): mean
        # vectorized cold mask build at 128k vocab and its speedup over the
        # pure-Python builder, bitwise-identity asserted (probe_mask_build).
        "constraint_mask_build_ms": (mask_build or {}).get(
            "constraint_mask_build_ms", 0.0),
        "constraint_mask_build_gain": (mask_build or {}).get(
            "constraint_mask_build_gain", 0.0),
        "detail": {
            "backend": jax.default_backend(),
            "suite": [c.get("preset") for c in configs],
            "configs": configs,
            "stall_probe": stall or {"pending": True},
            "spec_probe": spec or {"pending": True},
            "decode_kernel_probe": decode_kernel or {"pending": True},
            "slo_sched_probe": slo_sched or {"pending": True},
            "engine_overlap_probe": overlap or {"pending": True},
            "prefix_reuse_probe": prefix_reuse or {"pending": True},
            "fleet_sim_probe": fleet or {"pending": True},
            "quant_sweep_probe": quant_sweep or {"pending": True},
            "mask_build_probe": mask_build or {"pending": True},
            "kv_pull": pull,
            "kv_wire_cross_process": wire or {"pending": True},
            "ttft_note": "ttft_idle_* is the drained-engine best case; "
                         "under-load TTFT: bench/results pareto artifacts",
        },
    }


def main() -> None:
    from dynamo_tpu.models.config import PRESETS

    def emit(configs, pull, wire=None, stall=None, spec=None, dk=None, ss=None,
             ov=None, pr=None, fl=None, qs=None, mb=None):
        print(json.dumps(build_doc(configs, pull, wire, stall, spec, dk, ss, ov,
                                   pr, fl, qs, mb)),
              flush=True)

    suite = parse_suite()
    configs = []
    for entry in suite:
        # MoE on the axon AOT toolchain: lax.ragged_dot crashes the compile
        # helper at 64 experts and the capacity scatter->batched-matmul
        # composition never finishes scheduling at decode shapes; the dense
        # decode formulation compiles and hits roofline (models/llama.py
        # _mlp_moe). Opt MoE configs in automatically unless the caller set
        # a dispatch explicitly.
        preset_cfg = PRESETS.get(entry[0])
        moe_env = (preset_cfg is not None and preset_cfg.is_moe
                   and "DYNAMO_MOE_DISPATCH" not in os.environ)
        if moe_env:
            os.environ["DYNAMO_MOE_DISPATCH"] = "dense"
        try:
            configs.append(run_config(*entry))
        except Exception as e:  # OOM or compile failure: record, continue
            configs.append({"preset": entry[0], "quant": entry[1] or "bf16",
                            "error": f"{type(e).__name__}: {e}"[:300]})
        finally:
            if moe_env:
                del os.environ["DYNAMO_MOE_DISPATCH"]
        gc.collect()
        # Cumulative snapshot after EVERY config: if a driver timeout kills
        # the suite mid-run, the last stdout line still parses with every
        # config completed so far.
        emit(configs, {"pending": True})
    try:
        stall = probe_decode_stall()
    except Exception as e:
        stall = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall)
    gc.collect()
    try:
        spec = probe_spec_decode()
    except Exception as e:
        spec = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec)
    gc.collect()
    try:
        dk = probe_decode_kernel()
    except Exception as e:
        dk = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk)
    gc.collect()
    try:
        ss = probe_slo_sched()
    except Exception as e:
        ss = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss)
    gc.collect()
    try:
        ov = probe_engine_overlap()
    except Exception as e:
        ov = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov)
    gc.collect()
    try:
        pr = probe_prefix_reuse()
    except Exception as e:
        pr = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov,
         pr=pr)
    gc.collect()
    try:
        fl = probe_fleet_sim()
    except Exception as e:
        fl = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov,
         pr=pr, fl=fl)
    gc.collect()
    try:
        qs = probe_quant_sweep()
    except Exception as e:
        qs = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov,
         pr=pr, fl=fl, qs=qs)
    gc.collect()
    try:
        mb = probe_mask_build()
    except Exception as e:
        mb = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, {"pending": True}, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov,
         pr=pr, fl=fl, qs=qs, mb=mb)
    gc.collect()
    try:
        pull = probe_kv_pull_gbps()
    except Exception as e:
        pull = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, pull, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov, pr=pr, fl=fl,
         qs=qs, mb=mb)
    gc.collect()
    try:
        wire = probe_cross_process_wire()
    except Exception as e:
        wire = {"error": f"{type(e).__name__}: {e}"[:200]}
    emit(configs, pull, wire, stall=stall, spec=spec, dk=dk, ss=ss, ov=ov, pr=pr,
         fl=fl, qs=qs, mb=mb)


if __name__ == "__main__":
    import sys

    if "--tune" in sys.argv:
        # Closed-loop knob auto-tune instead of the measurement suite:
        # remaining flags pass through to python -m dynamo_tpu.tuning.
        from dynamo_tpu.tuning.__main__ import main as tune_main

        sys.exit(tune_main([a for a in sys.argv[1:] if a != "--tune"]))
    main()
