"""Benchmark: single-chip serving throughput (output tokens/sec) on the real TPU.

Runs the engine core directly (no HTTP) on Llama-3.2-1B-class weights
(random-init — no network egress) with a continuous-batching workload:
BATCH concurrent requests, ISL/OSL scaled from the reference recipe
(`benchmarks/llm/perf.sh`: ISL 3000 / OSL 150, concurrency swept to 256).
Defaults (batch 256, 32-step fused decode bursts) sit at this chip's
HBM-roofline sweet spot: decode is weight+KV-bandwidth-bound, so batch
amortizes the weight reads and burst length amortizes the host round-trip
(dominant on a tunneled chip).

Prints exactly one JSON line:
  {"metric": "output_tokens_per_sec_per_chip", "value": N, "unit": "tok/s", "vs_baseline": R}

``vs_baseline`` is measured/target where the target is the north-star
proxy scaled to this config: vLLM-H100 class single-chip decode throughput
on a 1B model. The reference publishes no absolute numbers
(BASELINE.json.published == {}), so the target constant below is the
commonly-cited ~8000 tok/s aggregate decode throughput for 1B-class models
on one accelerator at moderate batch — a deliberately hard bar.
"""

import json
import os
import time

import numpy as np

# Run on the real chip: do NOT force a platform here.
PRESET = os.environ.get("BENCH_PRESET", "llama-3.2-1b")
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
ISL = int(os.environ.get("BENCH_ISL", "512"))
OSL = int(os.environ.get("BENCH_OSL", "256"))
TARGET_TOKS = float(os.environ.get("BENCH_TARGET", "8000"))
DECODE_STEPS = int(os.environ.get("BENCH_DECODE_STEPS", "32"))


def main() -> None:
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    cfg = PRESETS[PRESET]
    # Page 128 is the TPU-idiomatic serving page (JetStream-class stacks use
    # 128-512): each page is one ~128 KB DMA slab, which the paged-attention
    # kernel needs to stay HBM-bound rather than descriptor-issue-bound
    # (measured: 8.6k tok/s at page 16 -> 11.6k at page 128 on v5e).
    page_size = int(os.environ.get("BENCH_PAGE_SIZE", "128"))
    pages_per_seq = (ISL + OSL) // page_size + 2
    num_pages = BATCH * pages_per_seq + 8

    params = llama.init_params(cfg, 0)
    if os.environ.get("BENCH_QUANT"):
        from dynamo_tpu.models.quant import quantize_params

        params = quantize_params(params, mode=os.environ["BENCH_QUANT"])
    runner_kw = {}
    if os.environ.get("BENCH_KV_DTYPE"):
        import jax.numpy as jnp

        runner_kw["cache_dtype"] = jnp.dtype(os.environ["BENCH_KV_DTYPE"])
    runner = ModelRunner(
        cfg, params, num_pages=num_pages, page_size=page_size,
        max_batch_size=BATCH, prefill_bucket=max(ISL, 64), **runner_kw,
    )
    core = EngineCore(
        runner,
        EngineConfig(
            num_pages=num_pages, page_size=page_size, max_batch_size=BATCH,
            # Prefill-batch budget per step: on a tunneled chip each step
            # pays a fixed ~100 ms dispatch round-trip, so TTFT at moderate
            # concurrency is minimized by packing many prompts per step.
            # ISL*32 packs the whole TTFT cohort into one step: p50 489 ms
            # vs 741 ms at ISL*4 (measured on v5e, concurrency 32, ISL 512).
            max_prefill_tokens=int(os.environ.get("BENCH_MAX_PREFILL", ISL * 32)),
            max_seq_len=ISL + OSL + 8,
            enable_prefix_caching=False,  # uniform-random prompts: measure raw decode
            decode_steps=DECODE_STEPS,
        ),
    )

    rng = np.random.default_rng(0)
    for i in range(BATCH):
        prompt = rng.integers(1, cfg.vocab_size - 1, size=ISL).tolist()
        core.add_request(
            PreprocessedRequest(
                token_ids=prompt,
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=OSL, ignore_eos=True),
            )
        )

    # Warmup: prefills + enough decode dispatches to compile the burst
    # programs (the pipelined path returns the first burst one step late).
    warmup_tokens = 0
    while core.waiting:
        warmup_tokens += len(core.step())
    for _ in range(2):
        warmup_tokens += len(core.step())

    start = time.perf_counter()
    generated = 0
    while core.has_work:
        outputs = core.step()
        generated += sum(len(o.token_ids) for _, o in outputs)
    elapsed = time.perf_counter() - start
    tok_per_sec = generated / elapsed if elapsed > 0 else 0.0

    # -- TTFT phase: fresh requests at moderate concurrency, pure prefill --
    # The north star is tok/s *under a TTFT SLO* (BASELINE.md): measure the
    # time from submit to each request's first sampled token, prefill running
    # the Pallas flash path. Programs are already compiled by the phase above
    # (same shapes), so this times the chip, not XLA.
    ttft_batch = int(os.environ.get("BENCH_TTFT_CONCURRENCY", "32"))
    prompts = [
        rng.integers(1, cfg.vocab_size - 1, size=ISL).tolist() for _ in range(ttft_batch)
    ]
    submitted: dict[int, float] = {}
    for prompt in prompts:
        seq = core.add_request(
            PreprocessedRequest(
                token_ids=prompt,
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=1, ignore_eos=True),
            )
        )
        submitted[id(seq)] = time.perf_counter()
    first_seen: dict[int, float] = {}
    while core.has_work and len(first_seen) < ttft_batch:
        outputs = core.step()
        now = time.perf_counter()
        for seq, out in outputs:
            if id(seq) not in first_seen and out.token_ids:
                first_seen[id(seq)] = now - submitted[id(seq)]
    ttfts = sorted(first_seen.values())

    def pct(p: float) -> float:
        if not ttfts:
            return 0.0
        return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

    print(
        json.dumps(
            {
                "metric": "output_tokens_per_sec_per_chip",
                "value": round(tok_per_sec, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_per_sec / TARGET_TOKS, 4),
                "detail": {
                    "preset": PRESET, "batch": BATCH, "isl": ISL, "osl": OSL,
                    "decode_steps": DECODE_STEPS,
                    "decode_tokens": generated, "seconds": round(elapsed, 3),
                    "ttft_p50_ms": round(pct(0.50) * 1e3, 1),
                    "ttft_p99_ms": round(pct(0.99) * 1e3, 1),
                    "ttft_concurrency": ttft_batch,
                    "backend": __import__("jax").default_backend(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
