"""Alert/anomaly-kind vocabulary check: the incident plane's trigger
vocabularies live in three places each that can drift — the declared
tuples (``ALERT_KINDS`` in ``observability/slo.py``, ``ANOMALY_KINDS`` in
``observability/anomaly.py``, ``INCIDENT_KINDS`` in
``observability/incidents.py``), the literal kind strings the source
actually records (``_update_alert("...")`` / ``self._update("...")`` /
``capture("...")`` call sites), and the kind tables in
``docs/OBSERVABILITY.md`` that operators read.

This gate pins all three to each other, the same contract as
``check_barrier_reasons.py``: a typo'd kind would mint an undocumented
metric label (``dynamo_alert_active{kind=...}``,
``dynamo_anomaly_active{kind=...}``, ``dynamo_incidents_captured_total
{kind=...}``), and a dead tuple entry means a detector was erased but its
vocabulary row lingers.

Run directly (``python tools/check_alert_kinds.py``) or via the test
suite (``tests/test_incidents.py``).
"""

from __future__ import annotations

import pathlib
import re
import sys

#: Literal alert kinds SloAccountant records: _update_alert("...") call sites.
_ALERT_CALL = re.compile(r"_update_alert\(\s*\"([a-z_]+)\"")
#: Literal anomaly kinds the sentinel records: self._update("...") call sites.
_ANOMALY_CALL = re.compile(r"self\._update\(\s*\"([a-z_]+)\"")
#: Literal incident trigger kinds: .capture("...") call sites anywhere in
#: the package (engine core/service, frontend metrics, sentinel wiring).
_CAPTURE_CALL = re.compile(r"\.capture\(\s*\"([a-z_]+)\"")
#: Docs table rows: | `kind` | ... |
_DOC_ROW = re.compile(r"^\|\s*`([a-z_]+)`\s*\|", re.MULTILINE)
_NEXT_HEADING = re.compile(r"^#{2,4}\s", re.MULTILINE)

#: Each vocabulary's docs section heading in docs/OBSERVABILITY.md.
_HEADINGS = {
    "alert": re.compile(r"^#{2,4}\s+Alert kinds\b.*$", re.MULTILINE),
    "anomaly": re.compile(r"^#{2,4}\s+Anomaly kinds\b.*$", re.MULTILINE),
    "incident": re.compile(r"^#{2,4}\s+Incident trigger kinds\b.*$", re.MULTILINE),
}


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def declared_kinds() -> dict[str, tuple[str, ...]]:
    from dynamo_tpu.observability.anomaly import ANOMALY_KINDS
    from dynamo_tpu.observability.incidents import INCIDENT_KINDS
    from dynamo_tpu.observability.slo import ALERT_KINDS

    return {
        "alert": tuple(ALERT_KINDS),
        "anomaly": tuple(ANOMALY_KINDS),
        "incident": tuple(INCIDENT_KINDS),
    }


def recorded_kinds(root: pathlib.Path | None = None) -> dict[str, set[str]]:
    root = root or _repo_root()
    pkg = root / "dynamo_tpu"
    slo_src = (pkg / "observability" / "slo.py").read_text()
    anomaly_src = (pkg / "observability" / "anomaly.py").read_text()
    capture_kinds: set[str] = set()
    for path in sorted(pkg.rglob("*.py")):
        capture_kinds |= set(_CAPTURE_CALL.findall(path.read_text()))
    return {
        "alert": set(_ALERT_CALL.findall(slo_src)),
        "anomaly": set(_ANOMALY_CALL.findall(anomaly_src)),
        "incident": capture_kinds,
    }


def documented_kinds(root: pathlib.Path | None = None) -> dict[str, list[str]]:
    doc = ((root or _repo_root()) / "docs" / "OBSERVABILITY.md").read_text()
    out: dict[str, list[str]] = {}
    for vocab, heading in _HEADINGS.items():
        head = heading.search(doc)
        if head is None:
            out[vocab] = []
            continue
        seg = doc[head.end():]
        nxt = _NEXT_HEADING.search(seg)
        if nxt is not None:
            seg = seg[: nxt.start()]
        out[vocab] = _DOC_ROW.findall(seg)
    return out


def check(
    declared: dict[str, tuple[str, ...]],
    recorded: dict[str, set[str]],
    documented: dict[str, list[str]],
) -> list[str]:
    problems: list[str] = []
    for vocab, decl_tuple in declared.items():
        decl = set(decl_tuple)
        if len(decl) != len(decl_tuple):
            problems.append(f"{vocab} kinds tuple has duplicate entries: {decl_tuple}")
        rec = recorded.get(vocab, set())
        for k in sorted(rec - decl):
            problems.append(
                f"source records {vocab} kind {k!r} missing from the declared tuple"
            )
        for k in sorted(decl - rec):
            problems.append(
                f"declared {vocab} kind {k!r} is never recorded by any call "
                "site (erased detector with a lingering row?)"
            )
        doc_rows = documented.get(vocab, [])
        docset = set(doc_rows)
        if len(docset) != len(doc_rows):
            dupes = sorted({k for k in doc_rows if doc_rows.count(k) > 1})
            problems.append(
                f"OBSERVABILITY.md {vocab}-kind table has duplicate rows: {dupes}"
            )
        if not doc_rows:
            problems.append(
                f"OBSERVABILITY.md has no {vocab}-kind table (missing the "
                f"section heading {_HEADINGS[vocab].pattern!r}?)"
            )
        for k in sorted(docset - decl):
            problems.append(
                f"OBSERVABILITY.md documents {vocab} kind {k!r} that the "
                "declared tuple does not contain (renamed or removed?)"
            )
        for k in sorted(decl - docset):
            problems.append(
                f"declared {vocab} kind {k!r} is missing from the "
                f"OBSERVABILITY.md {vocab}-kind table"
            )
    return problems


def main() -> int:
    declared = declared_kinds()
    problems = check(declared, recorded_kinds(), documented_kinds())
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    counts = ", ".join(f"{len(v)} {k}" for k, v in declared.items())
    print(
        f"ok: {counts} kinds — the declared tuples, the recording call "
        "sites, and the OBSERVABILITY.md tables all agree"
    )
    return 0


if __name__ == "__main__":
    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(_repo_root()))
    sys.exit(main())
