"""Bench-trajectory regression gate: compare the newest ``BENCH_r*.json``
round against the best prior round, per stable headline key, direction-aware.

Each round file is the driver's wrapper ``{n, cmd, rc, tail, parsed}``.
``parsed`` holds the bench's final JSON document when the run's last stdout
line parsed cleanly; otherwise the tail may still end with a recoverable
JSON line (the bench prints its document last). Rounds where neither yields
a dict are *unusable* and skipped — a truncated tail is not a measurement.

For every numeric headline key present in both the newest usable round and
at least one prior usable round, the newest value must not regress past the
best prior value by more than the tolerance: for higher-is-better keys
(throughput, gains, coverage fractions) ``new >= best * (1 - tol)``; for
lower-is-better keys (latencies, idle/barrier fractions)
``new <= best * (1 + tol)``. Keys with no known direction are reported as
informational only — an unknown key must not silently gate.

Knobs:

- ``DYN_BENCH_REGRESS_TOLERANCE`` — allowed fractional slack (default 0.25;
  bench rounds run on shared hardware and are noisy).
- ``DYN_BENCH_REGRESS_WAIVE`` — comma-separated key names to exempt, or
  ``all`` to disable the gate (prints findings, always exits 0). Use when a
  known trade-off intentionally moves a headline key.

Run directly (``python tools/bench_regress.py``) or via the test suite
(``tests/test_observability.py``). Exits 1 on any unwaived regression.
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import sys

_ROUND = re.compile(r"BENCH_r(\d+)\.json$")

#: Direction of goodness by key suffix. First match wins; unknown keys are
#: informational. Order matters: "idle_frac"/"barrier_frac" must outrank
#: the generic "frac" rule.
_LOWER_BETTER = (
    "idle_frac", "barrier_frac", "unattributed", "_ms", "_s", "seconds",
    "stall",
)
_HIGHER_BETTER = (
    "value", "vs_baseline", "per_sec", "per_chip", "gain", "frac",
    "goodput", "gbytes",
)


def direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown."""
    for suffix in _LOWER_BETTER:
        if key.endswith(suffix):
            return -1
    for suffix in _HIGHER_BETTER:
        if key.endswith(suffix):
            return 1
    return 0


def _recover_doc(wrapper: dict) -> dict | None:
    """The round's bench document: ``parsed``, else the last line of the
    tail that parses to a dict (the bench prints its document last)."""
    parsed = wrapper.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    for line in reversed((wrapper.get("tail") or "").splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def load_rounds(root: pathlib.Path | None = None) -> list[tuple[int, dict]]:
    """Usable (round_number, doc) pairs, ascending. Unusable rounds skip."""
    root = root or _repo_root()
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _ROUND.search(path.name)
        if m is None:
            continue
        try:
            wrapper = json.loads(path.read_text())
        except ValueError:
            continue
        doc = _recover_doc(wrapper) if isinstance(wrapper, dict) else None
        if doc is not None:
            out.append((int(m.group(1)), doc))
    out.sort()
    return out


def numeric_keys(doc: dict) -> dict[str, float]:
    return {
        k: float(v) for k, v in doc.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


def compare(rounds: list[tuple[int, dict]], *, tolerance: float) -> tuple[list[str], list[str]]:
    """(regressions, notes) comparing the newest round to the best prior."""
    if len(rounds) < 2:
        return [], [f"only {len(rounds)} usable round(s); nothing to compare"]
    newest_n, newest = rounds[-1]
    new_vals = numeric_keys(newest)
    regressions: list[str] = []
    notes: list[str] = []
    for key, new in sorted(new_vals.items()):
        prior = [
            (n, numeric_keys(doc)[key]) for n, doc in rounds[:-1]
            if key in numeric_keys(doc)
        ]
        if not prior:
            notes.append(f"{key}: new in r{newest_n:02d} (no trajectory yet)")
            continue
        sign = direction(key)
        if sign == 0:
            notes.append(f"{key}: no known direction; informational only")
            continue
        if sign > 0:
            best_n, best = max(prior, key=lambda p: p[1])
            floor = best * (1.0 - tolerance)
            if new < floor:
                regressions.append(
                    f"{key}: r{newest_n:02d}={new:g} fell below r{best_n:02d}="
                    f"{best:g} by more than {tolerance:.0%} (floor {floor:g})"
                )
        else:
            best_n, best = min(prior, key=lambda p: p[1])
            ceil = best * (1.0 + tolerance)
            if new > ceil:
                regressions.append(
                    f"{key}: r{newest_n:02d}={new:g} rose above r{best_n:02d}="
                    f"{best:g} by more than {tolerance:.0%} (ceiling {ceil:g})"
                )
    return regressions, notes


def check(root: pathlib.Path | None = None) -> list[str]:
    """Unwaived regressions against the committed bench history."""
    tolerance = float(os.environ.get("DYN_BENCH_REGRESS_TOLERANCE", "0.25"))
    waive = {
        w.strip() for w in os.environ.get("DYN_BENCH_REGRESS_WAIVE", "").split(",")
        if w.strip()
    }
    regressions, _ = compare(load_rounds(root), tolerance=tolerance)
    if "all" in waive:
        return []
    return [r for r in regressions if r.split(":", 1)[0] not in waive]


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    tolerance = float(os.environ.get("DYN_BENCH_REGRESS_TOLERANCE", "0.25"))
    rounds = load_rounds()
    regressions, notes = compare(rounds, tolerance=tolerance)
    waive = {
        w.strip() for w in os.environ.get("DYN_BENCH_REGRESS_WAIVE", "").split(",")
        if w.strip()
    }
    gating = [] if "all" in waive else [
        r for r in regressions if r.split(":", 1)[0] not in waive
    ]
    for n in notes:
        print(f"note: {n}")
    for r in regressions:
        tag = "WAIVED" if r not in gating else "FAIL"
        print(f"{tag}: {r}", file=sys.stderr if tag == "FAIL" else sys.stdout)
    if gating:
        return 1
    usable = ", ".join(f"r{n:02d}" for n, _ in rounds)
    print(
        f"ok: newest bench round holds the trajectory "
        f"(usable rounds: {usable or 'none'}; tolerance {tolerance:.0%})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
