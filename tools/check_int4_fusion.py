"""HLO fusion audit for the packed-int4 weight path (ROADMAP item 3).

The int4 bandwidth win exists only if XLA fuses the dequant expression
(unpack nibbles -> scale -> optional bias) into the consuming dot's operand
read. If the compiler instead *materializes* the full-width bf16 weight, the
weight round-trips HBM at 2 byte/elem and the packed format saved nothing —
the residual-dequant failure mode ROADMAP item 3 says to chase.

This tool compiles ``quant_matmul`` on a packed-int4 leaf at a decode-like
shape and checks the optimized artifact two ways:

1. **Memory analysis** (authoritative where the backend reports it): the
   compiled executable's temp allocation must be smaller than the
   full-width bf16 weight — a materialized dequant *must* live in a temp
   buffer at least that large.
2. **Optimized-HLO scan**: no instruction in the *entry* computation may
   produce the full-width weight shape in a wide dtype. Full-width shapes
   inside fusion bodies are fine — fusion-internal values live in
   registers/tiles, never in HBM.

Run directly (``python tools/check_int4_fusion.py``; exits non-zero on a
materialized dequant) or via the test suite (``tests/test_quant.py``). The
gate is **strict on TPU** — the fusion contract is an HBM-bandwidth claim
about the TPU pipeline. The CPU backend's dot kernels require materialized
operands (no operand fusion into dots exists there at all), so on CPU the
audit runs the identical checks but reports advisorily (exit 0), keeping
the tool tier-1-viable while still exercising every line of the gate;
``DYN_INT4_FUSION_STRICT=1`` forces the strict verdict anywhere.
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def audit_int4_fusion(
    batch: int = 8, d_in: int = 1024, d_out: int = 1024, group_size: int = 128
) -> dict:
    """Compile the int4 matmul and report fusion evidence.

    Returns a dict with ``ok`` (no materialized full-width weight),
    per-check verdicts, and the numbers behind them.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models.quant import quant_matmul, quantize_leaf_int4

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((d_in, d_out)), jnp.bfloat16)
    leaf = quantize_leaf_int4(w, group_size=group_size)
    leaf = {k: jax.device_put(v) for k, v in leaf.items()}
    x = jnp.asarray(rng.standard_normal((batch, d_in)), jnp.bfloat16)

    compiled = jax.jit(quant_matmul).lower(x, leaf).compile()
    full_weight_bytes = d_in * d_out * 2  # the bf16 tensor fusion must avoid

    report: dict = {
        "backend": jax.default_backend(),
        "shape": {"batch": batch, "d_in": d_in, "d_out": d_out, "group_size": group_size},
        "full_weight_bytes": full_weight_bytes,
    }

    # Check 1: temp allocation bound. A materialized dequant needs a temp at
    # least the size of the full-width weight.
    temp_bytes = None
    try:
        mem = compiled.memory_analysis()
        temp_bytes = int(getattr(mem, "temp_size_in_bytes"))
    except Exception:
        pass  # backend doesn't report memory analysis; HLO scan decides
    report["temp_bytes"] = temp_bytes
    report["temp_ok"] = temp_bytes is None or temp_bytes < full_weight_bytes

    # Check 2: entry-computation scan of the optimized HLO. Instructions
    # inside fusion computations are exempt (fusion-internal values never
    # round-trip HBM); any entry-scope instruction producing the full-width
    # weight shape in a >=2-byte dtype is a materialized dequant.
    hlo = compiled.as_text()
    wide = re.compile(
        rf"%?\w[\w.\-]*\s*=\s*(bf16|f16|f32)\[{d_in},{d_out}\]"
    )
    offenders: list[str] = []
    in_entry = False
    depth = 0
    for line in hlo.splitlines():
        stripped = line.strip()
        if stripped.startswith("ENTRY "):
            in_entry = True
            depth = 0
        if in_entry:
            m = wide.search(stripped)
            # Parameters echo their declared shapes; only computed values
            # (non-parameter instructions) can be materializations.
            if m and " parameter(" not in stripped:
                offenders.append(stripped[:160])
            depth += stripped.count("{") - stripped.count("}")
            if depth <= 0 and "}" in stripped:
                in_entry = False
    report["entry_offenders"] = offenders
    report["hlo_ok"] = not offenders
    report["ok"] = bool(report["temp_ok"] and report["hlo_ok"])
    # The fusion contract is a TPU-pipeline claim; CPU dot kernels always
    # take materialized operands, so only TPU (or a forced override) gates.
    report["strict"] = (
        report["backend"] == "tpu"
        or os.environ.get("DYN_INT4_FUSION_STRICT", "") == "1"
    )
    return report


def main() -> int:
    report = audit_int4_fusion()
    print(json.dumps(report, indent=2))
    if not report["ok"]:
        msg = (
            "optimized HLO materializes the full-width int4 weight "
            "(dequant not fused into the dot's operand read)"
        )
        if report["strict"]:
            print(f"FAIL: {msg}", file=sys.stderr)
            return 1
        print(
            f"advisory ({report['backend']} backend, expected there): {msg}",
            file=sys.stderr,
        )
        return 0
    print(
        "ok: int4 dequant fuses into the matmul operand read "
        f"(backend={report['backend']}, temp_bytes={report['temp_bytes']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
