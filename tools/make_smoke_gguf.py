"""Build tests/fixtures/smoke-q4k.gguf — a tiny REAL checkpoint fixture.

"Real" in every dimension the serving stack exercises (VERDICT r3 item 10 /
weak #5): a genuine BPE tokenizer trained on the corpus below and embedded
GGUF-style (gpt2 kind: vocab + merges), weights TRAINED (torch CPU, a few
hundred steps) until the model reliably memorizes the corpus continuations,
stored in llama.cpp's Q4_K superblock format via this repo's encoder. The
serving smoke test (tests/test_real_checkpoint_smoke.py) prompts with a
corpus prefix and asserts the CONTENT of the continuation — not logits —
through the full HTTP stack, which a random-weight fixture cannot do.

Run from the repo root:  python tools/make_smoke_gguf.py
Deterministic (seeded); ~1 minute on CPU. ~1 MB output, committed.
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump. "
    "sphinx of black quartz judge my vow. "
    "the five boxing wizards jump quickly. "
) * 4

PROMPT = "the quick brown fox"
EXPECTED_CONTINUATION = " jumps over the lazy dog"


def train_tokenizer():
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tk = Tokenizer(models.BPE(unk_token=None, fuse_unk=False))
    tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=True)
    tk.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=512, special_tokens=["<s>", "</s>"], show_progress=False,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tk.train_from_iterator([CORPUS], trainer)
    return tk


def train_model(tk):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    vocab = tk.get_vocab_size()
    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, tie_word_embeddings=False, rope_theta=10000.0,
        bos_token_id=0, eos_token_id=1, max_position_embeddings=512,
    )
    model = LlamaForCausalLM(cfg).train()
    ids = torch.tensor([[0] + tk.encode(CORPUS).ids])
    opt = torch.optim.AdamW(model.parameters(), lr=3e-3)
    for step in range(400):
        out = model(input_ids=ids, labels=ids)
        out.loss.backward()
        opt.step()
        opt.zero_grad()
        if step % 100 == 0:
            print(f"step {step}: loss {out.loss.item():.4f}", flush=True)
    model.eval()
    # Verify memorization greedily before exporting.
    p = torch.tensor([[0] + tk.encode(PROMPT).ids])
    gen = model.generate(p, max_new_tokens=8, do_sample=False)
    text = tk.decode(gen[0][p.shape[1]:].tolist())
    print("greedy continuation:", repr(text), flush=True)
    assert text.startswith(EXPECTED_CONTINUATION), text
    return model


def export(model, tk, out_path):
    import tempfile

    import numpy as np

    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.gguf import GGML_Q4_K, save_params_gguf
    from dynamo_tpu.models.loader import load_model

    tmp = tempfile.mkdtemp()
    model.save_pretrained(tmp, safe_serialization=True)
    cfg, params = load_model(tmp, dtype="float32", name="smoke")
    # Embedded gpt2-kind tokenizer: vocab in id order + merges.
    vocab = sorted(tk.get_vocab().items(), key=lambda kv: kv[1])
    tokens = [t for t, _ in vocab]
    merges = [" ".join(pair) for pair in _merges_of(tk)]
    token_type = [3 if t in ("<s>", "</s>") else 1 for t in tokens]
    tok_md = {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.merges": merges,
        "tokenizer.ggml.token_type": token_type,
        "tokenizer.ggml.bos_token_id": 0,
        "tokenizer.ggml.eos_token_id": 1,
    }
    # Q4_K for every 256-divisible matmul weight; f16/f32 fallback elsewhere
    # happens inside the writer.
    save_params_gguf(out_path, cfg, params, quant=GGML_Q4_K, tokenizer_metadata=tok_md)
    print("wrote", out_path, os.path.getsize(out_path), "bytes", flush=True)


def _merges_of(tk):
    import json

    data = json.loads(tk.to_str())
    merges = data["model"]["merges"]
    return [tuple(m) if isinstance(m, list) else tuple(m.split(" ", 1)) for m in merges]


def main():
    out = pathlib.Path(__file__).resolve().parent.parent / "tests" / "fixtures" / "smoke-q4k.gguf"
    out.parent.mkdir(parents=True, exist_ok=True)
    tk = train_tokenizer()
    model = train_model(tk)
    export(model, tk, out)

    # Round-trip sanity through this repo's own stack.
    from dynamo_tpu.models.gguf import GGUFReader, tokenizer_from_gguf

    r = GGUFReader(out)
    t2 = tokenizer_from_gguf(r)
    enc = t2.encode(PROMPT)
    assert t2.decode(enc) == PROMPT, t2.decode(enc)
    q4k = [n for n, info in r.tensors.items() if info.ggml_type == 12]
    print(f"Q4_K tensors: {len(q4k)} (e.g. {q4k[:3]})", flush=True)
    assert q4k, "no Q4_K tensors written"
    r.close()


if __name__ == "__main__":
    main()
