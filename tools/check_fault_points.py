"""Fault-point coverage check: every injection point registered in
``dynamo_tpu.runtime.faults.FAULT_POINTS`` must be armed at least once in
``tests/test_chaos.py``.

The fault plane is only as trustworthy as its exercise: a point that is
threaded through production code but never armed in the chaos suite is dead
instrumentation — its failure-handling path has never run, which is exactly
the bug class the plane exists to kill. This tool greps the chaos suite's
source for each registered point name (the names are unusual enough —
``kv.chunk.recv``, ``lease.keepalive`` — that a plain substring match is
reliable) and fails listing any absentees. Run directly
(``python tools/check_fault_points.py``) or via the test suite
(``tests/test_chaos.py::test_fault_point_coverage``).
"""

from __future__ import annotations

import pathlib
import sys

CHAOS_SUITE = pathlib.Path(__file__).resolve().parent.parent / "tests" / "test_chaos.py"


def registered_points() -> list[str]:
    from dynamo_tpu.runtime.faults import FAULT_POINTS

    return sorted(FAULT_POINTS)


def uncovered_points(source: str | None = None) -> list[str]:
    """Registered fault points that never appear in the chaos suite."""
    if source is None:
        source = CHAOS_SUITE.read_text()
    return [point for point in registered_points() if point not in source]


def main() -> int:
    if not CHAOS_SUITE.exists():
        print(f"FAIL: chaos suite missing at {CHAOS_SUITE}", file=sys.stderr)
        return 1
    missing = uncovered_points()
    if missing:
        for point in missing:
            print(f"FAIL: fault point {point!r} is never armed in {CHAOS_SUITE.name}", file=sys.stderr)
        return 1
    n = len(registered_points())
    print(f"ok: all {n} registered fault points are exercised by {CHAOS_SUITE.name}")
    return 0


if __name__ == "__main__":
    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
