"""Barrier-reason vocabulary check: the overlap pipeline's barrier reasons
live in three places that have drifted before — the ``BARRIER_REASONS``
tuple in ``engine/core.py`` (the source of truth the metrics plane labels
with), the literal reason strings the engine actually records
(``_note_barrier(...)`` call sites and ``_overlap_route``'s returns), and
the reason table in ``docs/SCHEDULER.md`` that operators read.

This gate pins all three to each other:

- every literal reason the source records must be in ``BARRIER_REASONS``
  (a typo'd reason would mint an undocumented metric label), and every
  tuple entry must be recordable from some call site (a dead entry means a
  barrier was erased but its vocabulary row lingers);
- the SCHEDULER.md barrier table must list exactly ``BARRIER_REASONS``.

It also pins the **loss-cause** vocabulary layered on top (ISSUE 15): the
``LOSS_CAUSES`` label set of ``dynamo_engine_lost_time_seconds_total`` must
be exactly ``BARRIER_REASONS`` plus the literal ``EXTRA_LOSS_CAUSES`` tuple
in ``observability/attribution.py``, and the loss-cause table in
``docs/OBSERVABILITY.md`` must list exactly that set — a new barrier reason
is a new loss cause by construction, and it must land in the operator docs.

Run directly (``python tools/check_barrier_reasons.py``) or via the test
suite (``tests/test_observability.py``).
"""

from __future__ import annotations

import pathlib
import re
import sys

#: Literal reason strings the engine can record: explicit _note_barrier
#: calls, and the (False, "reason") routing returns that _step_locked
#: forwards into _note_barrier.
_NOTE_CALL = re.compile(r"_note_barrier\(\s*\"([a-z_]+)\"\s*\)")
_ROUTE_RETURN = re.compile(r"return\s+False,\s*\"([a-z_]+)\"")
#: SCHEDULER.md barrier-table rows: | `reason` | description |
_DOC_ROW = re.compile(r"^\|\s*`([a-z_]+)`\s*\|", re.MULTILINE)
#: The default reason when a barrier step recorded nothing (core.step()'s
#: ``or "idle"`` fallback — not a literal _note_barrier site).
_IMPLICIT = {"idle"}


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def declared_reasons() -> tuple[str, ...]:
    from dynamo_tpu.engine.core import BARRIER_REASONS

    return tuple(BARRIER_REASONS)


def recorded_reasons(root: pathlib.Path | None = None) -> set[str]:
    src = ((root or _repo_root()) / "dynamo_tpu" / "engine" / "core.py").read_text()
    return set(_NOTE_CALL.findall(src)) | set(_ROUTE_RETURN.findall(src)) | _IMPLICIT


def documented_reasons(root: pathlib.Path | None = None) -> list[str]:
    doc = ((root or _repo_root()) / "docs" / "SCHEDULER.md").read_text()
    return _DOC_ROW.findall(doc)


def check(declared: tuple[str, ...], recorded: set[str],
          documented: list[str]) -> list[str]:
    problems: list[str] = []
    decl = set(declared)
    if len(decl) != len(declared):
        problems.append(f"BARRIER_REASONS has duplicate entries: {declared}")
    for r in sorted(recorded - decl):
        problems.append(
            f"core.py records barrier reason {r!r} missing from BARRIER_REASONS"
        )
    for r in sorted(decl - recorded):
        problems.append(
            f"BARRIER_REASONS entry {r!r} is never recorded by any "
            "_note_barrier call site (erased barrier with a lingering row?)"
        )
    docset = set(documented)
    if len(docset) != len(documented):
        dupes = sorted({r for r in documented if documented.count(r) > 1})
        problems.append(f"SCHEDULER.md barrier table has duplicate rows: {dupes}")
    for r in sorted(docset - decl):
        problems.append(
            f"SCHEDULER.md documents barrier reason {r!r} that BARRIER_REASONS "
            "does not declare (renamed or removed?)"
        )
    for r in sorted(decl - docset):
        problems.append(
            f"BARRIER_REASONS entry {r!r} is missing from the SCHEDULER.md "
            "barrier table"
        )
    return problems


#: The literal extras tuple in attribution.py (parsed from source so a
#: runtime mutation can't satisfy the gate).
_EXTRA_TUPLE = re.compile(r"EXTRA_LOSS_CAUSES\s*=\s*\(([^)]*)\)")
_TUPLE_ITEM = re.compile(r"\"([a-z_]+)\"")
#: The OBSERVABILITY.md loss-cause section: rows under the "Loss causes"
#: heading, up to the next heading.
_LOSS_HEADING = re.compile(r"^#{2,4}\s+Loss causes\b.*$", re.MULTILINE)
_NEXT_HEADING = re.compile(r"^#{2,4}\s", re.MULTILINE)


def declared_loss_causes() -> tuple[str, ...]:
    from dynamo_tpu.observability.attribution import LOSS_CAUSES

    return tuple(LOSS_CAUSES)


def source_extra_causes(root: pathlib.Path | None = None) -> tuple[str, ...]:
    src = (
        (root or _repo_root()) / "dynamo_tpu" / "observability" / "attribution.py"
    ).read_text()
    m = _EXTRA_TUPLE.search(src)
    return tuple(_TUPLE_ITEM.findall(m.group(1))) if m else ()


def documented_loss_causes(root: pathlib.Path | None = None) -> list[str]:
    doc = ((root or _repo_root()) / "docs" / "OBSERVABILITY.md").read_text()
    head = _LOSS_HEADING.search(doc)
    if head is None:
        return []
    seg = doc[head.end():]
    nxt = _NEXT_HEADING.search(seg)
    if nxt is not None:
        seg = seg[: nxt.start()]
    return _DOC_ROW.findall(seg)


def check_loss_causes(
    declared_barriers: tuple[str, ...],
    loss_causes: tuple[str, ...],
    extras: tuple[str, ...],
    documented: list[str],
) -> list[str]:
    problems: list[str] = []
    if not extras:
        problems.append(
            "could not parse the EXTRA_LOSS_CAUSES literal tuple out of "
            "observability/attribution.py"
        )
    expected = tuple(declared_barriers) + tuple(extras)
    if tuple(loss_causes) != expected:
        problems.append(
            f"LOSS_CAUSES is {loss_causes} but must be BARRIER_REASONS + "
            f"EXTRA_LOSS_CAUSES = {expected}"
        )
    docset = set(documented)
    if len(docset) != len(documented):
        dupes = sorted({r for r in documented if documented.count(r) > 1})
        problems.append(f"OBSERVABILITY.md loss-cause table has duplicate rows: {dupes}")
    losset = set(loss_causes)
    for r in sorted(docset - losset):
        problems.append(
            f"OBSERVABILITY.md documents loss cause {r!r} that LOSS_CAUSES "
            "does not declare (renamed or removed?)"
        )
    for r in sorted(losset - docset):
        problems.append(
            f"loss cause {r!r} is missing from the OBSERVABILITY.md "
            "loss-cause table"
        )
    return problems


def main() -> int:
    declared = declared_reasons()
    recorded = recorded_reasons()
    documented = documented_reasons()
    problems = check(declared, recorded, documented)
    problems += check_loss_causes(
        declared, declared_loss_causes(), source_extra_causes(),
        documented_loss_causes(),
    )
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(declared)} barrier reasons — BARRIER_REASONS, the "
        "_note_barrier call sites, and the SCHEDULER.md table all agree; "
        f"{len(declared_loss_causes())} loss causes pinned to the barrier "
        "vocabulary and the OBSERVABILITY.md table"
    )
    return 0


if __name__ == "__main__":
    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(_repo_root()))
    sys.exit(main())
