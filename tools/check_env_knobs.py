"""Env-knob hygiene check: every ``DYN_*`` environment variable the project
reads must appear in a docs env table, and every ``DYN_*`` name the docs
mention must actually exist — either as a literal the source reads or as a
config-cascade name auto-generated from a ``config.py`` settings dataclass
(``DYN_{SECTION}_{FIELD}``).

Knobs rot in both directions: a knob added in code but never documented is
undiscoverable (operators grep the docs, not the source), and a knob renamed
in code but not in the docs silently stops working for everyone following
the docs. This gate makes the docs env tables the enforced registry of both
sets. Dynamic prefix families (``DYN_SVC_<SERVICE>_<FIELD>`` from the SDK's
service-config cascade) are validated by prefix — the source reads the
prefix, the docs may enumerate concrete instances.

Run directly (``python tools/check_env_knobs.py``) or via the test suite
(``tests/test_observability.py``).
"""

from __future__ import annotations

import dataclasses
import pathlib
import re
import sys

_KNOB = re.compile(r"DYN_[A-Z0-9_]+")
_QUOTED_KNOB = re.compile(r"[\"'](DYN_[A-Z0-9_]*)[\"']")

#: Source files scanned for knob literals: the package, the top-level bench
#: harness (its BENCH_* knobs are out of scope; its DYN_* reads are not),
#: and the operator tools (bench_regress.py reads DYN_BENCH_REGRESS_*).
_SOURCE_GLOBS = [("dynamo_tpu", "**/*.py"), (".", "bench.py"), ("tools", "*.py")]
#: Docs scanned for the documented set — every env table the project keeps.
_DOC_GLOBS = [("docs", "*.md"), (".", "README.md")]


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def source_knobs(root: pathlib.Path | None = None) -> tuple[set[str], set[str]]:
    """(exact knob names, dynamic prefixes) read as string literals.

    A quoted literal ending in ``_`` (e.g. ``"DYN_SVC_"``) is a prefix the
    code composes names under, not a knob itself.
    """
    root = root or _repo_root()
    exact: set[str] = set()
    prefixes: set[str] = set()
    for base, glob in _SOURCE_GLOBS:
        for path in sorted((root / base).glob(glob)):
            for name in _QUOTED_KNOB.findall(path.read_text()):
                (prefixes if name.endswith("_") else exact).add(name)
    return exact, prefixes


def generated_knobs() -> set[str]:
    """``DYN_{SECTION}_{FIELD}`` names the config cascade accepts, derived
    from every ``*Settings`` dataclass in ``dynamo_tpu.config`` (section =
    snake_case of the class name minus the suffix — the same derivation the
    ``load_*_settings`` helpers hardcode)."""
    from dynamo_tpu import config

    knobs: set[str] = set()
    for attr in vars(config).values():
        if not (isinstance(attr, type) and dataclasses.is_dataclass(attr)
                and attr.__name__.endswith("Settings")):
            continue
        stem = attr.__name__[: -len("Settings")]
        section = re.sub(r"(?<!^)(?=[A-Z])", "_", stem).upper()
        for f in dataclasses.fields(attr):
            knobs.add(f"DYN_{section}_{f.name.upper()}")
    return knobs


def documented_knobs(root: pathlib.Path | None = None) -> set[str]:
    """Every full ``DYN_*`` name the docs mention. Wildcard/prefix mentions
    (``DYN_TENANT_*`` captures as ``DYN_TENANT_``) are dropped — a family
    mention documents nothing enumerable."""
    root = root or _repo_root()
    out: set[str] = set()
    for base, glob in _DOC_GLOBS:
        for path in sorted((root / base).glob(glob)):
            out.update(n for n in _KNOB.findall(path.read_text()) if not n.endswith("_"))
    return out


def check(source: set[str], generated: set[str], prefixes: set[str],
          documented: set[str]) -> list[str]:
    problems: list[str] = []
    known = source | generated
    for name in sorted(known - documented):
        problems.append(f"{name} is read by the source but appears in no docs env table")
    for name in sorted(documented - known):
        if any(name.startswith(p) for p in prefixes):
            continue  # concrete instance of a dynamic family (DYN_SVC_...)
        problems.append(f"{name} is documented but nothing reads it (renamed or removed?)")
    return problems


def main() -> int:
    source, prefixes = source_knobs()
    generated = generated_knobs()
    documented = documented_knobs()
    problems = check(source, generated, prefixes, documented)
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {len(source | generated)} DYN_* knobs "
        f"({len(source)} literal, {len(generated - source)} config-generated, "
        f"{len(prefixes)} dynamic prefixes) all documented; "
        f"{len(documented)} documented names all live"
    )
    return 0


if __name__ == "__main__":
    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(_repo_root()))
    sys.exit(main())
