"""Where does the 1B decode's last 6% go? (VERDICT r4 item 9)

Runs the headline 1B config's steady-state decode under an XPlane trace,
then breaks one burst down: per-op device time from the trace's XLA op
events, host gaps between dispatches, and the modeled-bytes bandwidth
view. Prints a JSON summary; the trace directory is left for TensorBoard.

Usage (on the chip): python tools/profile_1b_decode.py [trace_dir]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


PRESET = os.environ.get("PROFILE_PRESET", "llama-3.2-1b")


def build_core(batch: int, isl: int, osl: int):
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    cfg = PRESETS[PRESET]
    page = int(os.environ.get("PROFILE_PAGE", "128"))
    pages_per_seq = (isl + osl) // page + 2
    num_pages = batch * pages_per_seq + 8
    params = llama.init_params(cfg, 0)
    runner = ModelRunner(cfg, params, num_pages=num_pages, page_size=page,
                         max_batch_size=batch, prefill_bucket=max(isl, 64))
    core = EngineCore(runner, EngineConfig(
        num_pages=num_pages, page_size=page, max_batch_size=batch,
        max_prefill_tokens=isl * 32, max_seq_len=isl + osl + 8,
        enable_prefix_caching=False,
        decode_steps=int(os.environ.get("PROFILE_DECODE_STEPS", "32")),
    ))
    rng = np.random.default_rng(0)
    for _ in range(batch):
        core.add_request(PreprocessedRequest(
            token_ids=rng.integers(1, cfg.vocab_size - 1, size=isl).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        ))
    return core, cfg, params


def op_breakdown(trace_dir: str) -> tuple[list[tuple[str, float]], float, int]:
    """Aggregate device-op microseconds from the trace's trace.json.gz.

    Returns ``(per_op_totals_sorted, total_us, num_device_cores)``. The
    per-op totals and ``total_us`` are SUMMED over every device core pid,
    so busy-fraction math must divide by ``num_device_cores`` — an 8-core
    trace's op time can legitimately be 8x the wall window.
    """
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        return [], 0.0, 0
    with gzip.open(sorted(paths)[-1], "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    # Device rows: pid whose process_name metadata names an accelerator
    # ("/device:TPU:0" on chip — memory notes: device pid 3 on the axon
    # trace). "/host:CPU" rows are the host runtime, not XLA ops, but on a
    # CPU-only trace they're all there is — include them as fallback.
    def pids(pred):
        return {
            e["pid"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and pred(str(e.get("args", {}).get("name", "")))
        }

    device_pids = pids(lambda n: "TPU" in n or "/device:" in n)
    if not device_pids:
        device_pids = pids(lambda n: "CPU" in n)
    totals: dict[str, float] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            name = e.get("name", "?")
            totals[name] = totals.get(name, 0.0) + float(e.get("dur", 0.0))
    ordered = sorted(totals.items(), key=lambda kv: -kv[1])
    return ordered, sum(totals.values()), len(device_pids)


def main() -> None:
    import bench as bench_mod
    from dynamo_tpu import tracing
    from dynamo_tpu.observability import cost as cost_mod

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/trace_1b"
    batch = int(os.environ.get("PROFILE_BATCH", "256"))
    isl = int(os.environ.get("PROFILE_ISL", "512"))
    osl = int(os.environ.get("PROFILE_OSL", "256"))
    page = int(os.environ.get("PROFILE_PAGE", "128"))
    core, cfg, params = build_core(batch, isl, osl)

    # Prefill + warm the burst programs.
    while core.waiting:
        core.step()
    for _ in range(3):
        core.step()

    # Traced steady-state decode window.
    tracing.start_device_trace(trace_dir)
    t0 = time.perf_counter()
    generated = 0
    steps = 0
    while core.has_work and steps < 6:  # ~6 bursts of 32 = 192 tokens/seq
        outs = core.step()
        generated += sum(len(o.token_ids) for _, o in outs)
        steps += 1
    elapsed = time.perf_counter() - t0
    tracing.stop_device_trace()

    tok_per_sec = generated / elapsed
    step_bytes = bench_mod.decode_step_bytes(params, cfg, batch, isl, osl, page)
    roofline = bench_mod.roofline_tok_per_sec(step_bytes, batch)
    # Same estimate helpers the serving-path CostRegistry uses — one tree
    # walk shared between this tool and the live ledger (ISSUE 19 dedupe).
    weight_bytes = cost_mod.weight_stream_bytes(params, cfg)
    # XLA's own per-dispatch byte count for the decode bucket, from the
    # runner's cost registry: the cross-check column against the modeled
    # accounting above (agreement within ~15% is the acceptance bar; a
    # larger gap means the model or the extraction is lying).
    cost_analysis_bytes = 0
    cost_source = "disabled"
    cost_reg = getattr(core.runner, "cost_registry", None)
    if cost_reg is not None:
        cost_reg.drain(timeout=60.0)
        decode_row = cost_reg.ledger().get("decode", {})
        cost_analysis_bytes = int(decode_row.get("bytes_per_step", 0))
        rec = cost_reg.record_for("multi_step") or cost_reg.record_for("step")
        cost_source = rec.source if rec is not None else "none"
    ops, device_us, num_cores = op_breakdown(trace_dir)
    # device_us sums op time over every device core pid; per-core busy time
    # is that total divided by the core count (the old code skipped the
    # divide and reported fractions like 3.06 on multi-core traces).
    busy = device_us / (num_cores * elapsed * 1e6) if num_cores else 0.0
    summary = {
        "tok_per_sec_window": round(tok_per_sec, 1),
        "vs_roofline": round(tok_per_sec / roofline, 4),
        "window_seconds": round(elapsed, 3),
        "decode_tokens": generated,
        "device_op_us_total": round(device_us, 0),
        "device_cores": num_cores,
        "wall_us": round(elapsed * 1e6, 0),
        "device_busy_fraction": round(busy, 4),
        # Weight traffic per generated token, from the measured tree (packed
        # quantized leaves at true size) — HBM-utilization claims in bench
        # notes derive from these instead of hand-computed weight sizes.
        "weight_bytes_per_step": weight_bytes,
        "weight_bytes_per_token": round(weight_bytes / batch, 1),
        "weight_frac_of_step_bytes": round(weight_bytes / step_bytes, 4),
        # XLA cost-analysis bytes per decode dispatch (0 = cost plane off),
        # next to the modeled column so the two instruments cross-check.
        "cost_analysis_bytes": cost_analysis_bytes,
        "cost_analysis_source": cost_source,
        "modeled_step_bytes": step_bytes,
        "cost_vs_modeled": (
            round(cost_analysis_bytes / step_bytes, 4) if step_bytes else 0.0
        ),
        "top_ops_us": [[n, round(us, 0)] for n, us in ops[:15]],
        "trace_dir": trace_dir,
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
