"""Metric-name hygiene check: every Prometheus family the project exports
must be ``dynamo_``-prefixed and globally unique across registries.

The frontend registry (``frontend/metrics.py``) and the per-worker engine
registry (``observability/metrics.py``) federate into one ``/metrics``
document; a name collision between them would produce duplicate families
that Prometheus rejects, and an unprefixed name would escape the project's
namespace. Run directly (``python tools/check_metric_names.py``) or via the
test suite (``tests/test_observability.py``).
"""

from __future__ import annotations

import sys


def collect_names() -> dict[str, list[str]]:
    """Family names per registry. Importing here keeps the tool usable
    before optional deps of unrelated modules are present."""
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.observability.metrics import EngineMetrics

    out: dict[str, list[str]] = {}
    for label, registry in (
        ("frontend", FrontendMetrics().registry),
        ("engine", EngineMetrics(worker="check").registry),
    ):
        names: list[str] = []
        for collector in registry._collector_to_names:  # noqa: SLF001 - no public enumeration API
            for metric in collector.collect():
                names.append(metric.name)
        out[label] = sorted(names)
    return out


def check(names: dict[str, list[str]]) -> list[str]:
    """Returns a list of violations (empty = clean)."""
    problems: list[str] = []
    seen: dict[str, str] = {}
    for label, family_names in names.items():
        for name in family_names:
            if not name.startswith("dynamo_"):
                problems.append(f"{label}: {name!r} is not dynamo_-prefixed")
            prev = seen.get(name)
            if prev is not None and prev != label:
                problems.append(f"{name!r} exported by both {prev} and {label} registries")
            seen.setdefault(name, label)
        if len(set(family_names)) != len(family_names):
            dupes = sorted({n for n in family_names if family_names.count(n) > 1})
            problems.append(f"{label}: duplicate families {dupes}")
    return problems


def main() -> int:
    names = collect_names()
    problems = check(names)
    total = sum(len(v) for v in names.values())
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print(f"ok: {total} metric families across {len(names)} registries, all dynamo_-prefixed and unique")
    return 0


if __name__ == "__main__":
    import pathlib

    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
