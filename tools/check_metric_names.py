"""Metric-name hygiene check: every Prometheus family the project exports
must be ``dynamo_``-prefixed, globally unique across registries, carry
non-empty HELP text, and never reuse a name with a different label set.

The frontend registry (``frontend/metrics.py``) and the per-worker engine
registry (``observability/metrics.py``) federate into one ``/metrics``
document (there is no separate router registry — the router-prefixed family
lives in the frontend's); a name collision between them would produce
duplicate families that Prometheus rejects, an unprefixed name would escape
the project's namespace, and a same-name/different-labels family would make
federated samples unjoinable. Run directly
(``python tools/check_metric_names.py``) or via the test suite
(``tests/test_observability.py``).
"""

from __future__ import annotations

import sys


def collect_families() -> dict[str, list[dict]]:
    """Family descriptors per registry: name, HELP text, label names.

    Importing here keeps the tool usable before optional deps of unrelated
    modules are present.
    """
    from dynamo_tpu.fleetsim.metrics import FleetMetrics
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.observability.metrics import EngineMetrics
    from dynamo_tpu.tuning.metrics import TunerMetrics

    out: dict[str, list[dict]] = {}
    for label, registry in (
        ("frontend", FrontendMetrics().registry),
        ("engine", EngineMetrics(worker="check").registry),
        ("fleet", FleetMetrics().registry),
        ("tuner", TunerMetrics().registry),
    ):
        families: list[dict] = []
        for collector in registry._collector_to_names:  # noqa: SLF001 - no public enumeration API
            labels = tuple(getattr(collector, "_labelnames", ()) or ())
            for metric in collector.collect():
                families.append(
                    {
                        "name": metric.name,
                        "help": (metric.documentation or "").strip(),
                        "labels": labels,
                    }
                )
        out[label] = sorted(families, key=lambda f: f["name"])
    return out


def collect_names() -> dict[str, list[str]]:
    """Family names per registry (the name-only view of collect_families)."""
    return {
        label: [f["name"] for f in families]
        for label, families in collect_families().items()
    }


def check(names: dict[str, list[str]]) -> list[str]:
    """Name-level violations: prefix, cross-registry uniqueness, dupes."""
    problems: list[str] = []
    seen: dict[str, str] = {}
    for label, family_names in names.items():
        for name in family_names:
            if not name.startswith("dynamo_"):
                problems.append(f"{label}: {name!r} is not dynamo_-prefixed")
            prev = seen.get(name)
            if prev is not None and prev != label:
                problems.append(f"{name!r} exported by both {prev} and {label} registries")
            seen.setdefault(name, label)
        if len(set(family_names)) != len(family_names):
            dupes = sorted({n for n in family_names if family_names.count(n) > 1})
            problems.append(f"{label}: duplicate families {dupes}")
    return problems


#: Incident-plane families dashboards and the control tower depend on, and
#: the registry that must export each. A rename or accidental removal fails
#: the gate here rather than as a silently empty tower panel. (These
#: ``_total`` families are Gauges synced from internal counters, so unlike
#: Counter families the suffix stays part of the family name.)
REQUIRED_FAMILIES: dict[str, str] = {
    "dynamo_slo_burn_rate": "frontend",
    "dynamo_alert_active": "frontend",
    "dynamo_alert_fired_total": "frontend",
    "dynamo_federation_scrape_failures_total": "frontend",
    "dynamo_incidents_captured_total": "engine",
    "dynamo_anomaly_active": "engine",
    "dynamo_anomaly_fired_total": "engine",
    # Device-cost plane (roofline ledger) — Counter families are exposed
    # without the _total suffix in python-client exposition.
    "dynamo_engine_roofline_frac": "engine",
    "dynamo_engine_hbm_bytes": "engine",
    "dynamo_engine_flops": "engine",
    # HA control plane (replicated store + frontend reconstruction) — the
    # store_failover / frontend_restart fleetsim gates key on these.
    "dynamo_store_role": "frontend",
    "dynamo_store_epoch": "frontend",
    "dynamo_store_replication_lag_seconds": "frontend",
    "dynamo_store_failovers_total": "frontend",
    "dynamo_store_client_op_retries_total": "frontend",
    "dynamo_router_index_resyncs_total": "frontend",
}


def check_required(families: dict[str, list[dict]]) -> list[str]:
    problems: list[str] = []
    for name, registry in REQUIRED_FAMILIES.items():
        present = {f["name"] for f in families.get(registry, [])}
        if name not in present:
            problems.append(
                f"required family {name!r} missing from the {registry} "
                "registry (renamed? the control tower and dashboards key on it)"
            )
    return problems


def check_families(families: dict[str, list[dict]]) -> list[str]:
    """All violations: the name checks plus non-empty HELP, consistent
    label sets for any name seen more than once across registries, and
    required-presence of the incident-plane families."""
    problems = check(
        {label: [f["name"] for f in fams] for label, fams in families.items()}
    )
    problems += check_required(families)
    label_sets: dict[str, tuple[str, tuple]] = {}
    for label, fams in families.items():
        for f in fams:
            if not f["help"]:
                problems.append(f"{label}: {f['name']!r} has empty HELP text")
            prev = label_sets.get(f["name"])
            if prev is not None and prev[1] != f["labels"]:
                problems.append(
                    f"{f['name']!r} registered with conflicting label sets: "
                    f"{prev[1]} ({prev[0]}) vs {f['labels']} ({label})"
                )
            label_sets.setdefault(f["name"], (label, f["labels"]))
    return problems


def main() -> int:
    families = collect_families()
    problems = check_families(families)
    total = sum(len(v) for v in families.values())
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print(
        f"ok: {total} metric families across {len(families)} registries — "
        "dynamo_-prefixed, unique, HELP'd, label-consistent"
    )
    return 0


if __name__ == "__main__":
    import pathlib

    # Direct CLI use from a checkout: make the repo importable.
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
