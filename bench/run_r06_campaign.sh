#!/bin/bash
# Round-6 campaign: wire v3 (striped multi-stream KV transfer) vs the r05
# agg-vs-disagg baseline. Sequential: the chip fits one engine config at a
# time. Phases 1-2 run anywhere (loopback TCP + CPU mocker fleet); phase 3
# needs a chip.
set -x
cd "$(dirname "$0")/.."
mkdir -p bench/results
export DYNAMO_MOE_DISPATCH=  # not MoE configs; keep defaults

# 1. Loopback KV-wire sweep: streams x chunk grid over two real OS
#    processes, real TCP. Headline keys kv_wire_gbps / speedup_vs_v2 are
#    the acceptance numbers for the striped wire.
timeout 3600 env JAX_PLATFORMS=cpu \
  BENCH_WIRE_STREAMS="${BENCH_WIRE_STREAMS:-0,1,2,4,8}" \
  BENCH_WIRE_CHUNK="${BENCH_WIRE_CHUNK:-0}" \
  BENCH_WIRE_PAGES="${BENCH_WIRE_PAGES:-8}" \
  BENCH_WIRE_ITERS="${BENCH_WIRE_ITERS:-4}" \
  python - <<'EOF' \
  > bench/results/kv_wire_sweep_r06.json \
  2> bench/results/kv_wire_sweep_r06.log
import json
import bench
print(json.dumps(bench.probe_cross_process_wire(), indent=1))
EOF

# 2. Mocker-fleet agg vs disagg (multi-worker shape, CPU platform), wire v3
#    on the decode<-prefill ship path.
timeout 1800 python - <<'EOF' \
  > bench/results/pareto_agg_vs_disagg_mock_r06.json \
  2> bench/results/pareto_agg_vs_disagg_mock_r06.log
import jax
jax.config.update("jax_platforms", "cpu")
from dynamo_tpu.bench.__main__ import main
main([
    "--model", "test-tiny", "--mock", "--topologies", "agg,disagg",
    "--levels", "1,8,32", "--num-requests", "64", "--workers", "2",
    "--prefill-workers", "2", "--disagg-threshold", "64",
    "--shared-prefix", "64", "--group-prefix", "64", "--unique-len", "64",
    "--osl", "48", "--num-pages", "4096", "--max-batch-size", "32",
])
EOF

# 3. Agg vs disagg on the 1B, same chip, real dual-engine path with the
#    striped host fallback engaged (chip-only; skipped when no TPU).
if python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
  timeout 5400 python -m dynamo_tpu.bench \
    --model llama-3.2-1b --topologies agg,disagg \
    --levels 1,8,32 --num-requests 64 --workers 1 --prefill-workers 1 \
    --disagg-threshold 256 \
    --shared-prefix 512 --groups 4 --group-prefix 384 --unique-len 256 --osl 150 \
    --num-pages 512 --max-batch-size 32 --page-size 128 --max-seq-len 1536 \
    --max-prefill-tokens 4096 --decode-steps 8 \
    > bench/results/pareto_agg_vs_disagg_1b_r06.json \
    2> bench/results/pareto_agg_vs_disagg_1b_r06.log
else
  echo "no TPU: skipping phase 3 (see bench/results/R06_NOTES.md)"
fi

# A killed/failed phase leaves an empty or unparseable artifact: rename it
# .failed so nothing downstream mistakes a dead run for a result.
for f in bench/results/kv_wire_sweep_r06.json bench/results/pareto_*_r06.json; do
  [ -e "$f" ] || continue
  python -c "import json,sys; json.load(open(sys.argv[1]))" "$f" 2>/dev/null \
    || { mv "$f" "$f.failed"; echo "FAILED ARTIFACT: $f"; }
done
echo CAMPAIGN-DONE
