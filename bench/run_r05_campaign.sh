#!/bin/bash
# Round-5 on-chip pareto campaign (VERDICT r4 items 1 and 5).
# Sequential: the chip fits one engine config at a time.
set -x
cd "$(dirname "$0")/.."
mkdir -p bench/results
export DYNAMO_MOE_DISPATCH=  # not MoE configs; keep defaults

# 1. 8B int8 @ ISL 3000 / OSL 150, agg, conc 1..12 (HBM-bound ceiling:
#    8.1 GB weights + 0.42 GB KV/seq).
timeout 5400 python -m dynamo_tpu.bench \
  --model llama-3-8b --quantize int8 --topologies agg \
  --levels 1,4,8,12 --num-requests 24 \
  --shared-prefix 1024 --groups 4 --group-prefix 1024 --unique-len 952 --osl 150 \
  --num-pages 336 --max-batch-size 12 --page-size 128 --max-seq-len 3328 \
  --max-prefill-tokens 4096 --decode-steps 8 \
  > bench/results/pareto_isl3000_8b_int8_r05.json \
  2> bench/results/pareto_isl3000_8b_int8_r05.log

# 2. MLA-8B proxy int8 @ ISL 3000 / OSL 150, agg, conc 1..32 (latent cache
#    is 3.2x smaller per token).
timeout 5400 python -m dynamo_tpu.bench \
  --model mla-8b-proxy --quantize int8 --topologies agg \
  --levels 1,8,16,32 --num-requests 64 \
  --shared-prefix 1024 --groups 4 --group-prefix 1024 --unique-len 952 --osl 150 \
  --num-pages 848 --max-batch-size 32 --page-size 128 --max-seq-len 3328 \
  --max-prefill-tokens 4096 --decode-steps 8 \
  > bench/results/pareto_isl3000_mla_r05.json \
  2> bench/results/pareto_isl3000_mla_r05.log

# 3. Agg vs disagg on the 1B, same chip, real dual-engine device path.
timeout 5400 python -m dynamo_tpu.bench \
  --model llama-3.2-1b --topologies agg,disagg \
  --levels 1,8,32 --num-requests 64 --workers 1 --prefill-workers 1 \
  --disagg-threshold 256 \
  --shared-prefix 512 --groups 4 --group-prefix 384 --unique-len 256 --osl 150 \
  --num-pages 512 --max-batch-size 32 --page-size 128 --max-seq-len 1536 \
  --max-prefill-tokens 4096 --decode-steps 8 \
  > bench/results/pareto_agg_vs_disagg_1b_r05.json \
  2> bench/results/pareto_agg_vs_disagg_1b_r05.log

# 4. Mocker-fleet agg vs disagg (multi-worker shape, CPU platform).
timeout 1800 python - <<'EOF' \
  > bench/results/pareto_agg_vs_disagg_mock_r05.json \
  2> bench/results/pareto_agg_vs_disagg_mock_r05.log
import jax
jax.config.update("jax_platforms", "cpu")
from dynamo_tpu.bench.__main__ import main
main([
    "--model", "test-tiny", "--mock", "--topologies", "agg,disagg",
    "--levels", "1,8,32", "--num-requests", "64", "--workers", "2",
    "--prefill-workers", "2", "--disagg-threshold", "64",
    "--shared-prefix", "64", "--group-prefix", "64", "--unique-len", "64",
    "--osl", "48", "--num-pages", "4096", "--max-batch-size", "32",
])
EOF
# A killed/failed phase leaves an empty or unparseable artifact: rename it
# .failed so nothing downstream mistakes a dead run for a result.
for f in bench/results/pareto_*_r05.json; do
  python -c "import json,sys; json.load(open(sys.argv[1]))" "$f" 2>/dev/null \
    || { mv "$f" "$f.failed"; echo "FAILED ARTIFACT: $f"; }
done
echo CAMPAIGN-DONE
