"""Planner actuation: decisions drive a real fleet of worker processes.

The integration test mirrors the reference's planner-vs-circus setup
(`local_connector.py` against mocker fleets): a store server + metrics
aggregator in-process, mock-engine workers as real OS processes, and the
planner loop scaling the fleet as measured load ramps up and down.
"""

import asyncio
import socket

import numpy as np
import pytest

from dynamo_tpu.planner.connector import LocalProcessConnector, PlannerLoop
from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.router.metrics import KvMetricsAggregator
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.store_server import StoreServer
from dynamo_tpu.runtime.tcp import TcpTransport


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_worker_profile_json_roundtrip(tmp_path):
    p = WorkerProfile(prefill_tokens_per_sec=123.0, decode_tokens_per_sec=45.0,
                      max_concurrent=16, ttft_curve=[(0.0, 0.1), (1.0, 0.4)],
                      itl_curve=[(0.0, 0.01), (1.0, 0.02)])
    q = WorkerProfile.from_json(p.to_json())
    assert q == p
    assert q.ttft_at(0.5) == pytest.approx(0.25)


async def test_profiler_sweep_on_mocker():
    """profile_service produces monotone curves and sane capacities."""
    from dynamo_tpu.mocker import build_mock_service
    from dynamo_tpu.profiler import profile_service

    service = await build_mock_service()
    try:
        profile, levels = await profile_service(service, levels=[1, 4], isl=64, osl=16)
    finally:
        await service.close()
    assert len(levels) == 2
    assert profile.decode_tokens_per_sec > 0
    assert profile.prefill_tokens_per_sec > 0
    assert profile.max_concurrent == 4
    assert [x for x, _ in profile.ttft_curve] == [0.25, 1.0]


@pytest.mark.slow
@pytest.mark.e2e
async def test_planner_scales_live_fleet():
    """Load ramp on a mock-engine fleet: the planner loop spawns real worker
    processes on load and shrinks the fleet when load drains."""
    port = _free_port()
    server = await StoreServer(host="127.0.0.1", port=port).start()
    runtime = DistributedRuntime(server.store, TcpTransport(host="127.0.0.1"))
    aggregator = await KvMetricsAggregator(runtime, "dynamo", "backend").start()
    connector = LocalProcessConnector(
        model="test-tiny", store_url=f"tcp://127.0.0.1:{port}", mock=True,
        spawn_timeout=120.0,
    )
    planner = Planner(
        PlannerConfig(min_workers=1, max_workers=3, target_utilization=0.7),
        # Capacity far below the mocker's real throughput: measured load
        # forces a scale-up decision deterministically.
        WorkerProfile(prefill_tokens_per_sec=100000.0, decode_tokens_per_sec=60.0),
    )
    loop = PlannerLoop(planner, aggregator, connector)
    try:
        # Idle tick: fleet comes up at min_workers.
        await loop.tick()
        assert connector.live_counts() == (1, 0)

        # Drive real load through the fleet's endpoint.
        client = runtime.namespace("dynamo").component("backend").endpoint("generate").client()
        rng = np.random.default_rng(0)

        async def one(i: int) -> None:
            req = PreprocessedRequest(
                token_ids=[int(t) for t in rng.integers(5, 250, 64)],
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=120, ignore_eos=True),
                request_id=f"load-{i}",
            )
            async for _ in client.generate(req.to_dict(), Context()):
                pass

        await asyncio.gather(*(one(i) for i in range(8)))
        await asyncio.sleep(1.5)  # let the workers publish their counters

        decision = await loop.tick()
        assert decision.decode_workers > 1, decision
        assert connector.live_counts()[0] == decision.decode_workers

        # Load drains: fleet shrinks back to min_workers within a few ticks.
        for _ in range(6):
            await asyncio.sleep(0.5)
            decision = await loop.tick()
            if connector.live_counts() == (1, 0):
                break
        assert connector.live_counts() == (1, 0)
        assert connector.scale_events >= 2  # at least one up + one down
    finally:
        await loop.close()
        await aggregator.close()
        await runtime.close()
        await server.close()
