"""Planner actuation: decisions drive a real fleet of worker processes.

The integration test mirrors the reference's planner-vs-circus setup
(`local_connector.py` against mocker fleets): a store server + metrics
aggregator in-process, mock-engine workers as real OS processes, and the
planner loop scaling the fleet as measured load ramps up and down.
"""

import asyncio
import socket

import numpy as np
import pytest

from dynamo_tpu.planner.connector import LocalProcessConnector, PlannerLoop
from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.router.metrics import KvMetricsAggregator
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.store_server import StoreServer
from dynamo_tpu.runtime.tcp import TcpTransport


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_seasonal_predictor_beats_linear_on_periodic_load():
    """A repeating peak (the auto-scaling case): at the trough right before
    the next peak, the linear fit extrapolates the downslope while the
    seasonal model predicts the peak (VERDICT r4 missing #5)."""
    from dynamo_tpu.planner.predictor import LinearTrendPredictor, SeasonalPredictor

    period, peak, trough = 8, 1000.0, 100.0
    # Peaks at i % 8 == 0 (i = 0, 8, .., 32): the next index (40) is a peak.
    wave = [peak if i % period == 0 else trough for i in range(40)]
    lin, sea = LinearTrendPredictor(), SeasonalPredictor()
    for v in wave:
        lin.observe(v)
        sea.observe(v)
    assert len(wave) % period == 0  # next step is a peak
    lin_pred, sea_pred = lin.predict(), sea.predict()
    assert lin_pred < peak / 2, f"linear should miss the peak, got {lin_pred}"
    assert sea_pred == pytest.approx(peak, rel=0.05), sea_pred
    assert sea.last_period == period

    # Aperiodic ramp: the seasonal model must degrade to the default-window
    # linear fit exactly (same recent-ramp sensitivity).
    lin2, sea2 = LinearTrendPredictor(), SeasonalPredictor()
    for i in range(20):
        lin2.observe(10.0 * i)
        sea2.observe(10.0 * i)
    assert sea2.predict() == pytest.approx(lin2.predict())
    assert sea2.last_period is None


def test_make_predictor_selection():
    from dynamo_tpu.planner.predictor import (
        PREDICTORS,
        SeasonalPredictor,
        make_predictor,
    )

    assert isinstance(make_predictor("seasonal"), SeasonalPredictor)
    assert set(PREDICTORS) == {"constant", "moving_average", "linear", "seasonal"}
    with pytest.raises(ValueError, match="unknown predictor"):
        make_predictor("prophet")


def test_predictor_observe_predict_roundtrip():
    """Every registered predictor converges on a steady-state stream: after a
    constant-rate window, predict() returns that rate (the load model must
    not distort the easy case, whatever its shape machinery)."""
    from dynamo_tpu.planner.predictor import PREDICTORS, make_predictor

    for name in PREDICTORS:
        p = make_predictor(name)
        assert p.predict() == 0.0, f"{name}: cold predictor must predict 0"
        for _ in range(16):
            p.observe(100.0)
        assert p.predict() == pytest.approx(100.0), name


def test_planner_slo_percentile_changes_decision():
    """The SLA mode's slo_percentile knob (ISSUE 4): with divergent
    median/p99 ITL surfaces, sizing against p99 buys more workers than
    sizing against the median, and an absent tail curve falls back to the
    median curve unchanged."""
    from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile
    from dynamo_tpu.protocols.kv import ForwardPassMetrics

    # Median ITL stays comfortably under the SLO at any load; p99 blows
    # through it past 30% load (the saturation knee medians hide).
    profile = WorkerProfile(
        decode_tokens_per_sec=100.0, prefill_tokens_per_sec=1e9,
        itl_curve=[(0.0, 0.01), (1.0, 0.02)],
        itl_p99_curve=[(0.0, 0.01), (0.3, 0.02), (1.0, 1.0)],
    )

    def decide(pct, prof=profile):
        cfg = PlannerConfig(mode="sla", predictor="constant", slo_percentile=pct,
                            itl_slo_seconds=0.05, min_workers=1, max_workers=8)
        planner = Planner(cfg, prof)
        planner.observe({1: ForwardPassMetrics(worker_id=1, generated_tokens_total=300)}, 1.0)
        return planner.decide(disaggregated=False)

    median = decide(50)
    tail = decide(99)
    assert median.decode_workers == 3, median  # 300 tok/s / 100 per worker
    assert tail.decode_workers > median.decode_workers, (tail, median)
    # No profiled p99 curve: pct=99 degrades to the median sizing.
    flat = WorkerProfile(decode_tokens_per_sec=100.0, prefill_tokens_per_sec=1e9,
                         itl_curve=[(0.0, 0.01), (1.0, 0.02)])
    assert decide(99, flat).decode_workers == median.decode_workers


def test_planner_scales_up_ahead_of_repeating_peak():
    """Planner with predictor='seasonal' raises the decode fleet one tick
    BEFORE the recurring peak; 'linear' at the same trough does not."""
    from dynamo_tpu.planner.core import Planner, PlannerConfig, WorkerProfile
    from dynamo_tpu.protocols.kv import ForwardPassMetrics

    profile = WorkerProfile(decode_tokens_per_sec=100.0, prefill_tokens_per_sec=1e9)
    period, peak_tps, trough_tps = 6, 500.0, 20.0

    def drive(planner):
        total = 0
        for i in range(30):  # peaks at i % 6 == 0; the NEXT tick (30) is one
            tps = peak_tps if i % period == 0 else trough_tps
            total += int(tps)  # cumulative counter, dt=1s
            planner.observe({1: ForwardPassMetrics(worker_id=1, generated_tokens_total=total)}, 1.0)
        return planner.decide(disaggregated=False)

    cfg = dict(min_workers=1, max_workers=8, target_utilization=0.7)
    seasonal = drive(Planner(PlannerConfig(predictor="seasonal", **cfg), profile))
    linear = drive(Planner(PlannerConfig(predictor="linear", **cfg), profile))
    # 500 tok/s @ 70 tok/s effective per worker -> 8 workers needed at peak.
    assert seasonal.decode_workers == 8, seasonal
    assert linear.decode_workers <= 2, linear


def test_worker_profile_json_roundtrip(tmp_path):
    p = WorkerProfile(prefill_tokens_per_sec=123.0, decode_tokens_per_sec=45.0,
                      max_concurrent=16, ttft_curve=[(0.0, 0.1), (1.0, 0.4)],
                      itl_curve=[(0.0, 0.01), (1.0, 0.02)])
    q = WorkerProfile.from_json(p.to_json())
    assert q == p
    assert q.ttft_at(0.5) == pytest.approx(0.25)


async def test_profiler_sweep_on_mocker():
    """profile_service produces monotone curves and sane capacities."""
    from dynamo_tpu.mocker import build_mock_service
    from dynamo_tpu.profiler import profile_service

    service = await build_mock_service()
    try:
        profile, levels = await profile_service(service, levels=[1, 4], isl=64, osl=16)
    finally:
        await service.close()
    assert len(levels) == 2
    assert profile.decode_tokens_per_sec > 0
    assert profile.prefill_tokens_per_sec > 0
    assert profile.max_concurrent == 4
    assert [x for x, _ in profile.ttft_curve] == [0.25, 1.0]


@pytest.mark.slow
@pytest.mark.e2e
async def test_planner_scales_live_fleet():
    """Load ramp on a mock-engine fleet: the planner loop spawns real worker
    processes on load and shrinks the fleet when load drains."""
    port = _free_port()
    server = await StoreServer(host="127.0.0.1", port=port).start()
    runtime = DistributedRuntime(server.store, TcpTransport(host="127.0.0.1"))
    aggregator = await KvMetricsAggregator(runtime, "dynamo", "backend").start()
    connector = LocalProcessConnector(
        model="test-tiny", store_url=f"tcp://127.0.0.1:{port}", mock=True,
        spawn_timeout=120.0,
    )
    planner = Planner(
        PlannerConfig(min_workers=1, max_workers=3, target_utilization=0.7),
        # Capacity far below the mocker's real throughput: measured load
        # forces a scale-up decision deterministically.
        WorkerProfile(prefill_tokens_per_sec=100000.0, decode_tokens_per_sec=60.0),
    )
    loop = PlannerLoop(planner, aggregator, connector)
    try:
        # Idle tick: fleet comes up at min_workers.
        await loop.tick()
        assert connector.live_counts() == (1, 0)

        # Drive real load through the fleet's endpoint.
        client = runtime.namespace("dynamo").component("backend").endpoint("generate").client()
        rng = np.random.default_rng(0)

        async def one(i: int) -> None:
            req = PreprocessedRequest(
                token_ids=[int(t) for t in rng.integers(5, 250, 64)],
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=120, ignore_eos=True),
                request_id=f"load-{i}",
            )
            async for _ in client.generate(req.to_dict(), Context()):
                pass

        await asyncio.gather(*(one(i) for i in range(8)))
        await asyncio.sleep(1.5)  # let the workers publish their counters

        decision = await loop.tick()
        assert decision.decode_workers > 1, decision
        assert connector.live_counts()[0] == decision.decode_workers

        # Load drains: fleet shrinks back to min_workers within a few ticks.
        for _ in range(6):
            await asyncio.sleep(0.5)
            decision = await loop.tick()
            if connector.live_counts() == (1, 0):
                break
        assert connector.live_counts() == (1, 0)
        assert connector.scale_events >= 2  # at least one up + one down
    finally:
        await loop.close()
        await aggregator.close()
        await runtime.close()
        await server.close()
