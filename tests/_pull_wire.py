"""Socket-backed stand-in for the PJRT transfer engine.

Used by ``tests/test_pull_two_process.py``: the CPU backend doesn't
implement ``jax.experimental.transfer``, so this provides the same
offer/pull/finish contract as ``JaxPullTransport`` with the bytes carried
over a real TCP socket — offers staged in one OS process are genuinely
pulled by another. The production wire differs only in moving device
buffers over ICI/DCN instead of host copies over loopback.

Framing (little-endian): request = uuid:i64. Response = count:i64 (−1 when
the offer is unknown), then per array: ndim:i64, dims:i64*, dtype-name
length:i64 + utf8, payload length:i64 + raw bytes. Raw-bytes framing
because numpy's save formats can't represent bfloat16.
"""

from __future__ import annotations

import itertools
import socket
import socketserver
import struct
import threading

import numpy as np


def _send_arrays(sock, arrays) -> None:
    sock.sendall(struct.pack("<q", len(arrays)))
    for a in arrays:
        a = np.asarray(a)
        name = a.dtype.name.encode()
        payload = np.ascontiguousarray(a).tobytes()
        sock.sendall(struct.pack(f"<q{a.ndim}q", a.ndim, *a.shape))
        sock.sendall(struct.pack("<q", len(name)) + name)
        sock.sendall(struct.pack("<q", len(payload)))
        sock.sendall(payload)


def _recv_arrays(raw) -> list[np.ndarray] | None:
    (count,) = struct.unpack("<q", raw.read(8))
    if count < 0:
        return None
    out = []
    for _ in range(count):
        (ndim,) = struct.unpack("<q", raw.read(8))
        shape = struct.unpack(f"<{ndim}q", raw.read(8 * ndim))
        (nlen,) = struct.unpack("<q", raw.read(8))
        dtype = np.dtype(raw.read(nlen).decode())  # ml_dtypes registers bf16
        (plen,) = struct.unpack("<q", raw.read(8))
        out.append(np.frombuffer(raw.read(plen), dtype=dtype).reshape(shape))
    return out


class SocketWireTransport:
    def __init__(self) -> None:
        self.offers: dict[int, list] = {}
        self._lock = threading.Lock()
        self._server: socketserver.ThreadingTCPServer | None = None
        self._uuids = itertools.count(1)
        self.offered = 0
        self.served = 0  # pulls answered by this side's socket server
        self.pulled = 0  # pulls performed by this side
        self.drained = 0

    def _ensure_server(self) -> socketserver.ThreadingTCPServer:
        if self._server is None:
            transport = self

            class Handler(socketserver.BaseRequestHandler):
                def handle(self) -> None:
                    raw = self.request.makefile("rb")
                    (uuid,) = struct.unpack("<q", raw.read(8))
                    with transport._lock:
                        arrays = transport.offers.get(uuid)
                    if arrays is None:
                        self.request.sendall(struct.pack("<q", -1))
                        return
                    _send_arrays(self.request, arrays)
                    transport.served += 1

            self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
            self._server.daemon_threads = True
            threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self._server

    def address(self) -> str:
        host, port = self._ensure_server().server_address
        return f"{host}:{port}"

    def new_uuid(self) -> int:
        return next(self._uuids)

    def offer(self, uuid: int, arrays) -> None:
        self._ensure_server()
        with self._lock:
            self.offers[uuid] = list(arrays)
        self.offered += 1

    def finish_offer(self, uuid: int, consumed: bool = True) -> None:
        with self._lock:
            popped = self.offers.pop(uuid, None)
        if popped is not None and not consumed:
            self.drained += 1

    def pull(self, address: str, uuid: int, specs) -> list:
        import jax

        host, port = address.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=30) as sock:
            sock.sendall(struct.pack("<q", uuid))
            arrays = _recv_arrays(sock.makefile("rb"))
        if arrays is None:
            raise KeyError(f"no offer {uuid} at {address}")
        out = [
            jax.device_put(a.astype(spec.dtype), spec.sharding)
            for a, spec in zip(arrays, specs)
        ]
        self.pulled += 1
        return out

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
