"""Tests for the component model + client: registration, watch, routing, failover."""

import asyncio
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu.runtime.client import NoInstancesError
from dynamo_tpu.runtime.component import DistributedRuntime, Instance, instance_key
from dynamo_tpu.runtime.discovery import MemoryStore
from dynamo_tpu.runtime.engine import AsyncEngine, Context, collect
from dynamo_tpu.runtime.tcp import TcpTransport


class TaggedEngine(AsyncEngine[Any, Any]):
    def __init__(self, tag: str) -> None:
        self.tag = tag
        self.calls = 0

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        self.calls += 1
        yield {"tag": self.tag, "echo": request}


async def test_instance_record_roundtrip():
    inst = Instance("ns", "comp", "ep", 0xAB, "tcp://1.2.3.4:5/s", {"m": 1})
    assert Instance.from_bytes(inst.to_bytes()) == inst
    assert inst.key == "instances/ns/comp/ep:ab"
    assert inst.subject == "ns.comp.ep-ab"
    assert instance_key("ns", "comp", "ep", 0xAB) == inst.key


async def test_invalid_names_rejected():
    rt = DistributedRuntime.detached()
    with pytest.raises(ValueError):
        rt.namespace("bad/name")
    with pytest.raises(ValueError):
        rt.namespace("ok").component("no dots.")
    await rt.close()


async def test_serve_and_call_via_client():
    rt = DistributedRuntime.detached()
    ep = rt.namespace("dynamo").component("backend").endpoint("generate")
    await ep.serve(TaggedEngine("w1"))
    client = ep.client()
    await client.wait_for_instances(count=1, timeout=5)
    items = await collect(client.generate({"x": 1}))
    assert items == [{"tag": "w1", "echo": {"x": 1}}]
    await rt.close()


async def test_round_robin_spreads_load():
    # Two worker runtimes sharing one store/transport pair (same process).
    store = MemoryStore()
    rt1 = DistributedRuntime(store)
    rt2 = DistributedRuntime(store, rt1.transport)
    e1, e2 = TaggedEngine("w1"), TaggedEngine("w2")
    await rt1.namespace("ns").component("c").endpoint("e").serve(e1)
    await rt2.namespace("ns").component("c").endpoint("e").serve(e2)
    client = rt1.namespace("ns").component("c").endpoint("e").client()
    await client.wait_for_instances(count=2, timeout=5)
    for _ in range(10):
        await collect(client.generate({}))
    assert e1.calls == 5 and e2.calls == 5
    await rt1.close()
    await rt2.close()


async def test_direct_routing():
    store = MemoryStore()
    rt1 = DistributedRuntime(store)
    rt2 = DistributedRuntime(store, rt1.transport)
    e1, e2 = TaggedEngine("w1"), TaggedEngine("w2")
    i1 = await rt1.namespace("ns").component("c").endpoint("e").serve(e1)
    await rt2.namespace("ns").component("c").endpoint("e").serve(e2)
    client = rt1.namespace("ns").component("c").endpoint("e").client(router_mode="direct")
    await client.wait_for_instances(count=2, timeout=5)
    for _ in range(4):
        await collect(client.generate({}, instance_id=i1.instance_id))
    assert e1.calls == 4 and e2.calls == 0
    await rt1.close()
    await rt2.close()


async def test_lease_expiry_removes_instance_from_client():
    store = MemoryStore(reap_interval=0.05)
    rt_worker = DistributedRuntime(store, lease_ttl=0.15)
    rt_client = DistributedRuntime(store, rt_worker.transport)
    ep = rt_worker.namespace("ns").component("c").endpoint("e")
    await ep.serve(TaggedEngine("w"))
    client = rt_client.namespace("ns").component("c").endpoint("e").client()
    await client.wait_for_instances(count=1, timeout=5)
    # Kill the worker's keep-alive: simulate process death.
    rt_worker._keepalive_task.cancel()
    # Expiry + reap + watch delivery are wall-clock paths: poll instead of a
    # fixed sleep so suite-load scheduling jitter can't flake this.
    from conftest import wait_for

    assert await wait_for(lambda: client.instances() == [], timeout=10)
    with pytest.raises(NoInstancesError):
        await collect(client.generate({}))
    await rt_worker.close()
    await rt_client.close()


async def test_failover_inhibits_dead_instance_tcp():
    """A stale discovery record (worker gone, record not yet expired) is routed around."""
    store = MemoryStore()
    transport = TcpTransport()
    rt = DistributedRuntime(store, transport)
    ep = rt.namespace("ns").component("c").endpoint("e")
    good = TaggedEngine("good")
    inst_good = await ep.serve(good)
    # Forge a second instance record pointing at a dead port.
    lease = await store.create_lease(10)
    dead = Instance("ns", "c", "e", lease.id, "tcp://127.0.0.1:1/ns.c.e-dead")
    await store.put(dead.key, dead.to_bytes(), lease_id=lease.id)
    client = ep.client(router_mode="random")
    await client.wait_for_instances(count=2, timeout=5)
    for _ in range(8):
        items = await collect(client.generate({}))
        assert items[0]["tag"] == "good"
    assert good.calls == 8
    assert inst_good.instance_id not in client._inhibited
    await rt.close()


async def test_context_kill_propagates_to_children():
    from dynamo_tpu.runtime.engine import Context

    p = Context()
    c = p.child()
    p.kill()
    assert c.is_killed and c.is_stopped
    # Children created after the fact inherit the state too.
    c2 = p.child()
    assert c2.is_killed


async def test_put_if_absent_concurrent_single_winner():
    store = MemoryStore()

    async def racer(val):
        return await store.put_if_absent("k", val)

    results = await asyncio.gather(*[racer(f"v{i}".encode()) for i in range(10)])
    assert sum(results) == 1
    winner = await store.get("k")
    assert winner == f"v{results.index(True)}".encode()


async def test_first_generate_after_start_sees_existing_instances():
    rt = DistributedRuntime.detached()
    ep = rt.namespace("ns").component("c").endpoint("e")
    await ep.serve(TaggedEngine("w"))
    client = ep.client()
    # No wait_for_instances: the synchronous seed in start() must suffice.
    items = await collect(client.generate({}))
    assert items[0]["tag"] == "w"
    await rt.close()
