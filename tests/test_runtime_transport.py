"""Tests for the stream transports (in-memory and TCP): streaming, errors, cancel."""

import asyncio
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineError, collect
from dynamo_tpu.runtime.tcp import TcpTransport
from dynamo_tpu.runtime.transport import InMemoryTransport, NoSuchSubjectError


class CountingEngine(AsyncEngine[Any, Any]):
    """Streams {'i': k} for k < n; honors stop/kill; records how far it got."""

    def __init__(self, delay: float = 0.0) -> None:
        self.delay = delay
        self.emitted = 0
        self.saw_stop = False

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        n = request["n"]
        for k in range(n):
            if context.is_stopped:
                self.saw_stop = True
                return
            if self.delay:
                await asyncio.sleep(self.delay)
            self.emitted += 1
            yield {"i": k}


class FailingEngine(AsyncEngine[Any, Any]):
    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        yield {"i": 0}
        raise ValueError("engine exploded")


async def _transports():
    mem = InMemoryTransport()
    tcp = TcpTransport()
    return [mem, tcp]


async def test_stream_roundtrip_both_transports():
    for transport in await _transports():
        engine = CountingEngine()
        await transport.register_engine("ns.comp.ep-1", engine)
        addr = transport.address_of("ns.comp.ep-1")
        items = await collect(transport.generate(addr, {"n": 5}, Context()))
        assert items == [{"i": k} for k in range(5)]
        await transport.close()


async def test_unknown_subject_raises():
    for transport in await _transports():
        await transport.register_engine("known", CountingEngine())
        base = transport.address_of("known")
        bad = base.replace("known", "missing")
        with pytest.raises(NoSuchSubjectError):
            await collect(transport.generate(bad, {"n": 1}, Context()))
        await transport.close()


async def test_engine_error_propagates():
    for transport in await _transports():
        await transport.register_engine("f", FailingEngine())
        addr = transport.address_of("f")
        items = []
        with pytest.raises(EngineError):
            async for item in transport.generate(addr, {}, Context()):
                items.append(item)
        assert items == [{"i": 0}]
        await transport.close()


async def test_stop_generating_crosses_transport():
    for transport in await _transports():
        engine = CountingEngine(delay=0.02)
        await transport.register_engine("s", engine)
        addr = transport.address_of("s")
        ctx = Context()
        items = []
        async for item in transport.generate(addr, {"n": 1000}, ctx):
            items.append(item)
            if len(items) == 3:
                ctx.stop_generating()
        # Engine must have stopped long before 1000 items.
        assert 3 <= engine.emitted < 100
        await transport.close()


async def test_caller_abandons_stream_kills_engine():
    for transport in await _transports():
        engine = CountingEngine(delay=0.02)
        await transport.register_engine("a", engine)
        addr = transport.address_of("a")
        stream = transport.generate(addr, {"n": 1000}, Context())
        got = 0
        async for _ in stream:
            got += 1
            if got == 2:
                break  # abandon: generator close should kill remote
        await stream.aclose()
        await asyncio.sleep(0.2)
        emitted_after = engine.emitted
        await asyncio.sleep(0.2)
        assert engine.emitted == emitted_after, "engine kept running after caller left"
        await transport.close()


async def test_binary_payloads_roundtrip():
    class EchoEngine(AsyncEngine[Any, Any]):
        async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
            yield request

    for transport in await _transports():
        await transport.register_engine("b", EchoEngine())
        addr = transport.address_of("b")
        payload = {"blob": b"\x00\x01\xff" * 100, "ids": [1, 2, 3], "nested": {"x": 1.5}}
        items = await collect(transport.generate(addr, payload, Context()))
        assert items == [payload]
        await transport.close()


async def test_concurrent_streams_tcp():
    transport = TcpTransport()
    engine = CountingEngine(delay=0.001)
    await transport.register_engine("c", engine)
    addr = transport.address_of("c")

    async def one(n):
        return await collect(transport.generate(addr, {"n": n}, Context()))

    results = await asyncio.gather(*[one(10) for _ in range(20)])
    assert all(r == [{"i": k} for k in range(10)] for r in results)
    await transport.close()
